"""Elastic-training convergence experiment (reference parity:
docs/benchmark/report_cn.md:106-117 / data/3-1.csv — the reference's
flagship claim that training quality is unaffected by worker-membership
churn).

Trains the SAME DeepFM CTR job three ways against live PS + master over
gRPC, with workers as real OS processes on the CPU backend:

- fixed-2:  two workers, start to finish
- fixed-4:  four workers, start to finish
- elastic:  start with two, ADD two more at ~1/3 task progress, then
            SIGKILL one at ~2/3 progress (its in-flight tasks are
            recovered by the master's liveness monitor)

Each run records the periodic-eval curve (model_version -> AUC /
accuracy from the master's EvaluationService) and a FINAL eval over the
held-out set at the end-of-job PS state. The experiment asserts the
final metrics agree within tolerance and writes:

- docs/data/elastic_convergence.csv   (the three curves, long format)
- stdout: a JSON summary line

Run: python scripts/convergence_elastic.py [--records 6144]
(~3-6 min on 8 CPUs; set --records 1024 for a quick smoke run.)
"""

import argparse
import csv
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU experiment (workers/PS/eval are all host processes); force it
# before any jax import so the tunneled TPU is never touched
os.environ["JAX_PLATFORMS"] = "cpu"


def _wait_port(port, timeout=90):
    import socket

    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port))
            return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError("port %d never came up" % port)


def _spawn_ps(ps_id, num_ps, port, lr):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.ps.server",
         "--ps_id", str(ps_id), "--num_ps_pods", str(num_ps),
         "--port", str(port),
         "--opt_type", "adam", "--opt_args", "lr=%g" % lr],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _spawn_worker(idx, master_port, ps_addrs, train_dir, log_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    log = open(log_path, "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.worker.main",
         "--master_addr", "localhost:%d" % master_port,
         "--worker_id", str(idx),
         "--model_zoo", "elasticdl_tpu.models.deepfm",
         "--training_data", train_dir,
         "--ps_addrs", ps_addrs,
         "--minibatch_size", "64",
         "--report_version_steps", "2"],
        env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
    )


def _final_eval(ps_addrs, valid_dir):
    """Score the END-OF-JOB PS state over the held-out set with a local
    SparseTrainer eval loop (same pull path the workers use)."""
    from elasticdl_tpu.data.pipeline import Dataset
    from elasticdl_tpu.data.readers import RecordIODataReader
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.train.sparse import SparseTrainer
    from elasticdl_tpu.worker.ps_client import PSClient
    from elasticdl_tpu.common.constants import Mode

    import numpy as np

    reader = RecordIODataReader(data_dir=valid_dir)
    trainer = SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(batch_size=64),
        ps_client=PSClient(ps_addrs),
        seed=0,
    )
    from collections import namedtuple

    FakeTask = namedtuple("FakeTask", "shard_name start end")
    metrics = deepfm.eval_metrics_fn()
    state = None
    for shard_name, (start, count) in reader.create_shards().items():
        stream = reader.read_records(
            FakeTask(shard_name, start, start + count)
        )
        dataset = deepfm.dataset_fn(
            Dataset(lambda s=stream: s), Mode.EVALUATION, reader.metadata
        )
        for batch in dataset.batch(64):
            state = trainer.ensure_state(state, batch)
            outputs = trainer.eval_step(state, batch)
            from elasticdl_tpu.data.pipeline import batch_real_count

            real = batch_real_count(batch)
            for metric in metrics.values():
                metric.update_state(
                    np.asarray(batch["labels"])[:real],
                    np.asarray(outputs)[:real],
                )
    return {name: float(m.result()) for name, m in metrics.items()}


def run_scenario(name, schedule, train_dir, valid_dir, tmp,
                 records_per_task, num_epochs, eval_steps, lr):
    """schedule: dict with initial worker count and optional elastic
    triggers {"start": 2, "add_at": 0.33, "add": 2, "kill_at": 0.66}."""
    from elasticdl_tpu.common.grpc_utils import (
        build_server, find_free_port,
    )
    from elasticdl_tpu.data.readers import RecordIODataReader
    from elasticdl_tpu.master.evaluation_service import EvaluationService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.master.task_monitor import TaskMonitor
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.proto.services import add_master_servicer_to_server

    train_reader = RecordIODataReader(data_dir=train_dir)
    valid_reader = RecordIODataReader(data_dir=valid_dir)
    dispatcher = TaskDispatcher(
        training_shards=train_reader.create_shards(),
        evaluation_shards=valid_reader.create_shards(),
        records_per_task=records_per_task,
        num_epochs=num_epochs,
        seed=0,
    )
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    total_train_tasks = [0]
    done_train_tasks = [0]

    def on_task_done(task):
        if task.type == pb.TRAINING:
            done_train_tasks[0] += 1

    dispatcher.add_task_completed_callback(on_task_done)
    # total: tasks currently queued (one epoch is lazily materialized
    # at a time; fraction-of-first-epoch is a fine trigger)
    evals = EvaluationService(
        dispatcher, deepfm.eval_metrics_fn, eval_steps=eval_steps
    )
    servicer = MasterServicer(dispatcher, evals)
    monitor = TaskMonitor(
        dispatcher, servicer, liveness_timeout_secs=8.0,
        scan_interval_secs=0.5,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()
    monitor.start()

    num_ps = 2
    ps_ports = [find_free_port() for _ in range(num_ps)]
    ps_procs = [
        _spawn_ps(i, num_ps, p, lr) for i, p in enumerate(ps_ports)
    ]
    ps_addrs = ["localhost:%d" % p for p in ps_ports]
    workers = {}
    try:
        for p in ps_ports:
            _wait_port(p)
        for i in range(schedule["start"]):
            workers[i] = _spawn_worker(
                i, master_port, ",".join(ps_addrs), train_dir,
                os.path.join(tmp, "%s_w%d.log" % (name, i)),
            )

        # epoch 1's task count is known once created
        time.sleep(1.0)
        with dispatcher._lock:
            total_train_tasks[0] = len(dispatcher._todo) + len(
                dispatcher._doing
            )
        added = killed = False
        deadline = time.time() + 900
        while not dispatcher.finished():
            if time.time() > deadline:
                raise TimeoutError("%s never finished" % name)
            progress = done_train_tasks[0] / max(
                1, total_train_tasks[0] * num_epochs
            )
            if (
                not added
                and "add_at" in schedule
                and progress >= schedule["add_at"]
            ):
                base = len(workers)
                for j in range(schedule["add"]):
                    idx = base + j
                    workers[idx] = _spawn_worker(
                        idx, master_port, ",".join(ps_addrs), train_dir,
                        os.path.join(tmp, "%s_w%d.log" % (name, idx)),
                    )
                added = True
                print("[%s] +%d workers at %.0f%%"
                      % (name, schedule["add"], progress * 100))
            if (
                not killed
                and "kill_at" in schedule
                and progress >= schedule["kill_at"]
            ):
                victim = sorted(workers)[0]
                workers[victim].send_signal(signal.SIGKILL)
                killed = True
                print("[%s] SIGKILL worker %d at %.0f%%"
                      % (name, victim, progress * 100))
            time.sleep(0.5)
        assert not dispatcher.job_failed(), "%s job failed" % name
        # the elastic scenario must really have churned: a silent
        # no-trigger run would measure fixed-N and call it elastic
        if "add_at" in schedule:
            assert added, "%s: add trigger never fired" % name
        if "kill_at" in schedule:
            assert killed, "%s: kill trigger never fired" % name

        final = _final_eval(ps_addrs, valid_dir)
        curve = [
            (int(version), {k: float(v) for k, v in summary.items()})
            for version, summary in evals.completed_summaries
        ]
        return {"final": final, "curve": curve,
                "workers_seen": len(workers),
                "train_tasks": done_train_tasks[0]}
    finally:
        for proc in workers.values():
            if proc.poll() is None:
                proc.kill()
        for proc in ps_procs:
            proc.terminate()
        for proc in ps_procs:
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
        monitor.stop()
        server.stop(0)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--records", type=int, default=6144)
    parser.add_argument("--valid_records", type=int, default=1024)
    parser.add_argument("--records_per_task", type=int, default=256)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--eval_steps", type=int, default=8)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--tolerance", type=float, default=0.03,
                        help="max allowed final-AUC gap vs fixed-2")
    parser.add_argument("--out_csv",
                        default=os.path.join(
                            REPO, "docs", "data",
                            "elastic_convergence.csv"))
    args = parser.parse_args()

    from tests.test_utils import create_ctr_recordio

    tmp = tempfile.mkdtemp(prefix="edl_elastic_")
    train_dir = os.path.join(tmp, "train")
    valid_dir = os.path.join(tmp, "valid")
    os.makedirs(train_dir)
    os.makedirs(valid_dir)
    create_ctr_recordio(
        os.path.join(train_dir, "f0.rec"),
        num_records=args.records, seed=0,
    )
    create_ctr_recordio(
        os.path.join(valid_dir, "f0.rec"),
        num_records=args.valid_records, seed=1,
    )

    scenarios = {
        "fixed2": {"start": 2},
        "fixed4": {"start": 4},
        "elastic": {"start": 2, "add_at": 0.33, "add": 2,
                    "kill_at": 0.66},
    }
    results = {}
    for name, schedule in scenarios.items():
        t0 = time.time()
        results[name] = run_scenario(
            name, schedule, train_dir, valid_dir, tmp,
            args.records_per_task, args.num_epochs, args.eval_steps,
            args.lr,
        )
        results[name]["wall_secs"] = round(time.time() - t0, 1)
        print("[%s] final=%s (%.1fs)" % (
            name, results[name]["final"], results[name]["wall_secs"]))

    os.makedirs(os.path.dirname(args.out_csv), exist_ok=True)
    with open(args.out_csv, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["scenario", "model_version", "metric", "value"])
        for name, r in results.items():
            for version, summary in r["curve"]:
                for metric, value in summary.items():
                    writer.writerow([name, version, metric, round(value, 5)])
            for metric, value in r["final"].items():
                writer.writerow([name, "final", metric, round(value, 5)])

    metric_key = "auc"
    baselinev = results["fixed2"]["final"][metric_key]
    gaps = {
        name: abs(r["final"][metric_key] - baselinev)
        for name, r in results.items()
    }
    ok = all(gap <= args.tolerance for gap in gaps.values())
    print(json.dumps({
        "metric": metric_key,
        "final": {n: round(r["final"][metric_key], 4)
                  for n, r in results.items()},
        "max_gap": round(max(gaps.values()), 4),
        "tolerance": args.tolerance,
        "converged_equivalently": ok,
        "csv": args.out_csv,
    }))
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
