"""Wire-path microbenchmark: serialization + dedup + store apply.

Prints ONE JSON line with per-path milliseconds plus the in-run
speedup of each ISSUE-5 fast path over the legacy path it replaced:

- packed ids_blob serialization   vs  repeated-varint Python-loop ids
- sort+reduceat dedup             vs  np.add.at scatter-add
- vectorized numpy-store apply    vs  the per-id sequential loop

Exit code 1 ONLY when a fast path measures as an actual regression
(>= ``--fail-under``x SLOWER than its legacy twin, default 1/3x i.e.
"the new path is more than 3x worse than what it replaced"). Absolute
numbers are report-only — CI journals them but never gates on them, so
box-to-box noise cannot flake the lane; the relative comparison runs
both paths back-to-back in one process.
"""

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

from elasticdl_tpu.common import tensor_utils  # noqa: E402
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb  # noqa: E402


def timeit(fn, reps):
    best = float("inf")
    for _ in range(reps):
        start = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - start)
    return best * 1000.0  # ms


def bench_serialize(ids, values, reps):
    def legacy():
        slices = pb.IndexedSlicesProto()
        tensor_utils.ndarray_to_blob(values, slices.concat_tensors)
        del slices.ids[:]
        slices.ids.extend(int(i) for i in ids)  # the pre-ISSUE-5 path
        return slices.SerializeToString()

    def packed():
        slices = tensor_utils.serialize_indexed_slices(values, ids)
        return slices.SerializeToString()

    legacy_wire = legacy()
    packed_wire = packed()
    return {
        "serialize_legacy_ms": round(timeit(legacy, reps), 3),
        "serialize_packed_ms": round(timeit(packed, reps), 3),
        "serialize_legacy_bytes": len(legacy_wire),
        "serialize_packed_bytes": len(packed_wire),
    }


def bench_dedup(ids, values, reps):
    def add_at():
        # the pre-ISSUE-5 deduplicate_indexed_slices body
        unique, index = np.unique(ids, return_inverse=True)
        summed = np.zeros((unique.size, values.shape[1]), values.dtype)
        np.add.at(summed, index, values)
        return summed

    def segmented():
        return tensor_utils.deduplicate_indexed_slices(values, ids)

    ref = add_at()
    got, _ = segmented()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-2)
    return {
        "dedup_add_at_ms": round(timeit(add_at, reps), 3),
        "dedup_segment_ms": round(timeit(segmented, reps), 3),
    }


def bench_apply(dim, n_rows, reps):
    from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore

    rng = np.random.RandomState(0)
    unique_ids = rng.permutation(10 * n_rows)[:n_rows].astype(np.int64)
    grads = rng.randn(n_rows, dim).astype(np.float32)

    def run(ids):
        store = NumpyEmbeddingStore(seed=0)
        store.set_optimizer("adam", lr=0.01)
        store.create_table("t", dim)
        store.push_gradients("t", ids, grads)  # init rows (untimed cost
        # is shared: both paths lazily create the same rows first)

        def push():
            store.push_gradients("t", ids, grads)

        return timeit(push, reps)

    # one duplicated id forces the sequential per-id path on the same
    # data volume: n identical optimizer applies either way
    dup_ids = unique_ids.copy()
    dup_ids[-1] = dup_ids[0]
    return {
        "apply_vectorized_ms": round(run(unique_ids), 3),
        "apply_per_id_ms": round(run(dup_ids), 3),
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n-ids", type=int, default=100_000)
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--apply-rows", type=int, default=4096)
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument(
        "--fail-under", type=float, default=1.0 / 3.0,
        help="hard-fail when fast/legacy speedup drops below this "
             "(default 1/3 = a >3x regression)",
    )
    args = parser.parse_args()

    rng = np.random.RandomState(0)
    # Zipfian ids: the duplicate-heavy CTR stream shape both the dedup
    # and the scatter-add worst case come from
    ids = (rng.zipf(1.2, size=args.n_ids) % 1_000_000).astype(np.int64)
    values = rng.randn(args.n_ids, args.dim).astype(np.float32)

    out = {}
    out.update(bench_serialize(ids, values, args.reps))
    out.update(bench_dedup(ids, values, args.reps))
    out.update(bench_apply(args.dim, args.apply_rows, args.reps))
    out["serialize_speedup"] = round(
        out["serialize_legacy_ms"] / max(out["serialize_packed_ms"], 1e-6), 2
    )
    out["dedup_speedup"] = round(
        out["dedup_add_at_ms"] / max(out["dedup_segment_ms"], 1e-6), 2
    )
    out["apply_speedup"] = round(
        out["apply_per_id_ms"] / max(out["apply_vectorized_ms"], 1e-6), 2
    )
    print(json.dumps(out))

    failures = [
        name for name in
        ("serialize_speedup", "dedup_speedup", "apply_speedup")
        if out[name] < args.fail_under
    ]
    if failures:
        print(
            "wire-micro REGRESSION: %s below the %.2fx floor"
            % (", ".join(failures), args.fail_under),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
