#!/usr/bin/env python
"""Merge per-role /profilez captures into one flamegraph-ready file.

Input: one or more ``/profilez`` JSON captures (files, or directories
scanned for ``*.profile.json``) — each the output of
``curl role:port/profilez[?seconds=N]`` saved per role. Output:

- a merged collapsed-stack file (``-o``, default
  ``<first input dir>/merged.collapsed.txt``): one
  ``role;[segment];frame;... count`` line per aggregated stack, role
  (and critical-path segment, when the sample was span-tagged) folded
  in as leading frames so a flamegraph groups by role at the root —
  load it in speedscope / flamegraph.pl / any collapsed-stack viewer;
- a per-role top-N self-time table on stderr (self = samples with the
  frame on top, total = samples with the frame anywhere), the "where
  did this role's host time go" answer without leaving the terminal;
- the same summary as JSON on stdout (journaled by CI tier 1d).

Usage:
    python scripts/profile_report.py CAPTURES... [-o collapsed.txt]
        [--top N]
"""

import argparse
import glob
import json
import os
import sys


def discover(paths):
    """Capture file list: files as given, directories scanned for
    *.profile.json (sorted — deterministic merge order)."""
    found = []
    for path in paths:
        if os.path.isdir(path):
            found.extend(sorted(glob.glob(
                os.path.join(path, "*.profile.json")
            )))
        elif path:
            found.append(path)
    return found


def load_captures(paths):
    """[(path, capture dict)] for every parseable capture; a corrupt
    file is skipped loudly, not fatal — partial reports beat none."""
    captures = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                capture = json.load(f)
        except (OSError, ValueError) as e:
            print("skipping %s: %s" % (path, e), file=sys.stderr)
            continue
        if not isinstance(capture, dict) or "stacks" not in capture:
            print("skipping %s: not a /profilez capture" % path,
                  file=sys.stderr)
            continue
        captures.append((path, capture))
    return captures


def merge_collapsed(captures):
    """{collapsed line prefix -> count} with role (and segment) folded
    in as leading frames."""
    merged = {}
    for path, capture in captures:
        role = capture.get("role") or os.path.basename(path)
        for entry in capture.get("stacks", ()):
            frames = [role]
            if entry.get("segment"):
                frames.append("[%s]" % entry["segment"])
            frames.extend(entry.get("stack", ()))
            key = ";".join(frames)
            merged[key] = merged.get(key, 0) + int(entry.get("count", 0))
    return merged


def per_role_top(captures, top=10):
    """{role: {samples, top: [{frame, self, total}]}} — self time is
    leaf-frame sample count, total counts the frame anywhere in the
    stack (deduped per stack, so recursion doesn't double-bill)."""
    roles = {}
    for path, capture in captures:
        role = capture.get("role") or os.path.basename(path)
        book = roles.setdefault(
            role, {"samples": 0, "self": {}, "total": {}}
        )
        book["samples"] += int(capture.get("samples", 0))
        for entry in capture.get("stacks", ()):
            stack = entry.get("stack", ())
            count = int(entry.get("count", 0))
            if not stack:
                continue
            leaf = stack[-1]
            book["self"][leaf] = book["self"].get(leaf, 0) + count
            for frame in set(stack):
                book["total"][frame] = (
                    book["total"].get(frame, 0) + count
                )
    report = {}
    for role, book in sorted(roles.items()):
        ranked = sorted(
            book["self"].items(), key=lambda kv: (-kv[1], kv[0])
        )[:top]
        report[role] = {
            "samples": book["samples"],
            "top": [
                {
                    "frame": frame,
                    "self": self_count,
                    "total": book["total"].get(frame, self_count),
                }
                for frame, self_count in ranked
            ],
        }
    return report


def render_table(report, out=sys.stderr):
    for role, entry in report.items():
        print(
            "%s: %d samples" % (role, entry["samples"]), file=out
        )
        samples = max(1, entry["samples"])
        for row in entry["top"]:
            print(
                "  %5.1f%% self  %5.1f%% total  %s"
                % (100.0 * row["self"] / samples,
                   100.0 * row["total"] / samples, row["frame"]),
                file=out,
            )


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "captures", nargs="+",
        help="/profilez JSON capture files, or dirs of *.profile.json",
    )
    parser.add_argument("-o", "--output", default="",
                        help="collapsed-stack output path (default: "
                             "<first input dir>/merged.collapsed.txt)")
    parser.add_argument("--top", type=int, default=10,
                        help="rows per role in the self-time table")
    args = parser.parse_args(argv)
    paths = discover(args.captures)
    captures = load_captures(paths)
    if not captures:
        print("no /profilez captures found in %s" % args.captures,
              file=sys.stderr)
        return 1
    out_path = args.output
    if not out_path:
        first = args.captures[0]
        base = first if os.path.isdir(first) else os.path.dirname(first)
        out_path = os.path.join(base or ".", "merged.collapsed.txt")
    merged = merge_collapsed(captures)
    with open(out_path, "w", encoding="utf-8") as f:
        for key, count in sorted(
            merged.items(), key=lambda kv: (-kv[1], kv[0])
        ):
            f.write("%s %d\n" % (key, count))
    report = per_role_top(captures, top=args.top)
    render_table(report)
    print(
        "merged %d capture(s), %d distinct stacks -> %s"
        % (len(captures), len(merged), out_path),
        file=sys.stderr,
    )
    print(json.dumps({
        "captures": len(captures),
        "stacks": len(merged),
        "collapsed_path": out_path,
        "roles": report,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
