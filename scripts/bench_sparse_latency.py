"""Pipelined-vs-sequential sparse training under controlled PS latency.

The round-2 VERDICT (item 4) asked for the pipelined-sparse claim to be
measured, not extrapolated: this sweeps an injected per-RPC delay at
the PS processes (``--inject_rpc_delay_ms``, emulating worker<->PS
network RTT) and measures both training modes at each point.

MEASURE ON A REAL ACCELERATOR: run with ``--backend default`` (and
delays sized against the step time, e.g. ``--delays_ms 0,20,50,100``
on this tunneled box) — that is how the docs/PERF_SPARSE.md crossover
table was produced. The default ``--backend cpu`` only validates the
harness: on the CPU backend the "device" compute runs on the same
cores the pull/push threads need, so overlap cannot win by
construction (measured 0.91-1.01x).

Prints one JSON line with the crossover table.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--delays_ms", default="0,5,20",
        help="comma-separated injected per-RPC delays",
    )
    parser.add_argument("--batch_size", type=int, default=512)
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--warmup", type=int, default=10)
    parser.add_argument(
        "--backend", default="cpu", choices=["cpu", "default"],
        help="cpu: force JAX_PLATFORMS=cpu; default: whatever the "
        "machine provides (the real chip here). NOTE the cpu backend "
        "cannot demonstrate overlap — 'device' compute runs on the "
        "same cores the pull/push threads need — it only validates "
        "the harness; measure on a real accelerator.",
    )
    args = parser.parse_args()
    if args.backend == "cpu":
        # must precede any jax import (including the one inside bench)
        os.environ["JAX_PLATFORMS"] = "cpu"

    from bench import deepfm_run

    rows = []
    for delay in [float(d) for d in args.delays_ms.split(",")]:
        sequential, _ = deepfm_run(
            pipelined=False, inject_rpc_delay_ms=delay,
            batch_size=args.batch_size, warmup=args.warmup,
            steps=args.steps,
        )
        pipelined, _ = deepfm_run(
            pipelined=True, inject_rpc_delay_ms=delay,
            batch_size=args.batch_size, warmup=args.warmup,
            steps=args.steps,
        )
        rows.append({
            "rtt_ms": delay,
            "sequential_steps_per_sec": round(sequential, 2),
            "pipelined_steps_per_sec": round(pipelined, 2),
            "speedup": round(pipelined / sequential, 2),
        })
        print("rtt=%5.1fms  seq=%6.2f  pipe=%6.2f  speedup=%.2fx"
              % (delay, sequential, pipelined, pipelined / sequential),
              flush=True)
    print(json.dumps({"backend": args.backend, "batch": args.batch_size,
                      "rows": rows}))


if __name__ == "__main__":
    main()
