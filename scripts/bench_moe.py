"""MoE-transformer single-chip step bench vs dense at matched ACTIVE
FLOPs (round-4 VERDICT item 5: every capability ships a measured
number; MoE had correctness only).

Two arms, same embed/attention dims, full train step (fwd+bwd+AdamW)
under one jit'd lax.scan:

- moe:   MoeTransformerLM, E experts, top-k=2, capacity_factor cf —
         every token's FFN compute is k*cf x the dense block's
         (static-capacity GShard dispatch runs every slot, full or
         not), plus the dispatch/combine einsums (O(S * E*C * M) —
         the real price of the einsum-dispatch formulation).
- dense: TransformerLM with mlp_ratio scaled by ~k*cf so its FFN FLOPs
         match the MoE arm's ACTIVE FFN FLOPs.

Model FLOPs are counted exactly per arm (routing + dispatch included
for moe), so the reported MFUs are comparable and honest. Prints one
JSON line with both arms + the relative step-time overhead of the MoE
machinery at equal active compute.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v4": 275e12, "TPU v5p": 459e12}


def dense_flops(d, layers, seq, batch, vocab, mlp_ratio):
    tokens = batch * seq
    proj = 2 * tokens * ((4 + 2 * mlp_ratio) * d * d) * layers
    attn = 2 * (2 * batch * seq * seq * d) * layers / 2
    head = 2 * tokens * d * vocab
    return 3 * (proj + attn + head)


def moe_flops(d, layers, seq, batch, vocab, mlp_ratio, num_experts, k,
              capacity_factor, compact_dispatch):
    """Exact matmul FLOPs of MoeTransformerLM: MoE FFN in every other
    block (models/moe_transformer.py), static capacity C per group.

    The compact (slot-index gather) dispatch executes NO dispatch/
    combine matmuls — those terms only exist on the one-hot einsum
    path, so each arm's MFU divides by the FLOPs it actually runs."""
    from elasticdl_tpu.ops.moe import expert_capacity

    tokens = batch * seq
    moe_layers = layers // 2
    dense_layers = layers - moe_layers
    capacity = expert_capacity(seq, num_experts, k, capacity_factor)
    ff = mlp_ratio * d
    # attention + out-proj + qkv in EVERY block
    proj_attn = 2 * tokens * (4 * d * d) * layers
    attn = 2 * (2 * batch * seq * seq * d) * layers / 2
    # dense-block FFNs
    ffn_dense = 2 * tokens * (2 * mlp_ratio * d * d) * dense_layers
    # expert FFNs: every (expert, slot) computes, full or not
    slots = batch * num_experts * capacity
    ffn_moe = 2 * slots * (2 * d * ff) * moe_layers
    # router; dispatch/combine einsums (gsec,gsm->egcm and back) are
    # matmuls only on the one-hot path — the compact path gathers
    router = 2 * tokens * d * num_experts * moe_layers
    if compact_dispatch:
        dispatch = 0
    else:
        dispatch = (
            2 * 2 * batch * seq * num_experts * capacity * d * moe_layers
        )
    head = 2 * tokens * d * vocab
    return 3 * (proj_attn + attn + ffn_dense + ffn_moe + router
                + dispatch + head)


def run_arm(model, loss_fn, flops, batch_tokens, args, profile_dir=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.train.optimizers import create_optimizer
    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    if args.opt == "AdamW":
        tx = create_optimizer(
            "AdamW", learning_rate=3e-4, weight_decay=0.01
        )
    else:  # decomposition arm: no m/v state traffic (docs/PERF_MOE.md)
        tx = create_optimizer(args.opt, learning_rate=3e-4)
    train_step = make_train_step(
        model, loss_fn, tx, compute_dtype=jnp.bfloat16
    )

    def run_steps(state, batch, n):
        def body(state, _):
            state, loss = train_step(state, batch)
            return state, loss

        return jax.lax.scan(body, state, None, length=n)

    run = jax.jit(run_steps, static_argnums=(2,), donate_argnums=(0,))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, args.vocab, size=(args.batch, args.seq)), jnp.int32
    )
    batch = {
        "features": tokens,
        "labels": tokens,
        "_mask": jnp.ones((args.batch,), jnp.float32),
    }
    state = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )
    n_params = sum(
        x.size for x in jax.tree_util.tree_leaves(state.params)
    )
    state, losses = run(state, batch, args.steps)  # compile + warmup
    float(losses[-1])
    start = time.perf_counter()
    state, losses = run(state, batch, args.steps)
    final_loss = float(losses[-1])
    elapsed = time.perf_counter() - start
    assert np.isfinite(final_loss), final_loss
    if profile_dir:
        from scripts.trace_summary import capture_trace

        def _once():
            _, traced_losses = run(state, batch, args.steps)
            float(traced_losses[-1])

        capture_trace(_once, profile_dir, args.steps)
    kind = jax.devices()[0].device_kind
    peak = PEAK_FLOPS.get(kind, 197e12)
    step = elapsed / args.steps
    return {
        "params_m": round(n_params / 1e6, 1),
        "step_ms": round(step * 1e3, 2),
        "tokens_per_sec": round(batch_tokens / step, 1),
        "model_tflop_per_step": round(flops / 1e12, 3),
        "mfu": round(flops / step / peak, 4),
        "device": kind,
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--d", type=int, default=1024)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--mlp_ratio", type=int, default=4)
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--top_k", type=int, default=2)
    p.add_argument("--capacity_factor", type=float, default=1.25)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--attn", default="pallas")
    p.add_argument(
        "--opt", default="AdamW",
        help="optimizer for BOTH arms (SGD isolates the optimizer-"
             "state-traffic share of the MoE step premium)",
    )
    p.add_argument(
        "--dispatch", default="auto",
        choices=["auto", "compact", "onehot"],
        help="MoE dispatch impl (auto = the one-hot einsums, the "
             "measured default; compact = the slot-index gather path)",
    )
    p.add_argument(
        "--profile", default=None,
        help="trace dir for the MoE arm (HLO-category summary printed)",
    )
    args = p.parse_args()

    import jax

    # the container's sitecustomize pins the axon platform at
    # interpreter start; honor an explicit JAX_PLATFORMS (e.g. the CPU
    # smoke run) through jax.config, which wins over that registration
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from elasticdl_tpu.models import moe_transformer, transformer

    batch_tokens = args.batch * args.seq
    moe_model = moe_transformer.MoeTransformerLM(
        vocab_size=args.vocab,
        num_layers=args.layers,
        num_heads=args.heads,
        embed_dim=args.d,
        mlp_ratio=args.mlp_ratio,
        num_experts=args.experts,
        top_k=args.top_k,
        capacity_factor=args.capacity_factor,
        attention_impl=args.attn,
        dispatch_impl=args.dispatch,
    )
    # "auto" resolves to the one-hot einsums (models/moe_transformer.py:
    # the measured default); only an explicit --dispatch compact drops
    # the dispatch-einsum FLOPs from the count
    compact = args.dispatch == "compact"
    moe = run_arm(
        moe_model,
        moe_transformer.loss,
        moe_flops(args.d, args.layers, args.seq, args.batch, args.vocab,
                  args.mlp_ratio, args.experts, args.top_k,
                  args.capacity_factor, compact),
        batch_tokens,
        args,
        profile_dir=args.profile,
    )
    # dense arm at matched ACTIVE FFN FLOPs: half the blocks carry
    # k*cf-times the FFN (the other half already match), i.e. mean
    # mlp_ratio = r * (1 + k*cf) / 2
    dense_ratio = max(
        1, round(args.mlp_ratio * (1 + args.top_k * args.capacity_factor)
                 / 2)
    )
    dense_model = transformer.TransformerLM(
        vocab_size=args.vocab,
        num_layers=args.layers,
        num_heads=args.heads,
        embed_dim=args.d,
        mlp_ratio=dense_ratio,
        attention_impl=args.attn,
    )
    dense = run_arm(
        dense_model,
        transformer.loss,
        dense_flops(args.d, args.layers, args.seq, args.batch,
                    args.vocab, dense_ratio),
        batch_tokens,
        args,
    )
    print(json.dumps({
        "config": {
            "d": args.d, "layers": args.layers, "seq": args.seq,
            "batch": args.batch, "experts": args.experts,
            "top_k": args.top_k,
            "capacity_factor": args.capacity_factor,
            "moe_mlp_ratio": args.mlp_ratio,
            "dense_mlp_ratio_matched": dense_ratio,
            "attn": args.attn,
            "dispatch": args.dispatch,
        },
        "moe": moe,
        "dense_matched_active": dense,
        "moe_step_overhead_vs_dense": round(
            moe["step_ms"] / dense["step_ms"], 3
        ),
    }))


if __name__ == "__main__":
    main()
