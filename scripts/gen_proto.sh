#!/bin/sh
# Regenerate protobuf Python code. The gRPC stubs are hand-written in
# elasticdl_tpu/proto/services.py (no grpc_tools in this environment), so
# only message codegen is needed.
set -e
cd "$(dirname "$0")/.."
protoc --python_out=. elasticdl_tpu/proto/elasticdl_tpu.proto
echo "Regenerated elasticdl_tpu/proto/elasticdl_tpu_pb2.py"
