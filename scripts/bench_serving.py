#!/usr/bin/env python
"""Serving-tier bench: open-loop Zipfian load at fixed QPS, with a
mid-run zero-downtime version swap (ISSUE 8).

Topology: a deepfm model trained briefly in-process (LocalExecutor),
exported, then served through the REAL stack — gRPC Serve service,
admission-controlled micro-batcher, read-only embedding client with
TTL cache against the trained store. The load generator is OPEN-LOOP
(requests fire on a fixed schedule regardless of completions — the
only honest way to measure a serving tier: closed-loop generators
self-throttle exactly when the server degrades) with Zipfian ids, the
id distribution the hot-row stack exists for.

Mid-run, the trainer exports a NEWER version into the watched
directory. The HARD GATE (exit 1): the swap must complete and ZERO
requests may fail or shed across the whole run — in-flight requests
finish on the version that admitted them, new ones ride the warmed
replacement. p50/p99 latency and QPS/chip are REPORT-ONLY (journaled
by ci.sh tier 1f like the wire and tier benches; absolute numbers
flake across boxes).

Env knobs: BENCH_SERVING_QPS (default 150), BENCH_SERVING_SECS (8),
BENCH_SERVING_SWAP_AT (0.5 = mid-run fraction).
"""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

import numpy as np  # noqa: E402


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax  # noqa: E402 (after platform pin)

    from test_utils import create_ctr_recordio  # noqa: E402
    from elasticdl_tpu.common.grpc_utils import (  # noqa: E402
        build_server,
        find_free_port,
    )
    from elasticdl_tpu.observability import events  # noqa: E402
    from elasticdl_tpu.proto.services import (  # noqa: E402
        add_serve_servicer_to_server,
    )
    from elasticdl_tpu.serve.client import ServeClient  # noqa: E402
    from elasticdl_tpu.serve.engine import ServingEngine  # noqa: E402
    from elasticdl_tpu.serve.servicer import ServeServicer  # noqa: E402
    from elasticdl_tpu.train.export import export_train_state  # noqa: E402
    from elasticdl_tpu.train.local_executor import LocalExecutor  # noqa: E402

    events.configure("bench-serving")

    qps = _env_float("BENCH_SERVING_QPS", 150.0)
    duration = _env_float("BENCH_SERVING_SECS", 8.0)
    swap_at = _env_float("BENCH_SERVING_SWAP_AT", 0.5)
    vocab = 1000
    zipf_a = 1.3
    rows_per_request = 4
    fields = 10

    # ---- train + export ------------------------------------------------
    tmp = tempfile.mkdtemp(prefix="edl-bench-serving-")
    create_ctr_recordio(
        tmp + "/f0.rec", num_records=256, vocab=vocab, seed=0
    )
    executor = LocalExecutor(
        "elasticdl_tpu.models.deepfm", training_data=tmp,
        minibatch_size=32, num_epochs=1,
    )
    executor.train()
    export_dir = os.path.join(tmp, "export")
    export_train_state(executor.state, export_dir)

    # ---- serve through the real stack ----------------------------------
    engine = ServingEngine(
        "elasticdl_tpu.models.deepfm", export_dir,
        ps_client=executor.trainer.preparer._ps,
        max_batch=64, max_delay_ms=3.0, queue_depth=512,
        deadline_ms=5000.0, cache_ttl_secs=2.0, watch_secs=0.25,
    ).start(block=True)
    server = build_server()
    add_serve_servicer_to_server(ServeServicer(engine), server)
    port = find_free_port()
    server.add_insecure_port("[::]:%d" % port)
    server.start()
    client = ServeClient("localhost:%d" % port)
    first_step = engine.model.step

    # warm the compiled shape out of the measurement
    warm_ids = np.ones((rows_per_request, fields), np.int64)
    client.predict({"ids": warm_ids}, deadline_secs=60)

    # ---- open-loop load ------------------------------------------------
    rng = np.random.RandomState(42)
    total = int(qps * duration)
    latencies = [None] * total
    steps_seen = [0] * total
    failures = []
    swap_window = []  # (start, end) of the swap, filled by the swapper
    done = threading.Semaphore(0)
    pool_lock = threading.Lock()
    inflight = 0
    max_inflight = 0

    def zipf_ids():
        raw = rng.zipf(zipf_a, size=(rows_per_request, fields))
        return np.minimum(raw, vocab - 1).astype(np.int64)

    def fire(i, ids):
        nonlocal inflight, max_inflight
        start = time.perf_counter()
        try:
            _, step, _ = client.predict({"ids": ids}, deadline_secs=10)
            latencies[i] = time.perf_counter() - start
            steps_seen[i] = step
        except Exception as e:  # the hard gate counts every failure
            failures.append((i, repr(e)))
        finally:
            with pool_lock:
                inflight -= 1
            done.release()

    def swapper():
        time.sleep(duration * swap_at)
        t0 = time.monotonic()
        # train a few more steps so the exported step really moves
        batches = []
        for batch in executor._batches(executor._train_reader, "training"):
            batches.append(batch)
            if len(batches) >= 3:
                break
        for batch in batches:
            executor.state, _ = executor.trainer.train_step(
                executor.state, batch
            )
        export_train_state(executor.state, export_dir)
        while engine.swaps == 0 and time.monotonic() - t0 < 30:
            time.sleep(0.02)
        swap_window.append((t0, time.monotonic()))

    swap_thread = threading.Thread(target=swapper, daemon=True)
    swap_thread.start()

    interval = 1.0 / qps
    t_start = time.monotonic()
    for i in range(total):
        target = t_start + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        ids = zipf_ids()
        with pool_lock:
            inflight += 1
            max_inflight = max(max_inflight, inflight)
        threading.Thread(target=fire, args=(i, ids), daemon=True).start()
    for _ in range(total):
        done.acquire()
    wall = time.monotonic() - t_start
    swap_thread.join(timeout=60)

    server.stop(0)
    client.close()
    engine.drain(timeout=10)

    # ---- report --------------------------------------------------------
    served = [lat for lat in latencies if lat is not None]
    # all-failed runs must still reach the hard-gate diagnostics (and
    # the journaled report) instead of crashing on an empty percentile
    if served:
        lat_ms = np.asarray(served) * 1e3
        p50_ms = round(float(np.percentile(lat_ms, 50)), 2)
        p99_ms = round(float(np.percentile(lat_ms, 99)), 2)
    else:
        p50_ms = p99_ms = None
    chips = max(jax.device_count(), 1)
    new_step = engine.model.step
    report = {
        "qps_target": qps,
        "qps_achieved": round(len(served) / wall, 1),
        "qps_per_chip": round(len(served) / wall / chips, 1),
        "requests": total,
        "served": len(served),
        "failed": len(failures),
        "shed": engine.batcher.shed_total,
        "max_inflight": max_inflight,
        "p50_ms": p50_ms,
        "p99_ms": p99_ms,
        "swap": {
            "completed": engine.swaps >= 1,
            "from_step": int(first_step),
            "to_step": int(new_step),
            "secs": (
                round(swap_window[0][1] - swap_window[0][0], 2)
                if swap_window else None
            ),
        },
        "cache_hit_rate": round(engine.model.embedding_hit_rate, 3),
    }
    # compact single line: ci.sh tees stdout into the NDJSON bench
    # journal (one record per line, like the wire/tier benches)
    print(json.dumps(report))

    # ---- hard gates ----------------------------------------------------
    failed = []
    if not report["swap"]["completed"]:
        failed.append("version swap never completed")
    if new_step <= first_step:
        failed.append(
            "swap did not advance the step (%s -> %s)"
            % (first_step, new_step)
        )
    if failures:
        failed.append(
            "%d requests FAILED across the run (first: %s) — the "
            "zero-downtime swap contract does not hold"
            % (len(failures), failures[0][1])
        )
    if engine.batcher.shed_total:
        failed.append(
            "%d requests shed at this modest load — admission control "
            "is misconfigured for the bench envelope"
            % engine.batcher.shed_total
        )
    post_swap = [s for s in steps_seen if s == new_step]
    if report["swap"]["completed"] and not post_swap:
        failed.append("no request was served by the new version")
    if failed:
        print("BENCH GATE FAILED:", file=sys.stderr)
        for reason in failed:
            print("  - %s" % reason, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
