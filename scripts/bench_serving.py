#!/usr/bin/env python
"""Serving-tier bench: open-loop Zipfian load through the real stack.

Two modes, one harness (ISSUE 8 + ISSUE 17):

**Single-pod (default)** — a deepfm model trained briefly in-process
(LocalExecutor), exported, then served through the REAL stack — gRPC
Serve service, admission-controlled micro-batcher, read-only embedding
client with TTL cache against the trained store. Mid-run, the trainer
exports a NEWER version into the watched directory. The HARD GATE
(exit 1): the swap must complete and ZERO requests may fail or shed
across the whole run.

**Fleet (--router --replicas N)** — the same load generator pointed at
the ISSUE 17 router fronting N serve-replica SUBPROCESSES over a real
PS subprocess and a versioned export root. The run drives the full
fleet lifecycle under continuous open-loop traffic:

  1. spin-up     — N replicas spawn, register, load v1;
  2. SIGKILL     — one replica is hard-killed mid-traffic; its keys
                   fail over, the autoscaler's floor replaces it;
  3. promote     — a healthy v2 export lands; the canary slice loads
                   it, the judge promotes on matching prediction
                   distributions;
  4. rollback    — a POISONED v3 export lands (params scrambled, so
                   its prediction distribution drifts); the judge
                   rolls the canary back and blacklists the stamp.

HARD GATES (exit 1): zero failed client requests across all phases,
the killed replica replaced (floor restored), the canary BOTH promoted
v2 AND rolled back v3, and every scale/canary decision journaled with
its reasons. Latency and QPS are REPORT-ONLY (journaled by ci.sh tier
1f like the other benches; absolute numbers flake across boxes).

The load generator is OPEN-LOOP (requests fire on a fixed schedule
regardless of completions — the only honest way to measure a serving
tier: closed-loop generators self-throttle exactly when the server
degrades) with Zipfian ids and cycling affinity keys.

Env knobs, single-pod: BENCH_SERVING_QPS (default 150),
BENCH_SERVING_SECS (8), BENCH_SERVING_SWAP_AT (0.5).
Fleet: BENCH_FLEET_QPS (0 = auto-scale by CPU count — this bench runs
on 1-CPU CI boxes), BENCH_FLEET_CANARY_MIN (30 requests per arm),
BENCH_FLEET_DEADLINE_SECS (120 — generous: a request landing on a
cold replica pays its jit compile), BENCH_FLEET_TIMEOUT_SECS (900
per-phase watchdog).
"""

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

import numpy as np  # noqa: E402

_VOCAB = 1000
_ZIPF_A = 1.3
_ROWS_PER_REQUEST = 4
_FIELDS = 10


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _train_executor(tmp):
    """Brief in-process deepfm training run; returns the executor."""
    from test_utils import create_ctr_recordio
    from elasticdl_tpu.train.local_executor import LocalExecutor

    data = os.path.join(tmp, "data")
    os.makedirs(data, exist_ok=True)
    create_ctr_recordio(
        data + "/f0.rec", num_records=256, vocab=_VOCAB, seed=0
    )
    executor = LocalExecutor(
        "elasticdl_tpu.models.deepfm", training_data=data,
        minibatch_size=32, num_epochs=1,
    )
    executor.train()
    return executor


def _advance_training(executor, steps):
    """Train a few more steps so the next export's step really moves."""
    batches = []
    for batch in executor._batches(executor._train_reader, "training"):
        batches.append(batch)
        if len(batches) >= steps:
            break
    for batch in batches:
        executor.state, _ = executor.trainer.train_step(
            executor.state, batch
        )


def _zipf_ids(rng):
    raw = rng.zipf(_ZIPF_A, size=(_ROWS_PER_REQUEST, _FIELDS))
    return np.minimum(raw, _VOCAB - 1).astype(np.int64)


def _percentiles(latencies):
    if not latencies:
        return None, None
    lat_ms = np.asarray(latencies) * 1e3
    return (
        round(float(np.percentile(lat_ms, 50)), 2),
        round(float(np.percentile(lat_ms, 99)), 2),
    )


# ======================================================================
# single-pod mode (ISSUE 8)
# ======================================================================
def run_single():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax  # noqa: E402 (after platform pin)

    from elasticdl_tpu.common.grpc_utils import (  # noqa: E402
        build_server,
        find_free_port,
    )
    from elasticdl_tpu.observability import events  # noqa: E402
    from elasticdl_tpu.proto.services import (  # noqa: E402
        add_serve_servicer_to_server,
    )
    from elasticdl_tpu.serve.client import ServeClient  # noqa: E402
    from elasticdl_tpu.serve.engine import ServingEngine  # noqa: E402
    from elasticdl_tpu.serve.servicer import ServeServicer  # noqa: E402
    from elasticdl_tpu.train.export import export_train_state  # noqa: E402

    events.configure("bench-serving")

    qps = _env_float("BENCH_SERVING_QPS", 150.0)
    duration = _env_float("BENCH_SERVING_SECS", 8.0)
    swap_at = _env_float("BENCH_SERVING_SWAP_AT", 0.5)

    # ---- train + export ------------------------------------------------
    tmp = tempfile.mkdtemp(prefix="edl-bench-serving-")
    executor = _train_executor(tmp)
    export_dir = os.path.join(tmp, "export")
    export_train_state(executor.state, export_dir)

    # ---- serve through the real stack ----------------------------------
    engine = ServingEngine(
        "elasticdl_tpu.models.deepfm", export_dir,
        ps_client=executor.trainer.preparer._ps,
        max_batch=64, max_delay_ms=3.0, queue_depth=512,
        deadline_ms=5000.0, cache_ttl_secs=2.0, watch_secs=0.25,
    ).start(block=True)
    server = build_server()
    add_serve_servicer_to_server(ServeServicer(engine), server)
    port = find_free_port()
    server.add_insecure_port("[::]:%d" % port)
    server.start()
    client = ServeClient("localhost:%d" % port)
    first_step = engine.model.step

    # warm the compiled shape out of the measurement
    warm_ids = np.ones((_ROWS_PER_REQUEST, _FIELDS), np.int64)
    client.predict({"ids": warm_ids}, deadline_secs=60)

    # ---- open-loop load ------------------------------------------------
    rng = np.random.RandomState(42)
    total = int(qps * duration)
    latencies = [None] * total
    steps_seen = [0] * total
    failures = []
    swap_window = []  # (start, end) of the swap, filled by the swapper
    done = threading.Semaphore(0)
    pool_lock = threading.Lock()
    inflight = 0
    max_inflight = 0

    def fire(i, ids):
        nonlocal inflight, max_inflight
        start = time.perf_counter()
        try:
            _, step, _ = client.predict({"ids": ids}, deadline_secs=10)
            latencies[i] = time.perf_counter() - start
            steps_seen[i] = step
        except Exception as e:  # the hard gate counts every failure
            failures.append((i, repr(e)))
        finally:
            with pool_lock:
                inflight -= 1
            done.release()

    def swapper():
        time.sleep(duration * swap_at)
        t0 = time.monotonic()
        # train a few more steps so the exported step really moves
        _advance_training(executor, steps=3)
        export_train_state(executor.state, export_dir)
        while engine.swaps == 0 and time.monotonic() - t0 < 30:
            time.sleep(0.02)
        swap_window.append((t0, time.monotonic()))

    swap_thread = threading.Thread(target=swapper, daemon=True)
    swap_thread.start()

    interval = 1.0 / qps
    t_start = time.monotonic()
    for i in range(total):
        target = t_start + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        ids = _zipf_ids(rng)
        with pool_lock:
            inflight += 1
            max_inflight = max(max_inflight, inflight)
        threading.Thread(target=fire, args=(i, ids), daemon=True).start()
    for _ in range(total):
        done.acquire()
    wall = time.monotonic() - t_start
    swap_thread.join(timeout=60)

    server.stop(0)
    client.close()
    engine.drain(timeout=10)

    # ---- report --------------------------------------------------------
    served = [lat for lat in latencies if lat is not None]
    # all-failed runs must still reach the hard-gate diagnostics (and
    # the journaled report) instead of crashing on an empty percentile
    p50_ms, p99_ms = _percentiles(served)
    chips = max(jax.device_count(), 1)
    new_step = engine.model.step
    report = {
        "qps_target": qps,
        "qps_achieved": round(len(served) / wall, 1),
        "qps_per_chip": round(len(served) / wall / chips, 1),
        "requests": total,
        "served": len(served),
        "failed": len(failures),
        "shed": engine.batcher.shed_total,
        "max_inflight": max_inflight,
        "p50_ms": p50_ms,
        "p99_ms": p99_ms,
        "swap": {
            "completed": engine.swaps >= 1,
            "from_step": int(first_step),
            "to_step": int(new_step),
            "secs": (
                round(swap_window[0][1] - swap_window[0][0], 2)
                if swap_window else None
            ),
        },
        "cache_hit_rate": round(engine.model.embedding_hit_rate, 3),
    }
    # compact single line: ci.sh tees stdout into the NDJSON bench
    # journal (one record per line, like the wire/tier benches)
    print(json.dumps(report))

    # ---- hard gates ----------------------------------------------------
    failed = []
    if not report["swap"]["completed"]:
        failed.append("version swap never completed")
    if new_step <= first_step:
        failed.append(
            "swap did not advance the step (%s -> %s)"
            % (first_step, new_step)
        )
    if failures:
        failed.append(
            "%d requests FAILED across the run (first: %s) — the "
            "zero-downtime swap contract does not hold"
            % (len(failures), failures[0][1])
        )
    if engine.batcher.shed_total:
        failed.append(
            "%d requests shed at this modest load — admission control "
            "is misconfigured for the bench envelope"
            % engine.batcher.shed_total
        )
    post_swap = [s for s in steps_seen if s == new_step]
    if report["swap"]["completed"] and not post_swap:
        failed.append("no request was served by the new version")
    if failed:
        print("BENCH GATE FAILED:", file=sys.stderr)
        for reason in failed:
            print("  - %s" % reason, file=sys.stderr)
        return 1
    return 0


# ======================================================================
# fleet mode (ISSUE 17)
# ======================================================================
def _wait_port(port, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port))
            return True
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    return False


def _poison_bundle(path):
    """Scramble a bundle's dense params so its prediction distribution
    drifts hard off the incumbent's — the canary judge must roll it
    back on TV distance, not on crashes (the model stays finite)."""
    npz = os.path.join(path, "model.npz")
    data = np.load(npz)
    arrays = {name: data[name] for name in data.files}
    for name, arr in arrays.items():
        if name.startswith("params/"):
            arrays[name] = (arr * 6.0 + 4.0).astype(arr.dtype)
    np.savez(npz, **arrays)


def run_fleet(replicas):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    qps = _env_float("BENCH_FLEET_QPS", 0.0)
    if qps <= 0:
        # auto-scale to the box: the gates are invariants (zero
        # failures, both canary cycles), not throughput — 1-CPU CI
        # boxes run the same protocol at lower pressure
        qps = max(6.0, 4.0 * (os.cpu_count() or 1))
    canary_min = int(_env_float("BENCH_FLEET_CANARY_MIN", 30))
    deadline_secs = _env_float("BENCH_FLEET_DEADLINE_SECS", 120.0)
    watchdog = _env_float("BENCH_FLEET_TIMEOUT_SECS", 900.0)

    tmp = tempfile.mkdtemp(prefix="edl-bench-fleet-")
    events_dir = os.path.join(tmp, "events")
    root = os.path.join(tmp, "exports")
    log_dir = os.path.join(tmp, "logs")
    for d in (events_dir, root, log_dir):
        os.makedirs(d)
    # the canary controller and registry read their knobs from env at
    # construction; pin the bench's envelope before importing anything
    os.environ["EDL_EVENTS_DIR"] = events_dir
    os.environ["EDL_CANARY_FRACTION"] = os.environ.get(
        "EDL_CANARY_FRACTION", "0.5"
    )
    os.environ["EDL_CANARY_MIN_REQUESTS"] = str(canary_min)
    os.environ.setdefault("EDL_CANARY_DRIFT_MAX", "0.25")
    # the judge must outlive cold-replica compiles; the bench's own
    # watchdog is the timeout that matters
    os.environ["EDL_CANARY_TIMEOUT_SECS"] = str(watchdog)

    from elasticdl_tpu.common.grpc_utils import (  # noqa: E402
        build_server,
        find_free_port,
    )
    from elasticdl_tpu.models import deepfm  # noqa: E402
    from elasticdl_tpu.observability import events  # noqa: E402
    from elasticdl_tpu.proto.services import (  # noqa: E402
        add_router_servicer_to_server,
        add_serve_servicer_to_server,
    )
    from elasticdl_tpu.serve.client import ServeClient  # noqa: E402
    from elasticdl_tpu.serve.fleet import (  # noqa: E402
        ReplicaAutoscaler,
        SubprocessReplicaScaler,
    )
    from elasticdl_tpu.serve.model import export_signature  # noqa: E402
    from elasticdl_tpu.serve.router import RouterServicer  # noqa: E402
    from elasticdl_tpu.train.export import export_train_state  # noqa: E402
    from elasticdl_tpu.worker.ps_client import PSClient  # noqa: E402
    from test_utils import load_journal  # noqa: E402

    events.configure("bench-fleet")
    gate_failures = []
    phases = {}

    def wait_until(condition, what, timeout=None):
        deadline = time.monotonic() + (
            timeout if timeout is not None else watchdog
        )
        while time.monotonic() < deadline:
            if condition():
                return True
            time.sleep(0.25)
        gate_failures.append("timed out waiting for %s" % what)
        return False

    # ---- train + v1 into the versioned root ----------------------------
    executor = _train_executor(tmp)
    export_train_state(executor.state, os.path.join(root, "v00001"))

    # ---- real PS subprocess, seeded with the trained rows --------------
    base_env = {
        **os.environ, "JAX_PLATFORMS": "cpu", "EDL_EVENTS_DIR": events_dir,
    }
    pport = find_free_port()
    ps = subprocess.Popen([
        sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
        "--num_ps_pods", "1", "--port", str(pport),
        "--opt_type", "adam", "--opt_args", "lr=0.001", "--use_async", "1",
    ], env=base_env)
    if not _wait_port(pport):
        print("BENCH GATE FAILED:\n  - PS never came up", file=sys.stderr)
        return 1
    seed_client = PSClient(["localhost:%d" % pport])
    specs = deepfm.sparse_embedding_specs(batch_size=32)
    seed_client.push_embedding_table_infos(
        [(s.name, s.dim, str(float(s.init_scale))) for s in specs]
    )
    store = executor.trainer.preparer._ps.store
    seed_client.push_embedding_rows({
        s.name: store.export_table(s.name) for s in specs
    })

    # ---- in-process router + subprocess replica fleet ------------------
    servicer = RouterServicer(
        # 15s timeout: a replica's heartbeat thread starves for several
        # seconds while jit compiles on a 1-CPU CI box — 4-5s would
        # expire live-but-compiling replicas
        heartbeat_secs=1.0, replica_timeout_secs=15.0,
        inflight_cap=max(64, int(qps) * 4),
        failover_retries=max(2, replicas - 1),
    )
    server = build_server()
    add_serve_servicer_to_server(servicer, server)
    add_router_servicer_to_server(servicer, server)
    rport = find_free_port()
    server.add_insecure_port("[::]:%d" % rport)
    server.start()
    scaler = SubprocessReplicaScaler(
        "127.0.0.1:%d" % rport, root,
        extra_args=[
            "--model_zoo", "elasticdl_tpu.models.deepfm",
            "--ps_addrs", "localhost:%d" % pport,
            "--max_batch", "32", "--max_delay_ms", "5",
            "--queue_depth", "256",
        ],
        env=base_env, log_dir=log_dir,
    )
    # floor == the fleet size: the only grow this bench should see is
    # the below-floor replacement after the SIGKILL. The cooldown must
    # outlast a replica's cold start (jax import + model load) or the
    # floor check re-fires into a spawn storm.
    autoscaler = ReplicaAutoscaler(
        servicer.registry, scaler,
        min_replicas=replicas, max_replicas=replicas + 1, step=1,
        hold_secs=1.0, cooldown_secs=60.0,
        queue_per_replica=1e9, qps_per_replica=1e9,
    )

    def all_loaded():
        state = servicer.registry.state()
        return (
            len(servicer.registry.routable_ids()) >= replicas
            and len(state) >= replicas
            and all(v["loaded_stamp"] for v in state.values())
        )

    # ---- phase 0: spin-up ----------------------------------------------
    t0 = time.monotonic()
    scaler.scale_up(replicas)
    if not wait_until(all_loaded, "initial %d replicas" % replicas):
        _fleet_report(
            {}, phases, gate_failures, replicas, qps, 0, [], [],
        )
        return 1
    phases["spinup_secs"] = round(time.monotonic() - t0, 1)

    # the control loop starts AFTER manual placement so the
    # autoscaler's floor check can't race the first spawn
    stop_ticks = threading.Event()

    def ticker():
        while not stop_ticks.is_set():
            time.sleep(0.5)
            try:
                servicer.tick()
                scaler.reap()
                autoscaler.tick()
            except Exception:
                pass

    tick_thread = threading.Thread(target=ticker, daemon=True)
    tick_thread.start()

    # ---- warm every replica's compiled forward -------------------------
    client = ServeClient("localhost:%d" % rport)
    warm_ids = np.ones((_ROWS_PER_REQUEST, _FIELDS), np.int64)
    for key in range(replicas * 8):
        client.predict(
            {"ids": warm_ids}, deadline_secs=max(180.0, deadline_secs),
            affinity_key=key,
        )

    # ---- continuous open-loop load -------------------------------------
    rng = np.random.RandomState(42)
    stop_load = threading.Event()
    failures = []
    latencies = []
    book_lock = threading.Lock()
    outstanding = [0]
    total_sent = [0]

    def fire(i, ids):
        start = time.perf_counter()
        try:
            client.predict(
                {"ids": ids}, deadline_secs=deadline_secs,
                affinity_key=i % 509,
            )
            with book_lock:
                latencies.append(time.perf_counter() - start)
        except Exception as e:  # the hard gate counts every failure
            with book_lock:
                failures.append((i, repr(e)))
        finally:
            with book_lock:
                outstanding[0] -= 1

    def generator():
        interval = 1.0 / qps
        i = 0
        next_t = time.monotonic()
        while not stop_load.is_set():
            now = time.monotonic()
            if now < next_t:
                time.sleep(min(0.05, next_t - now))
                continue
            next_t += interval
            ids = _zipf_ids(rng)
            with book_lock:
                outstanding[0] += 1
            threading.Thread(
                target=fire, args=(i, ids), daemon=True
            ).start()
            i += 1
        total_sent[0] = i

    load_start = time.monotonic()
    load_thread = threading.Thread(target=generator, daemon=True)
    load_thread.start()
    time.sleep(3.0)

    # ---- phase A: SIGKILL one replica mid-traffic ----------------------
    victim = sorted(servicer.registry.routable_ids())[0]
    victim_pid = int(victim.rsplit("-", 1)[1])
    tA = time.monotonic()
    scaler.kill(victim_pid, sig=signal.SIGKILL)
    ok = wait_until(
        lambda: (
            victim not in servicer.registry.live_ids() and all_loaded()
        ),
        "below-floor replacement after SIGKILL of %s" % victim,
    )
    if ok:
        phases["replace_secs"] = round(time.monotonic() - tA, 1)

    # ---- phase B: healthy v2 export -> canary promote ------------------
    v2_stamp = None
    if ok:
        _advance_training(executor, steps=3)
        export_train_state(executor.state, os.path.join(root, "v00002"))
        v2_stamp = export_signature(os.path.join(root, "v00002"))
        tB = time.monotonic()
        ok = wait_until(
            lambda: (
                servicer.state()["canary"]["incumbent"]["stamp"]
                == v2_stamp
            ),
            "canary promote of v00002",
        )
        if ok:
            phases["promote_secs"] = round(time.monotonic() - tB, 1)

    # ---- phase C: poisoned v3 export -> forced rollback ----------------
    v3_stamp = None
    if ok:
        _advance_training(executor, steps=2)
        staging = os.path.join(tmp, "staging-v00003")
        export_train_state(executor.state, staging)
        _poison_bundle(staging)
        # atomic publish: replicas scan the root every heartbeat and
        # must never see the pre-poison bundle under this name
        os.rename(staging, os.path.join(root, "v00003"))
        v3_stamp = export_signature(os.path.join(root, "v00003"))
        tC = time.monotonic()
        ok = wait_until(
            lambda: (
                v3_stamp in servicer.state()["canary"]["rejected"]
            ),
            "canary rollback of poisoned v00003",
        )
        if ok:
            phases["rollback_secs"] = round(time.monotonic() - tC, 1)
            # the members must land back on the incumbent
            wait_until(
                lambda: all(
                    v["loaded_stamp"] == v2_stamp
                    for v in servicer.registry.state().values()
                    if not v["draining"]
                ),
                "canary members reloading the incumbent",
                timeout=max(300.0, watchdog / 3),
            )

    # ---- wind down -----------------------------------------------------
    stop_load.set()
    load_thread.join(timeout=10)
    drain_deadline = time.monotonic() + deadline_secs + 30
    while time.monotonic() < drain_deadline:
        with book_lock:
            if outstanding[0] <= 0:
                break
        time.sleep(0.25)
    else:
        gate_failures.append(
            "%d requests still in flight at wind-down" % outstanding[0]
        )
    wall = time.monotonic() - load_start
    stop_ticks.set()
    tick_thread.join(timeout=5)
    final_state = servicer.state()
    client.close()
    server.stop(0)
    scaler.stop_all()
    ps.terminate()
    ps.wait(timeout=30)
    events.flush()

    # ---- journal gates: every decision explained -----------------------
    journal = load_journal(events_dir)
    lost = [
        e for e in journal
        if e["event"] == "replica_lost" and e.get("replica") == victim
    ]
    grows = [
        e for e in journal
        if e["event"] == "scale_decision"
        and e.get("tag") == "serve" and e.get("direction") == "grow"
    ]
    promoted = [
        e for e in journal
        if e["event"] == "canary_promoted" and e.get("export") == "v00002"
    ]
    rolled_back = [
        e for e in journal
        if e["event"] == "canary_rolled_back"
        and e.get("export") == "v00003"
    ]
    if not lost:
        gate_failures.append(
            "SIGKILLed replica %s never journaled replica_lost" % victim
        )
    if not any(
        any(str(r).startswith("below_floor") for r in e.get("reasons", []))
        for e in grows
    ):
        gate_failures.append(
            "no below_floor scale_decision journaled for the replacement"
        )
    if v2_stamp and not (promoted and promoted[0].get("reasons")):
        gate_failures.append(
            "canary_promoted for v00002 missing (or carries no reasons)"
        )
    if v3_stamp and not (rolled_back and rolled_back[0].get("reasons")):
        gate_failures.append(
            "canary_rolled_back for v00003 missing (or carries no "
            "reasons)"
        )
    if failures:
        gate_failures.append(
            "%d client requests FAILED across the run (first: %s) — "
            "the fleet must hold zero failures through kill, promote "
            "and rollback" % (len(failures), failures[0][1])
        )

    report = _fleet_report(
        final_state, phases, gate_failures, replicas, qps,
        total_sent[0], latencies, failures, wall=wall,
        promoted=promoted, rolled_back=rolled_back, grows=grows,
    )
    return 1 if gate_failures else 0


def _fleet_report(state, phases, gate_failures, replicas, qps, total,
                  latencies, failures, wall=None, promoted=(),
                  rolled_back=(), grows=()):
    p50_ms, p99_ms = _percentiles(latencies)
    report = {
        "mode": "fleet",
        "replicas": replicas,
        "qps_target": qps,
        "qps_achieved": (
            round(len(latencies) / wall, 1) if wall else None
        ),
        "requests": total,
        "served": len(latencies),
        "failed": len(failures),
        "p50_ms": p50_ms,
        "p99_ms": p99_ms,
        "phases": phases,
        "scale_decisions": len(grows),
        "canary": {
            "promoted": [e.get("stamp") for e in promoted],
            "rolled_back": [e.get("stamp") for e in rolled_back],
            "final": (state or {}).get("canary", {}).get("incumbent"),
        },
    }
    print(json.dumps(report))
    if gate_failures:
        print("BENCH GATE FAILED:", file=sys.stderr)
        for reason in gate_failures:
            print("  - %s" % reason, file=sys.stderr)
    return report


def main():
    parser = argparse.ArgumentParser("bench_serving")
    parser.add_argument(
        "--router", action="store_true",
        help="fleet mode: router + --replicas serve subprocesses over "
        "a real PS and a versioned export root (ISSUE 17)",
    )
    parser.add_argument(
        "--replicas", type=int, default=4,
        help="fleet size for --router (the ISSUE 17 acceptance floor "
        "is 4)",
    )
    args = parser.parse_args()
    if args.router:
        return run_fleet(max(2, args.replicas))
    return run_single()


if __name__ == "__main__":
    sys.exit(main())
