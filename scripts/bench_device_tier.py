"""Fast device-tier A-B for CI tier 1f (ISSUE 6).

DeepFM CTR steps/s with the device-resident embedding tier on vs off
over a synthetic Zipfian id stream, against an in-process PS whose
pull/push/writeback legs charge an EMULATED per-row wire cost
(default 2 us/row + 1 ms/call, the ballpark of the PR 5 measured
deepfm wire path: ~20 steps/s at ~10k rows/step each way). Without
the emulation an in-process A-B is a strawman — there is no gRPC wire
to skip, which is the entire point of the tier — while spawning live
PS processes is too slow for a CI smoke (that comparison lives in
bench.py's deepfm A-B).

Absolute numbers are REPORT-ONLY (journaled by scripts/ci.sh, never
gated — timings flake across boxes); the script hard-fails only when

- the tier-on run measures >3x SLOWER than tier-off in the same run
  (a real fast-path regression, not noise — the wire-micro lane's
  discipline; with the wire model the tier normally WINS, so 3x has
  wide margin), or
- the warm-phase hit rate falls below 0.9 on the Zipfian stream (the
  ISSUE 6 acceptance bound: promotion/demotion stopped keeping the
  hot set resident), or
- the tier run's flushed rows diverge from the PS store (writeback
  correctness, not perf).
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

PER_ROW_SECS = 2e-6
PER_CALL_SECS = 1e-3


class WireCostClient:
    """LocalPSClient proxy charging the emulated wire cost per leg.

    Every row crossing the emulated wire — pulled, pushed, or written
    back — pays ``per_row``; every RPC-shaped call pays ``per_call``.
    The tier's writebacks pay like everything else: its win must come
    from hit rows genuinely skipping the wire, not from an accounting
    hole."""

    def __init__(self, inner, per_row=PER_ROW_SECS,
                 per_call=PER_CALL_SECS):
        self._inner = inner
        self._per_row = per_row
        self._per_call = per_call
        self.store = inner.store

    @property
    def ps_num(self):
        return self._inner.ps_num

    def _charge(self, rows):
        time.sleep(self._per_call + self._per_row * rows)

    def push_embedding_table_infos(self, infos):
        return self._inner.push_embedding_table_infos(infos)

    def push_dense_init(self, params, version=0):
        return self._inner.push_dense_init(params, version)

    def pull_dense_init(self, version=-1):
        return self._inner.pull_dense_init(version)

    def pull_embedding_vectors(self, name, ids):
        self._charge(np.asarray(ids).size)
        return self._inner.pull_embedding_vectors(name, ids)

    def pull_embedding_batch(self, ids_by_table):
        self._charge(sum(
            np.asarray(ids).size for ids in ids_by_table.values()
        ))
        return self._inner.pull_embedding_batch(ids_by_table)

    def push_gradients(self, grads_by_table, **kwargs):
        self._charge(sum(
            np.asarray(ids).size
            for _, ids in grads_by_table.values()
        ))
        return self._inner.push_gradients(grads_by_table, **kwargs)

    def push_embedding_rows(self, rows_by_table):
        self._charge(sum(
            np.asarray(ids).size
            for ids, _ in rows_by_table.values()
        ))
        return self._inner.push_embedding_rows(rows_by_table)


def make_batches(n, batch=512, fields=16, vocab=10_000, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        # Zipf over a BOUNDED vocab (the %-fold wraps the tail back
        # onto the universe): the whole working set fits the 32k-row
        # tier, so the warm-phase hit rate measures whether the
        # promotion policy actually captured it (>= 0.9 bound below).
        # An unbounded tail would cap unique-id hit rate around the
        # singleton fraction regardless of policy — hit rate counts
        # unique ids, the deduped rows that actually cross the wire.
        ids = (rng.zipf(1.3, size=(batch, fields)) % vocab).astype(
            np.int64
        )
        out.append({
            "features": {"ids": ids},
            "labels": rng.randint(0, 2, batch).astype(np.float32),
            "_mask": np.ones(batch, np.float32),
        })
    return out


def run(device_tier, batches, warmup=10):
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.ps.local_client import LocalPSClient
    from elasticdl_tpu.train.sparse import SparseTrainer

    trainer = SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(
            num_features=16, batch_size=256
        ),
        ps_client=WireCostClient(
            LocalPSClient(seed=0, opt_type="adam", lr=0.001)
        ),
        seed=0,
        device_tier=device_tier,
    )
    state = None
    start = None
    for i, batch in enumerate(batches):
        state, loss = trainer.train_step(state, batch)
        if i + 1 == warmup:
            float(loss)
            if trainer.device_tier is not None:
                # measure the warm phase: cold-start promotion misses
                # are start-up cost, not steady-state hit rate
                trainer.device_tier.hits = 0
                trainer.device_tier.misses = 0
            start = time.perf_counter()
    elapsed = time.perf_counter() - start
    steps_per_sec = (len(batches) - warmup) / elapsed
    stats = None
    if trainer.device_tier is not None:
        stats = trainer.device_tier.stats()
        trainer.flush_device_tier()
        store = trainer.preparer._ps.store
        for table in ("deepfm_emb", "deepfm_linear"):
            ids, rows = trainer.device_tier.table_rows(table)
            if ids.size and not np.allclose(
                rows, store.lookup(table, ids), rtol=1e-5, atol=1e-6
            ):
                print(
                    "bench_device_tier: FAIL %s flush parity" % table,
                    file=sys.stderr,
                )
                sys.exit(1)
    trainer.close()
    return steps_per_sec, stats


def main():
    from elasticdl_tpu.train.device_tier import DeviceTierConfig

    batches = make_batches(45)
    tier_off, _ = run(False, batches, warmup=15)
    config = DeviceTierConfig(
        capacity=32768, promote_hits=1, ttl=4096, stage_budget=2048,
        opt_type="adam", opt_args={"lr": 0.001}, writeback_steps=256,
    )
    tier_on, stats = run(config, batches, warmup=15)
    result = {
        "deepfm_ctr_steps_per_sec_device_tier": round(tier_on, 3),
        "deepfm_ctr_steps_per_sec_tier_off": round(tier_off, 3),
        "device_tier_speedup": round(tier_on / tier_off, 3),
        "deepfm_device_tier_hit_rate": round(stats["hit_rate"], 4),
        "device_tier_occupancy": round(stats["occupancy"], 4),
        "device_tier_evictions": stats["evictions"],
        "emulated_wire_us_per_row": PER_ROW_SECS * 1e6,
    }
    print(json.dumps(result))
    if tier_on * 3.0 < tier_off:
        print(
            "bench_device_tier: FAIL tier-on (%.2f steps/s) is >3x "
            "slower than tier-off (%.2f)" % (tier_on, tier_off),
            file=sys.stderr,
        )
        sys.exit(1)
    if stats["hit_rate"] < 0.9:
        print(
            "bench_device_tier: FAIL warm hit rate %.3f < 0.9 on a "
            "Zipfian stream — promotion/demotion policy regression"
            % stats["hit_rate"],
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
