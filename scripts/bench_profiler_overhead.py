#!/usr/bin/env python
"""Profiler overhead gate (ISSUE 14): deepfm steps/s, sampler on vs off.

The continuous profiler's contract is "always-on costs nothing you can
measure": at the default 29 Hz its steps/s cost on the deepfm
local-executor workload must stay within 3%. This bench runs the A/B
inside ONE process and ONE trainer (same compiled step, same store,
same box thermals): after a warmup, alternating measurement segments
run with the sampler stopped and started (via the real
``EDL_PROF_HZ``/``maybe_start`` path), and the gate compares the
medians — interleaving cancels the slow drift (page cache, turbo
clocks) that poisons sequential A/Bs.

Absolute steps/s are REPORT-ONLY (journaled by ci.sh tier 1f like
every bench); the script hard-fails only the acceptance gate:
measured overhead above 3% (with one full re-measure first — a single
GC pause or CI-box neighbor can eat 3% on its own; a REAL sampler
regression fails both passes), or a sampler that collected no samples
at all (the A/B would be vacuous).
"""

import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, ".")

PROF_HZ = 29.0
GATE = 0.03
WARMUP_STEPS = 12
DISTINCT_BATCHES = 30
# long enough that each segment spans many 29 Hz ticks AND many GIL
# switch quanta on a fast box — sub-100ms segments measure noise
SEGMENT_STEPS = 150
SEGMENTS_PER_MODE = 3


def make_batches(n, batch=256, fields=16, vocab=10_000, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = (rng.zipf(1.3, size=(batch, fields)) % vocab).astype(
            np.int64
        )
        out.append({
            "features": {"ids": ids},
            "labels": rng.randint(0, 2, batch).astype(np.float32),
            "_mask": np.ones(batch, np.float32),
        })
    return out


def build_trainer():
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.ps.local_client import LocalPSClient
    from elasticdl_tpu.train.sparse import SparseTrainer

    return SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(
            num_features=16, batch_size=256
        ),
        ps_client=LocalPSClient(seed=0, opt_type="adam", lr=0.001),
        seed=0,
    )


def run_segment(trainer, state, batches):
    start = time.perf_counter()
    for step in range(SEGMENT_STEPS):
        state, loss = trainer.train_step(state, batches[step % len(batches)])
    float(loss)  # join any async device work before stopping the clock
    elapsed = time.perf_counter() - start
    return state, SEGMENT_STEPS / elapsed


def measure(trainer, state, batches):
    """Interleaved off/on segments; returns (off median, on median,
    samples taken while on). Pair order ALTERNATES (off-on, on-off,
    off-on, ...): a box that monotonically warms up or cools down over
    the run would otherwise hand the consistent second position a
    systematic edge that reads as fake overhead (or fake speedup)."""
    from elasticdl_tpu.observability import profiler

    off = []
    on = []
    samples = 0

    def run_off():
        nonlocal state
        profiler.stop()
        state, sps = run_segment(trainer, state, batches)
        off.append(sps)

    def run_on():
        nonlocal state, samples
        sampler = profiler.maybe_start("bench")
        assert sampler is not None, (
            "EDL_PROF_HZ did not enable the sampler"
        )
        state, sps = run_segment(trainer, state, batches)
        samples += sampler.snapshot()["samples"]
        profiler.stop()
        on.append(sps)

    for pair in range(SEGMENTS_PER_MODE):
        if pair % 2 == 0:
            run_off()
            run_on()
        else:
            run_on()
            run_off()
    return state, statistics.median(off), statistics.median(on), samples


def main():
    os.environ["EDL_PROF_HZ"] = str(PROF_HZ)
    from elasticdl_tpu.observability import profiler

    profiler.stop()  # measure from a known-off state
    trainer = build_trainer()
    batches = make_batches(DISTINCT_BATCHES)
    state = None
    for batch in batches[:WARMUP_STEPS]:
        state, loss = trainer.train_step(state, batch)
    float(loss)

    state, off_sps, on_sps, samples = measure(trainer, state, batches)
    overhead = 1.0 - on_sps / off_sps
    if overhead > GATE:
        # one re-measure before failing: a GC pause or noisy neighbor
        # can eat 3% in a single pass; a real regression repeats
        state, off2, on2, samples2 = measure(trainer, state, batches)
        if 1.0 - on2 / off2 < overhead:
            off_sps, on_sps, samples = off2, on2, samples2
            overhead = 1.0 - on2 / off2
    trainer.close()

    result = {
        "deepfm_profiler_overhead_ratio": round(overhead, 4),
        "deepfm_steps_per_sec_prof_off": round(off_sps, 3),
        "deepfm_steps_per_sec_prof_on": round(on_sps, 3),
        "prof_hz": PROF_HZ,
        "prof_samples": samples,
    }
    print(json.dumps(result))
    if samples <= 0:
        print(
            "bench_profiler_overhead: FAIL sampler collected 0 samples "
            "— the A/B measured nothing",
            file=sys.stderr,
        )
        return 1
    if overhead > GATE:
        print(
            "bench_profiler_overhead: FAIL %.1f%% overhead at %g Hz "
            "exceeds the %.0f%% contract (off %.2f vs on %.2f steps/s)"
            % (overhead * 100, PROF_HZ, GATE * 100, off_sps, on_sps),
            file=sys.stderr,
        )
        return 1
    print(
        "profiler overhead %.2f%% at %g Hz (off %.2f, on %.2f steps/s)"
        % (overhead * 100, PROF_HZ, off_sps, on_sps),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
