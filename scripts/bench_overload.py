"""Overload-resilience drill for the training plane (ISSUE 19 gates).

Three real PS processes (plus one flapping one) run the SAME seeded
push workload; the drills measure what the overload machinery
(common/overload.py + grpc_utils.retry_call + ps/servicer admission
control) actually buys:

- PROTECTED: workers push through ``retry_call(target=...)`` against a
  PS whose applies are slow for the first ``--slow-secs`` (the
  ``overload`` fault kind) and whose admission boundary pushes back at
  ``--max-pending`` in-flight applies. Attempts per logical push
  during the slow window is the ATTEMPT AMPLIFICATION; the hard gate
  is ``<= --max-amplification`` (default 2x).
- BASELINE: the same workload storms an identically-faulted PS with
  the naive loop this layer replaces — retry immediately on any
  failure, ignore the server's retry-after hint. Reported next to the
  protected number; this is the amplification an unprotected fleet
  would inflict.
- CLEAN: the same workload against a fault-free PS. Because every
  worker owns a disjoint id range (per-row update order is then
  deterministic regardless of thread interleaving) and tables
  zero-init, the protected PS's post-recovery state must be BIT-EQUAL
  to this run's — the zero-lost-updates gate: admission rejects happen
  before apply, so a retried push is never double-applied.
- RECOVERY: pushes against a PS failing in call-count windows (the
  ``flap`` fault kind) must open the circuit breaker and re-close it
  via half-open probes; the gap between the last failed probe and the
  first success must fit inside the journaled probe window
  (``--reset-secs`` + ``--recovery-slack``).

Prints ONE JSON line; exit 1 on any gate failure unless
``--report-only``. PS startup dominates the short configurations — CI
runs this report-only with reduced ``--slow-secs``.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, ".")

from elasticdl_tpu.common import overload  # noqa: E402
from elasticdl_tpu.common.grpc_utils import (  # noqa: E402
    build_channel,
    find_free_port,
    retry_call,
)
from elasticdl_tpu.common.tensor_utils import (  # noqa: E402
    deduplicate_indexed_slices,
    pack_ids,
    serialize_indexed_slices,
)
from elasticdl_tpu.observability import events  # noqa: E402
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb  # noqa: E402
from elasticdl_tpu.proto.services import PserverStub  # noqa: E402

import grpc  # noqa: E402

TABLE = "emb"
CIRCUIT_FAILURES = 3
FLAP_WINDOW_CALLS = 5   # calls 1-5 fail, 6-10 pass, ...
FLAP_PUSHES = 4         # stays inside the first passing window

_STORM_RETRY = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
)


def start_ps(port, seed, extra_env):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **extra_env}
    return subprocess.Popen(
        [
            sys.executable, "-m", "elasticdl_tpu.ps.server",
            "--ps_id", "0", "--num_ps_pods", "1", "--port", str(port),
            "--opt_type", "adam", "--opt_args", "lr=0.01",
            "--use_async", "1", "--seed", str(seed),
        ],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def wait_port(port, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port))
            return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError("ps on port %d never came up" % port)


def make_workload(threads, pushes, rows, dim):
    """Per-thread push sequences over DISJOINT id ranges: per-row
    update order is then each owner thread's serial order, so the
    final store state is independent of cross-thread interleaving —
    the property the bit-equality gate rests on."""
    work = []
    for t in range(threads):
        rng = np.random.RandomState(7000 + t)
        base = t * 10_000_000
        seq = []
        for _ in range(pushes):
            ids = base + rng.randint(0, 2048, size=rows).astype(np.int64)
            grads = rng.randn(rows, dim).astype(np.float32)
            values, ids = deduplicate_indexed_slices(grads, ids)
            seq.append((ids, values))
        work.append(seq)
    return work


def push_request(ids, values):
    request = pb.PushGradientsRequest()
    request.gradients.version = 0
    serialize_indexed_slices(
        values, ids, request.gradients.embedding_tables[TABLE],
        packed=True,
    )
    return request


def create_table(stub, dim):
    request = pb.Model()
    # zeros: row init must not depend on first-touch order (a
    # sequential RNG stream would break cross-run bit-equality)
    request.embedding_table_infos.add(
        name=TABLE, dim=dim, initializer="zeros"
    )
    stub.push_embedding_table_infos(request, timeout=60)


def run_protected(addr, work, dim, budget_secs=300.0):
    channel = build_channel(addr)
    stub = PserverStub(channel)
    create_table(stub, dim)
    records = []
    lock = threading.Lock()
    barrier = threading.Barrier(len(work))

    def runner(seq):
        barrier.wait()
        for ids, values in seq:
            request = push_request(ids, values)
            rec = {"start": time.monotonic(), "attempts": 0}

            def attempt(request=request, rec=rec):
                rec["attempts"] += 1
                return stub.push_gradients(
                    request, timeout=overload.rpc_timeout(60.0)
                )

            retry_call(
                attempt, "bench push", budget_secs=budget_secs,
                channel=channel, target=addr,
            )
            with lock:
                records.append(rec)

    threads = [
        threading.Thread(target=runner, args=(seq,)) for seq in work
    ]
    start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return records, start, time.monotonic() - start, channel


def run_baseline(addr, work, dim, window_secs):
    """The unbounded-retry client the overload plane replaces: retry
    every failure immediately-ish, ignore the server's pacing hint.
    Runs for the slow window only — it measures amplification, not
    completion."""
    channel = build_channel(addr)
    stub = PserverStub(channel)
    create_table(stub, dim)
    counts = {"attempts": 0, "successes": 0}
    lock = threading.Lock()
    barrier = threading.Barrier(len(work))
    box = {}

    def runner(seq):
        barrier.wait()
        stop_at = box["stop_at"]
        i = 0
        attempts = successes = 0
        while time.monotonic() < stop_at:
            ids, values = seq[i % len(seq)]
            request = push_request(ids, values)
            while time.monotonic() < stop_at:
                attempts += 1
                try:
                    stub.push_gradients(request, timeout=60)
                    successes += 1
                    i += 1
                    break
                except grpc.RpcError as e:
                    if e.code() not in _STORM_RETRY:
                        raise
                    time.sleep(0.01)
        with lock:
            counts["attempts"] += attempts
            counts["successes"] += successes

    threads = [
        threading.Thread(target=runner, args=(seq,)) for seq in work
    ]
    box["stop_at"] = time.monotonic() + window_secs
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    channel.close()
    return counts


def run_recovery(addr, seq, dim, reset_secs):
    """Serial pushes against the flapping PS: the first push rides
    through breaker open -> half-open probes -> close; the follow-ups
    land in the passing window."""
    channel = build_channel(addr)
    stub = PserverStub(channel)
    create_table(stub, dim)
    timeline = []

    for ids, values in seq[:FLAP_PUSHES]:
        request = push_request(ids, values)

        def attempt(request=request):
            try:
                response = stub.push_gradients(
                    request, timeout=overload.rpc_timeout(60.0)
                )
            except grpc.RpcError:
                timeline.append((time.monotonic(), False))
                raise
            timeline.append((time.monotonic(), True))
            return response

        retry_call(
            attempt, "bench push", budget_secs=60.0, channel=channel,
            # keep jitter draws below the probe window so the measured
            # recovery is the breaker's pacing, not backoff noise
            base_delay=0.2, max_delay=0.25, target=addr,
        )
    channel.close()

    failures = [t for t, ok in timeline if not ok]
    successes = [t for t, ok in timeline if ok]
    recovery = None
    if failures:
        after = [t for t in successes if t > failures[-1]]
        if after:
            recovery = after[0] - failures[-1]
    breaker = overload.breaker_for(addr, "write")
    return {
        "attempts": len(timeline),
        "failed_attempts": len(failures),
        "recovery_secs": None if recovery is None else round(recovery, 3),
        "breaker_open_count": breaker.open_count,
        "breaker_final_state": breaker.state(),
    }


def pull_state(stub, work):
    """Every pushed row, pulled per owner thread; returns the raw wire
    bytes for bitwise comparison."""
    blobs = []
    for seq in work:
        ids = np.unique(np.concatenate([ids for ids, _ in seq]))
        request = pb.PullEmbeddingVectorsRequest(
            name=TABLE, ids_blob=pack_ids(ids)
        )
        blob = stub.pull_embedding_vectors(request, timeout=120)
        blobs.append((blob.dtype, blob.content))
    return blobs


def journal_counts(events_dir):
    counts = {}
    for fname in os.listdir(events_dir):
        if not fname.endswith(".events.ndjson"):
            continue
        with open(os.path.join(events_dir, fname)) as f:
            for line in f:
                try:
                    event = json.loads(line).get("event")
                except ValueError:
                    continue
                counts[event] = counts.get(event, 0) + 1
    return counts


def main():
    parser = argparse.ArgumentParser(__doc__)
    parser.add_argument("--threads", type=int, default=6)
    parser.add_argument("--pushes", type=int, default=20,
                        help="logical pushes per thread")
    parser.add_argument("--rows", type=int, default=256)
    parser.add_argument("--dim", type=int, default=8)
    parser.add_argument("--slow-secs", type=float, default=10.0,
                        help="target wall length of the slow-apply "
                             "window")
    parser.add_argument("--apply-lat", type=float, default=0.5,
                        help="injected seconds per apply in the window")
    parser.add_argument("--max-pending", type=float, default=4,
                        help="EDL_PS_MAX_PENDING_APPLIES on the "
                             "faulted PS processes")
    parser.add_argument("--reset-secs", type=float, default=1.0,
                        help="EDL_CIRCUIT_RESET_SECS for the recovery "
                             "drill")
    parser.add_argument("--max-amplification", type=float, default=2.0,
                        help="hard ceiling on protected attempts per "
                             "push in the slow window (0 disables)")
    parser.add_argument("--recovery-slack", type=float, default=1.0,
                        help="allowed recovery beyond the probe window")
    parser.add_argument("--report-only", action="store_true",
                        help="print the report but never exit nonzero")
    args = parser.parse_args()

    max_pending = int(args.max_pending)
    # client-side knobs, set before any breaker/bucket is built. The
    # retry-token bucket is provisioned out of the way: pushback
    # retries spend tokens, and THIS drill measures pacing and
    # exactly-once, not budget exhaustion (tests/test_grpc_utils.py
    # covers that edge directly).
    os.environ["EDL_CIRCUIT_FAILURES"] = str(CIRCUIT_FAILURES)
    os.environ["EDL_CIRCUIT_RESET_SECS"] = "%g" % args.reset_secs
    os.environ["EDL_RETRY_BUDGET_TOKENS"] = "100000"
    events_dir = tempfile.mkdtemp(prefix="bench_overload_events_")
    os.environ["EDL_EVENTS_DIR"] = events_dir
    events.configure("bench-overload")

    # the slow window is expressed in admitted-apply counts: with
    # max_pending applies in flight at apply_lat each, `bound` slow
    # applies take ~slow_secs of wall clock under saturation
    bound = max(1, int(args.slow_secs * max_pending / args.apply_lat))
    overload_spec = "ps-0:push_gradients:overload:%g:%d" % (
        args.apply_lat, bound
    )
    flap_spec = "ps-0:push_gradients:flap:%d" % FLAP_WINDOW_CALLS
    faulted_env = {
        "EDL_FAULT_SPEC": overload_spec,
        "EDL_PS_MAX_PENDING_APPLIES": str(max_pending),
    }
    ports = {name: find_free_port() for name in
             ("protected", "baseline", "clean", "flap")}
    procs = {
        "protected": start_ps(ports["protected"], 7, faulted_env),
        "baseline": start_ps(ports["baseline"], 7, faulted_env),
        "clean": start_ps(ports["clean"], 7, {
            "EDL_FAULT_SPEC": "",
            "EDL_PS_MAX_PENDING_APPLIES": str(max_pending),
        }),
        "flap": start_ps(ports["flap"], 7, {
            "EDL_FAULT_SPEC": flap_spec,
        }),
    }
    addr = {name: "localhost:%d" % port for name, port in ports.items()}

    work = make_workload(args.threads, args.pushes, args.rows, args.dim)
    try:
        for port in ports.values():
            wait_port(port)

        stats_before = overload.client_stats()
        records, start, protected_secs, protected_channel = run_protected(
            addr["protected"], work, args.dim
        )
        stats_after = overload.client_stats()

        window = [r for r in records
                  if r["start"] - start < args.slow_secs] or records
        window_attempts = sum(r["attempts"] for r in window)
        window_amp = window_attempts / float(len(window))
        overall_amp = (
            sum(r["attempts"] for r in records) / float(len(records))
        )

        baseline = run_baseline(
            addr["baseline"], work, args.dim, args.slow_secs
        )
        baseline_amp = (
            baseline["attempts"] / float(baseline["successes"])
            if baseline["successes"] else None
        )

        _, _, clean_secs, clean_channel = run_protected(
            addr["clean"], work, args.dim
        )
        protected_state = pull_state(PserverStub(protected_channel), work)
        clean_state = pull_state(PserverStub(clean_channel), work)
        bit_equal = protected_state == clean_state
        protected_channel.close()
        clean_channel.close()

        recovery = run_recovery(
            addr["flap"], work[0], args.dim, args.reset_secs
        )
    finally:
        for proc in procs.values():
            proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()

    journal = journal_counts(events_dir)
    gates = {
        "attempt_amplification": (
            args.max_amplification <= 0
            or window_amp <= args.max_amplification
        ),
        "zero_lost_updates": bit_equal,
        "recovery_in_probe_window": (
            recovery["recovery_secs"] is not None
            and recovery["recovery_secs"]
            <= args.reset_secs + args.recovery_slack
            and recovery["breaker_final_state"] == overload.CLOSED
            and recovery["breaker_open_count"] >= 1
        ),
    }
    out = {
        "threads": args.threads,
        "pushes_per_thread": args.pushes,
        "rows": args.rows,
        "dim": args.dim,
        "slow_secs": args.slow_secs,
        "apply_lat": args.apply_lat,
        "max_pending": max_pending,
        "protected": {
            "elapsed_secs": round(protected_secs, 2),
            "window_pushes": len(window),
            "window_attempts": window_attempts,
            "window_amplification": round(window_amp, 3),
            "overall_amplification": round(overall_amp, 3),
            "pushback_waits": (
                stats_after["pushback_waits"]
                - stats_before["pushback_waits"]
            ),
            "retry_budget_exhausted": (
                stats_after["retry_budget_exhausted"]
                - stats_before["retry_budget_exhausted"]
            ),
        },
        "baseline": {
            "window_attempts": baseline["attempts"],
            "window_successes": baseline["successes"],
            "amplification": (
                None if baseline_amp is None else round(baseline_amp, 2)
            ),
        },
        "clean_elapsed_secs": round(clean_secs, 2),
        "state_bit_equal": bit_equal,
        "recovery": dict(recovery, reset_secs=args.reset_secs),
        "journal": {k: journal.get(k, 0) for k in (
            "circuit_open", "circuit_half_open", "circuit_closed",
            "ps_overload_enter", "ps_overload_clear",
        )},
        "gates": gates,
    }
    print(json.dumps(out))
    if not all(gates.values()) and not args.report_only:
        print("FAIL: gates %s" % {k: v for k, v in gates.items() if not v},
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
