#!/usr/bin/env python
"""Span-id entropy A/B (ISSUE 15 satellite): buffered pool vs
per-call os.urandom.

PR 14's continuous profiler measured ``trace:_new_span_id`` — one
``os.urandom`` syscall per span — at ~5-7% of traced-run host samples.
The fix (``trace._EntropyPool``) refills 4 KiB under a lock and deals
8/16-byte slices, amortizing the syscall ~512x. This bench proves the
win with the same interleaved-A/B discipline as
``bench_profiler_overhead.py``: alternating segments generate span ids
through the pool and through a per-call ``os.urandom`` twin, pair
order alternating so box drift cancels.

Absolute rates are REPORT-ONLY (journaled by ci.sh tier 1f); the
script hard-fails only when the pooled path fails to BEAT the per-call
path (speedup < 1.0 after one re-measure) — the satellite's whole
point — or when pooled ids collide within a segment (the pool must
never deal the same bytes twice).
"""

import json
import statistics
import sys
import time

sys.path.insert(0, ".")

SEGMENT_IDS = 200_000
SEGMENTS_PER_MODE = 3


def urandom_segment():
    import os

    start = time.perf_counter()
    for _ in range(SEGMENT_IDS):
        os.urandom(8).hex()
    return SEGMENT_IDS / (time.perf_counter() - start)


def pooled_segment(check_unique=False):
    from elasticdl_tpu.observability.trace import _new_span_id

    seen = set() if check_unique else None
    start = time.perf_counter()
    for _ in range(SEGMENT_IDS):
        _new_span_id()
    rate = SEGMENT_IDS / (time.perf_counter() - start)
    if check_unique:
        # correctness spot-check outside the timed loop: a fresh run
        # of ids must not collide (the pool advances its cursor)
        seen = {_new_span_id() for _ in range(10_000)}
        assert len(seen) == 10_000, "entropy pool dealt duplicate ids"
    return rate


def measure():
    pooled = []
    urandom = []
    for pair in range(SEGMENTS_PER_MODE):
        if pair % 2 == 0:
            urandom.append(urandom_segment())
            pooled.append(pooled_segment())
        else:
            pooled.append(pooled_segment())
            urandom.append(urandom_segment())
    return statistics.median(urandom), statistics.median(pooled)


def main():
    pooled_segment(check_unique=True)  # warm + uniqueness check
    urandom_rate, pooled_rate = measure()
    speedup = pooled_rate / urandom_rate
    if speedup < 1.0:
        urandom2, pooled2 = measure()
        if pooled2 / urandom2 > speedup:
            urandom_rate, pooled_rate = urandom2, pooled2
            speedup = pooled_rate / urandom_rate
    result = {
        "span_id_pool_speedup": round(speedup, 3),
        "span_ids_per_sec_pooled": round(pooled_rate),
        "span_ids_per_sec_urandom": round(urandom_rate),
    }
    print(json.dumps(result))
    if speedup < 1.0:
        print(
            "bench_span_entropy: FAIL pooled span ids are SLOWER than "
            "per-call os.urandom (%.2fx) — the buffered-entropy "
            "satellite regressed" % speedup,
            file=sys.stderr,
        )
        return 1
    print(
        "span-id entropy pool %.2fx vs per-call os.urandom "
        "(%.0f vs %.0f ids/s)"
        % (speedup, pooled_rate, urandom_rate),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
