"""Measure TransformerLM training MFU on the real chip.

The evidence behind docs/PERF_TRANSFORMER.md (VERDICT r2 item 1: prove
>=50% MFU on a compute-bound workload). Runs the full train step —
forward, backward, AdamW update — under one jit'd lax.scan so the
wall-clock between dispatch and the fetched loss is pure device time
(immune to the axon tunnel's per-call latency; see
.claude/skills/verify/SKILL.md "Timing on the real chip").

Model FLOPs are counted exactly from the architecture (matmul FLOPs
only, causal attention halved, embedding gather excluded) — NOT from
the 6NT approximation — so remat recompute never inflates MFU.

Usage:
  python scripts/bench_transformer_mfu.py --d 2048 --layers 12 \
      --seq 2048 --batch 8 --remat dots [--profile /tmp/tlm_trace]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# v5e (TPU v5 lite): bf16 peak per chip.
PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v4": 275e12, "TPU v5p": 459e12}


def xla_memory_fields(compiled):
    """Best-effort XLA buffer-assignment sizes as a JSON-ready dict.

    Empty on backends whose compiled executables expose no memory
    analysis (some CPU/GPU jaxlib builds return None or raise).
    """
    try:
        ma = compiled.memory_analysis()
        return {
            "xla_args_gb": round(ma.argument_size_in_bytes / 1e9, 2),
            "xla_temp_gb": round(ma.temp_size_in_bytes / 1e9, 2),
            "xla_aliased_gb": round(ma.alias_size_in_bytes / 1e9, 2),
            # what the program needs resident: args + temps + outputs,
            # minus the donated-argument buffers outputs reuse
            "xla_peak_gb": round(
                (ma.argument_size_in_bytes + ma.temp_size_in_bytes
                 + ma.output_size_in_bytes - ma.alias_size_in_bytes)
                / 1e9, 2
            ),
        }
    except Exception:
        return {}


def xla_cost_flops(compiled, steps):
    """XLA's own cost_analysis() FLOPs for ONE step, or 0.0 where the
    backend exposes none. The compiled program runs ``steps`` scanned
    steps, so the program total divides down. This is the same number
    the ISSUE-18 device-obs layer feeds the worker's MFU gauge — the
    cross-check below keeps the hand count honest."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return 0.0
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return 0.0
    return float(cost.get("flops", 0.0)) / max(steps, 1)


def model_train_flops(d, layers, seq, batch, vocab, mlp_ratio=4):
    """Exact matmul FLOPs for one train step (fwd + bwd = 3x fwd)."""
    tokens = batch * seq
    # per layer: qkv (3 d^2) + out-proj (d^2) + mlp up/down
    # (2 * mlp_ratio * d^2)
    proj = 2 * tokens * ((4 + 2 * mlp_ratio) * d * d) * layers
    # attention: QK^T + PV, causal halves the score matrix
    attn = 2 * (2 * batch * seq * seq * d) * layers / 2
    head = 2 * tokens * d * vocab
    return 3 * (proj + attn + head)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--d", type=int, default=2048)
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--mlp_ratio", type=int, default=4)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument(
        "--remat", choices=["none", "full", "dots", "flash"],
        default="dots",
    )
    p.add_argument(
        "--attn", choices=["auto", "pallas", "xla"], default="pallas"
    )
    p.add_argument("--opt", default="AdamW")
    p.add_argument(
        "--grad_accum_steps", type=int, default=1,
        help="split the batch into k sequential microbatches "
             "(exact semantics, train/step_fns.py) — lifts the HBM "
             "ceiling: activations are materialized for batch/k rows "
             "at a time while the optimizer still sees the full-batch "
             "gradient",
    )
    p.add_argument("--profile", default=None, help="trace output dir")
    p.add_argument(
        "--compile_only", action="store_true",
        help="report XLA's buffer-assignment memory analysis without "
             "executing — documents WHY an over-HBM config cannot run",
    )
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    # The container's sitecustomize imports jax at interpreter start
    # with platforms "axon,cpu", so the env var alone cannot force a
    # backend — re-apply it here (same pattern as tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from elasticdl_tpu.models.transformer import TransformerLM
    from elasticdl_tpu.train.optimizers import create_optimizer
    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    model = TransformerLM(
        vocab_size=args.vocab,
        num_layers=args.layers,
        num_heads=args.heads,
        embed_dim=args.d,
        mlp_ratio=args.mlp_ratio,
        attention_impl=args.attn,
        remat=args.remat != "none",
        remat_policy=args.remat,
    )
    tx = create_optimizer(
        args.opt, learning_rate=3e-4, weight_decay=0.01
    )

    from elasticdl_tpu.models.transformer import loss as loss_fn

    train_step = make_train_step(
        model, loss_fn, tx, compute_dtype=jnp.bfloat16,
        grad_accum_steps=args.grad_accum_steps,
    )

    def run_steps(state, batch, n):
        def body(state, _):
            state, loss = train_step(state, batch)
            return state, loss

        return jax.lax.scan(body, state, None, length=n)

    run = jax.jit(run_steps, static_argnums=(2,), donate_argnums=(0,))

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, args.vocab, size=(args.batch, args.seq)), jnp.int32
    )
    batch = {
        "features": tokens,
        "labels": tokens,
        "_mask": jnp.ones((args.batch,), jnp.float32),
    }
    state = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )
    n_params = sum(
        x.size for x in jax.tree_util.tree_leaves(state.params)
    )

    # AOT compile so XLA's buffer-assignment peak is available even
    # where the runtime's memory_stats() is unsupported (the axon
    # tunnel returns {}): arguments + temps - aliased(donated) bounds
    # the peak HBM the program needs.
    t0 = time.perf_counter()
    compiled = run.lower(state, batch, args.steps).compile()
    config = {
        "d": args.d, "layers": args.layers, "heads": args.heads,
        "seq": args.seq, "batch": args.batch, "vocab": args.vocab,
        "remat": args.remat, "attn": args.attn, "opt": args.opt,
        "grad_accum_steps": args.grad_accum_steps,
    }
    if args.compile_only:
        print(json.dumps({
            "config": config,
            **xla_memory_fields(compiled),
        }))
        return
    state, losses = compiled(state, batch)
    float(losses[-1])
    compile_s = time.perf_counter() - t0
    run = compiled

    start = time.perf_counter()
    state, losses = run(state, batch)
    final_loss = float(losses[-1])
    elapsed = time.perf_counter() - start
    assert np.isfinite(final_loss), final_loss

    step_ms = elapsed / args.steps * 1e3
    flops = model_train_flops(
        args.d, args.layers, args.seq, args.batch, args.vocab,
        args.mlp_ratio,
    )
    kind = jax.devices()[0].device_kind
    peak = PEAK_FLOPS.get(kind, 197e12)
    mfu = flops / (elapsed / args.steps) / peak
    toks_per_sec = args.batch * args.seq / (elapsed / args.steps)

    mem = {}
    try:
        stats = jax.devices()[0].memory_stats() or {}
        if stats.get("peak_bytes_in_use"):
            mem["hbm_peak_gb"] = round(
                stats["peak_bytes_in_use"] / 1e9, 2
            )
    except Exception:
        pass
    mem.update(xla_memory_fields(compiled))

    # cost-model cross-check (ISSUE 18): XLA's own count of the
    # program actually compiled, beside the hand count. Disagreement
    # >10% means one of them is wrong — usually the hand count after
    # an architecture change (new attention kind, remat recompute the
    # hand count deliberately excludes showing up in XLA's total).
    xla_flops = xla_cost_flops(compiled, args.steps)
    if xla_flops:
        mem["xla_tflop_per_step"] = round(xla_flops / 1e12, 2)
        mem["xla_mfu"] = round(
            xla_flops / (elapsed / args.steps) / peak, 4
        )
        disagreement = abs(xla_flops - flops) / max(xla_flops, flops)
        mem["flops_disagreement"] = round(disagreement, 4)
        if disagreement > 0.10:
            print(
                "WARNING: hand-counted FLOPs (%.2f T) and XLA "
                "cost_analysis (%.2f T) disagree by %.0f%% — "
                "re-derive model_train_flops for this config"
                % (flops / 1e12, xla_flops / 1e12,
                   disagreement * 100),
                file=sys.stderr,
            )

    print(json.dumps({
        "config": config,
        "params_m": round(n_params / 1e6, 1),
        "device": kind,
        "peak_tflops": peak / 1e12,
        "model_tflop_per_step": round(flops / 1e12, 2),
        "step_ms": round(step_ms, 2),
        "tokens_per_sec": round(toks_per_sec, 1),
        "mfu": round(mfu, 4),
        "compile_s": round(compile_s, 1),
        **mem,
    }))

    if args.profile:
        from scripts.trace_summary import capture_trace

        def _once():
            _, traced_losses = run(state, batch)
            float(traced_losses[-1])

        capture_trace(_once, args.profile, args.steps)


if __name__ == "__main__":
    main()
