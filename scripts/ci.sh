#!/usr/bin/env bash
# One-command local reproduction of CI tiers 1-2
# (.github/workflows/ci.yml; reference pipeline: .travis.yml:30-98).
#
# Lanes (reference parity: the travis fast/slow tier split):
#   scripts/ci.sh        — fast lane: unit suite minus @slow (<5 min)
#   scripts/ci.sh full   — everything, incl. multi-minute live-process
#                          e2es (chaos, multi-worker sparse, convergence)
set -euo pipefail
cd "$(dirname "$0")/.."

LANE="${1:-fast}"

# Cached sanitizer probe (PR 16): g++ alone is not enough — libtsan/
# libasan ship separately on minimal images, so a tiny link probe
# answers whether each -fsanitize flag is usable. The answer is
# memoized in a cache file keyed by (flag, compiler version) — the
# same reasoning as tests/test_native_race.py's lru_cache probe, but
# persisted so repeat lanes on one box skip the compiler spawn
# entirely. Delete ${TMPDIR:-/tmp}/edl_sanitizer_probe_* after a
# toolchain change.
sanitizer_available() {
  local flag="$1" key cache tmp out=no
  key="$(printf '%s|%s' "$flag" "$(g++ --version 2>/dev/null | head -1)" \
    | cksum | cut -d' ' -f1)"
  cache="${TMPDIR:-/tmp}/edl_sanitizer_probe_${key}"
  if [ -f "$cache" ]; then
    cat "$cache"
    return
  fi
  tmp="$(mktemp -d)"
  echo 'int main() { return 0; }' > "$tmp/probe.cc"
  if command -v g++ >/dev/null 2>&1 \
    && g++ "$flag" -o "$tmp/probe" "$tmp/probe.cc" 2>/dev/null; then
    out=yes
  fi
  rm -rf "$tmp"
  echo "$out" | tee "$cache"
}

echo "== tier 1a: native store build + TSAN/ASan race stress =="
make -C elasticdl_tpu/native
# the stress binaries run only where the toolchain can link them; the
# outcome (pass/fail/skip per sanitizer) is carried into the final
# summary line so a lane that silently skipped is visible in the log
TSAN_STATUS=skip
ASAN_STATUS=skip
if [ "$(sanitizer_available -fsanitize=thread)" = yes ]; then
  if make -C elasticdl_tpu/native tsan; then
    TSAN_STATUS=pass
  else
    TSAN_STATUS=fail
  fi
else
  echo "tsan stress skipped: toolchain cannot link -fsanitize=thread"
fi
if [ "$(sanitizer_available -fsanitize=address,undefined)" = yes ]; then
  if make -C elasticdl_tpu/native asan; then
    ASAN_STATUS=pass
  else
    ASAN_STATUS=fail
  fi
else
  echo "asan stress skipped: toolchain cannot link -fsanitize=address,undefined"
fi
if [ "$TSAN_STATUS" = fail ] || [ "$ASAN_STATUS" = fail ]; then
  echo "tier 1a sanitizer stress FAILED (tsan: $TSAN_STATUS, asan: $ASAN_STATUS)"
  exit 1
fi
# store-parity gate (ISSUE 11): the suite must run against the .so
# just built above — native and numpy stores bit-identical across all
# optimizers x wire dtypes x duplicate streams, checkpoint interop
# both directions, loader ABI-drift fallback
JAX_PLATFORMS=cpu python -m pytest \
  tests/test_native_parity.py tests/test_embedding_store.py -q

echo "== tier 1c: edlint static analysis =="
# zero-findings gate (both lanes): new findings are fixed, suppressed
# with a comment, or baselined with a justification — never ignored.
# Also runs inside the fast suite as tests/test_static_analysis.py
# (-m lint selects just the gate).
python -m elasticdl_tpu.analysis elasticdl_tpu/

if [ "$LANE" = "full" ]; then
  echo "== tier 1b: FULL unit suite (8-virtual-device CPU mesh) =="
  python -m pytest tests/ -x -q
else
  echo "== tier 1b: fast-lane unit suite (pytest -m 'not slow') =="
  python -m pytest tests/ -x -q -m "not slow"
fi

echo "== tier 1d: observability smoke (/metrics over a local run) =="
# a local executor run with EDL_METRICS_PORT set must serve the core
# series in Prometheus text format (docs/OBSERVABILITY.md catalog)
JAX_PLATFORMS=cpu python - <<'PYEOF'
import sys, tempfile, urllib.request
sys.path.insert(0, "tests")
from test_utils import create_mnist_recordio
from elasticdl_tpu.common.grpc_utils import find_free_port
import os
port = find_free_port()
os.environ["EDL_METRICS_PORT"] = str(port)
from elasticdl_tpu.train.local_executor import LocalExecutor
with tempfile.TemporaryDirectory() as tmp:
    create_mnist_recordio(tmp + "/f0.rec", num_records=64, seed=0)
    executor = LocalExecutor(
        "elasticdl_tpu.models.mnist", training_data=tmp,
        minibatch_size=32, num_epochs=1,
    )
    executor.train()
    url = "http://localhost:%d/metrics" % executor.observability.port
    body = urllib.request.urlopen(url, timeout=5).read().decode()
    for series in (
        'edl_up{role="local"} 1',
        "edl_step_time_seconds",
        'edl_phase_seconds_count{phase="batch_process"} 2',
    ):
        assert series in body, "missing series: %s" % series
    ready = urllib.request.urlopen(
        "http://localhost:%d/readyz" % executor.observability.port,
        timeout=5,
    )
    assert ready.status == 200
print("observability smoke OK")
PYEOF

echo "== tier 1d (tracing): distributed-trace smoke (merge + critical path) =="
# ISSUE 9: a deepfm local-executor run with EDL_TRACE_DIR + head
# sampling on must yield one trace per step whose worker root span has
# PS-side child spans linked via propagated context; merge_trace +
# critical_path then produce a per-segment attribution report. The
# report numbers are REPORT-ONLY (journaled below, like tier 1f); the
# hard gate is structural: every step trace spans >= 2 roles (worker
# AND ps), or cross-role propagation broke.
TRACE_DIR="$(mktemp -d)"
export TRACE_DIR
JAX_PLATFORMS=cpu EDL_TRACE_DIR="$TRACE_DIR" EDL_TRACE_SAMPLE=1 \
python - <<'PYEOF'
import sys, tempfile
sys.path.insert(0, "tests")
from test_utils import create_ctr_recordio
from elasticdl_tpu.train.local_executor import LocalExecutor
from elasticdl_tpu.observability import trace

with tempfile.TemporaryDirectory() as tmp:
    create_ctr_recordio(tmp + "/f0.rec", num_records=128, seed=0)
    executor = LocalExecutor(
        "elasticdl_tpu.models.deepfm", training_data=tmp,
        minibatch_size=32, num_epochs=1,
    )
    executor.train()
    trace.flush()
print("traced deepfm run OK")
PYEOF
python scripts/merge_trace.py "$TRACE_DIR"
# both consumers read the file merge_trace just wrote (no re-merge)
python scripts/trace_summary.py "$TRACE_DIR/merged.trace.json" --slowest 3
python scripts/critical_path.py "$TRACE_DIR/merged.trace.json" 2>/dev/null > /tmp/_critical_path.json
printf '{"ts": "%s", "critical_path": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_critical_path.json)" \
  >> /tmp/ci_wire_micro.jsonl
python - <<'PYEOF'
import json
report = json.load(open("/tmp/_critical_path.json"))
step = report.get("step")
assert step and step["count"] >= 2, report
# the gate: every step trace carries spans from BOTH roles
assert step["multi_role_traces"] == step["count"], step
assert {"worker", "ps"} <= set(step["roles"]), step
assert {"compute", "apply"} <= set(step["segments"]), step
print("tracing smoke OK: %d step traces, roles %s, segments %s"
      % (step["count"], step["roles"], sorted(step["segments"])))
PYEOF

echo "== tier 1d (profiling): continuous profiler smoke (/profilez + span-correlated frames) =="
# ISSUE 14: a traced+profiled deepfm local run (the local executor
# plays the worker role) must answer a mid-run /profilez window
# capture whose collapsed stacks name a known hot frame, and the
# end-of-run ring snapshot + merged trace must let critical_path.py
# --frames attribute real frame stacks to BOTH the compute and apply
# segments (the span-correlation acceptance gate). profile_report.py
# merges the capture into a flamegraph-ready collapsed file. The
# numbers are REPORT-ONLY (journaled below, like tier 1f); the gates
# are structural.
PROF_DIR="$(mktemp -d)"
PROF_TRACE_DIR="$(mktemp -d)"
PROF_EVENTS_DIR="$(mktemp -d)"
export PROF_DIR PROF_TRACE_DIR
# 211 Hz here, NOT the 29 Hz default: this lane gates a STRUCTURAL
# property (>=1 frame stack lands in each of compute and apply), and
# the apply leg is a small slice of a CPU deepfm step — at 29 Hz its
# expected sample count is low single digits, i.e. a coin-flip gate.
# The 29 Hz overhead contract has its own tier-1f A/B gate.
JAX_PLATFORMS=cpu EDL_TRACE_DIR="$PROF_TRACE_DIR" EDL_TRACE_SAMPLE=1 \
EDL_PROF_HZ=211 EDL_EVENTS_DIR="$PROF_EVENTS_DIR" \
python - <<'PYEOF'
import json, os, re, sys, tempfile, threading, time, urllib.request
sys.path.insert(0, "tests")
from test_utils import create_ctr_recordio
from elasticdl_tpu.common.grpc_utils import find_free_port

port = find_free_port()
os.environ["EDL_METRICS_PORT"] = str(port)
from elasticdl_tpu.train.local_executor import LocalExecutor
from elasticdl_tpu.observability import trace

prof_dir = os.environ["PROF_DIR"]
with tempfile.TemporaryDirectory() as tmp:
    create_ctr_recordio(tmp + "/f0.rec", num_records=4096, seed=0)
    executor = LocalExecutor(
        "elasticdl_tpu.models.deepfm", training_data=tmp,
        minibatch_size=128, num_epochs=12,
    )
    base = "http://localhost:%d" % executor.observability.port
    thread = threading.Thread(target=executor.train, daemon=True)
    thread.start()
    # wait past jit compile: capture only once real steps are landing
    # (the batch_process phase counter ticks once per train step)
    deadline = time.time() + 180
    while time.time() < deadline:
        body = urllib.request.urlopen(
            base + "/metrics", timeout=5
        ).read().decode()
        m = re.search(
            r'edl_phase_seconds_count\{phase="batch_process"\} (\d+)',
            body,
        )
        if m and int(m.group(1)) >= 2:
            break
        time.sleep(0.5)
    else:
        raise AssertionError("training never started stepping")
    collapsed = urllib.request.urlopen(
        base + "/profilez?seconds=2&format=collapsed", timeout=30
    ).read().decode()
    assert "train_step" in collapsed or "apply" in collapsed, (
        "mid-run capture names no known hot frame:\n%s"
        % collapsed[:2000]
    )
    thread.join(timeout=300)
    assert not thread.is_alive(), "deepfm run did not finish"
    # the rolling ring saw the whole run: save it as the per-role
    # capture the report tooling consumes
    snap = json.loads(urllib.request.urlopen(
        base + "/profilez", timeout=5
    ).read())
    assert snap["samples"] > 0, snap
    with open(os.path.join(
        prof_dir, "%s.profile.json" % snap["role"]
    ), "w") as f:
        json.dump(snap, f)
    # the profiler's own series are live on /metrics
    body = urllib.request.urlopen(
        base + "/metrics", timeout=5
    ).read().decode()
    assert "edl_prof_samples_total" in body, body[:1000]
    assert "edl_prof_overhead_ratio" in body
    trace.flush()
print("profiled deepfm run OK (mid-run /profilez capture verified)")
PYEOF
python scripts/merge_trace.py "$PROF_TRACE_DIR"
python scripts/profile_report.py "$PROF_DIR" \
  -o "$PROF_DIR/merged.collapsed.txt" > /tmp/_profile_report.json
python scripts/critical_path.py "$PROF_TRACE_DIR/merged.trace.json" \
  --frames "$PROF_DIR" 2>/dev/null > /tmp/_critical_frames.json
printf '{"ts": "%s", "profile_report": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_profile_report.json)" \
  >> /tmp/ci_wire_micro.jsonl
python - <<'PYEOF'
import json
report = json.load(open("/tmp/_critical_frames.json"))
frames = report.get("frames") or {}
# the ISSUE 14 acceptance gate: the live run's compute AND apply
# segments each attribute at least one real frame stack
for segment in ("compute", "apply"):
    stacks = frames.get(segment)
    assert stacks, "segment %r got no frame stacks: %s" % (
        segment, sorted(frames))
    assert all(s["count"] > 0 and s["stack"] for s in stacks)
print("span-correlated frames OK: %s" % {
    seg: len(stacks) for seg, stacks in sorted(frames.items())})
PYEOF
# the flight recorder saw the profiler lifecycle (the journal carries
# profiler_started + the mid-run profile_captured on the timeline)
python scripts/postmortem.py "$PROF_EVENTS_DIR" 2>/dev/null \
  > /tmp/_prof_postmortem.out
grep -q "profiler_started" /tmp/_prof_postmortem.out
grep -q "profile_captured" /tmp/_prof_postmortem.out

echo "== tier 1d+: flight recorder smoke (/statusz /alerts + postmortem) =="
# a real master + in-process worker with EDL_EVENTS_DIR set: the master
# must serve the fleet snapshot and alert list, the roles must journal
# lifecycle events, and scripts/postmortem.py must reconstruct a
# non-empty ordered timeline from them (docs/OBSERVABILITY.md)
EDL_EVENTS_DIR="$(mktemp -d)"
export EDL_EVENTS_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, sys, tempfile, threading, urllib.request
sys.path.insert(0, "tests")
from test_utils import create_mnist_recordio
from elasticdl_tpu.common.grpc_utils import find_free_port

events_dir = os.environ["EDL_EVENTS_DIR"]
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker

with tempfile.TemporaryDirectory() as tmp:
    create_mnist_recordio(tmp + "/f0.rec", num_records=96, seed=0)
    master = Master(
        "elasticdl_tpu.models.mnist", training_data=tmp,
        records_per_task=32, num_epochs=1,
        port=find_free_port(), metrics_port=find_free_port(),
    )
    master.prepare()
    mc = MasterClient("localhost:%d" % master._port, worker_id=0)
    mc.reset_worker()  # registration -> worker_register journaled
    worker = Worker(
        mc,
        "elasticdl_tpu.models.mnist",
        RecordIODataReader(data_dir=tmp),
        minibatch_size=32, wait_sleep_secs=0.1,
    )
    thread = threading.Thread(target=worker.run, daemon=True)
    thread.start()
    rc = master.run(poll_secs=0.2, timeout_secs=180)
    thread.join(timeout=30)
    assert rc == 0, "job did not finish"
    # master.run() stopped the server; restart exposition to curl the
    # final fleet state the way an operator would mid-run
    obs_port = find_free_port()
    from elasticdl_tpu.observability.http_server import (
        ObservabilityServer,
    )
    obs = ObservabilityServer("master", obs_port).start()
    obs.add_json_handler(
        "/statusz",
        lambda: master.fleet_monitor.snapshot(
            extra={"tasks": master.task_dispatcher.stats()}
        ),
    )
    obs.add_json_handler("/alerts", master.fleet_monitor.alerts)
    base = "http://localhost:%d" % obs.port
    statusz = json.loads(
        urllib.request.urlopen(base + "/statusz", timeout=5).read()
    )
    assert "worker-0" in statusz["fleet"], statusz
    assert statusz["fleet"]["worker-0"]["model_version"] >= 3
    assert statusz["tasks"]["done"]["training"] == 3
    alerts = json.loads(
        urllib.request.urlopen(base + "/alerts", timeout=5).read()
    )
    assert isinstance(alerts, list)
    # save the final metrics snapshot for the postmortem to fold in
    metrics = urllib.request.urlopen(
        base + "/metrics", timeout=5
    ).read().decode()
    with open(os.path.join(events_dir, "master.metrics.txt"), "w") as f:
        f.write(metrics)
    obs.stop()
print("flight recorder smoke OK")
PYEOF
python scripts/postmortem.py "$EDL_EVENTS_DIR" 2>/dev/null | tee /tmp/_postmortem.out | head -5 || true
# non-empty ordered timeline with the task lifecycle threaded through
grep -q "task_dispatch" /tmp/_postmortem.out
grep -q "per-worker summary:" /tmp/_postmortem.out

echo "== tier 1d (health): training-health smoke (NaN injection -> /alerts + skip) =="
# ISSUE 15: a real master + PS + worker deepfm job with a
# deterministically injected NaN batch (testing/faults.py nan-batch
# spec) under EDL_HEALTH_ON_NONFINITE=skip. The worker's health
# sentinels must catch the batch in-graph, the master's
# nonfinite_loss detector must raise on /alerts while the job runs,
# the job must still COMPLETE (skip drops only the poisoned batch),
# and the postmortem must thread the health events.
HEALTH_DIR="$(mktemp -d)"
export HEALTH_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, subprocess, sys, tempfile, threading, time, urllib.request
sys.path.insert(0, "tests")
from test_utils import create_ctr_recordio
from elasticdl_tpu.common.grpc_utils import find_free_port

events_dir = os.path.join(os.environ["HEALTH_DIR"], "events")
os.makedirs(events_dir)
os.environ["EDL_EVENTS_DIR"] = events_dir
os.environ["EDL_HEALTH_ON_NONFINITE"] = "skip"
# hold the alert through the short job so the poll can't miss it
os.environ["EDL_HEALTH_ALERT_SECS"] = "600"
# the injection: poison the 5th train batch of this process
os.environ["EDL_FAULT_SPEC"] = "worker-0:train_step:nan-batch:5"

train = tempfile.mkdtemp()
create_ctr_recordio(train + "/f0.rec", num_records=512, seed=0)
pport = find_free_port()
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--opt_type", "adam", "--opt_args", "lr=0.01", "--use_async", "1",
], env={**os.environ, "JAX_PLATFORMS": "cpu",
        "EDL_FAULT_SPEC": ""})

import socket
def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(pport)
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from elasticdl_tpu.testing import faults

faults.set_role("worker-0")
statz = find_free_port()
master = Master(
    "elasticdl_tpu.models.deepfm", training_data=train,
    records_per_task=64, num_epochs=1,
    port=find_free_port(), metrics_port=statz,
)
master.prepare()
mc = MasterClient("localhost:%d" % master._port, worker_id=0)
mc.reset_worker()
worker = Worker(
    mc, "elasticdl_tpu.models.deepfm",
    RecordIODataReader(data_dir=train), minibatch_size=32,
    wait_sleep_secs=0.1, ps_addrs=["localhost:%d" % pport],
)
wt = threading.Thread(target=worker.run, daemon=True)
wt.start()
rc_box = {}
mt = threading.Thread(
    target=lambda: rc_box.update(
        rc=master.run(poll_secs=0.2, timeout_secs=240)
    ),
    daemon=True,
)
mt.start()
# the injection window: poll /alerts until nonfinite_loss fires
alert = None
deadline = time.time() + 180
while time.time() < deadline and mt.is_alive():
    try:
        alerts = json.load(urllib.request.urlopen(
            "http://127.0.0.1:%d/alerts" % statz, timeout=5))
    except Exception:
        time.sleep(0.5); continue
    hit = [a for a in alerts if a["alert"] == "nonfinite_loss"]
    if hit:
        alert = hit[0]
        break
    time.sleep(0.5)
mt.join(timeout=300)
wt.join(timeout=60)
ps.terminate(); ps.wait(timeout=30)
assert alert is not None, "nonfinite_loss never raised on /alerts"
assert alert["skipped"] >= 1, alert
assert rc_box.get("rc") == 0, "job did not complete under skip: %s" % rc_box
stats = worker.trainer.health.stats()
assert stats["nonfinite_batches"] == 1, stats
assert stats["skipped_batches"] == 1, stats
print("health smoke OK: nonfinite_loss on /alerts (%r), job rc 0, "
      "1 batch skipped" % alert["alert"])
PYEOF
python scripts/postmortem.py "$HEALTH_DIR/events" 2>/dev/null | tee /tmp/_health_pm.out | head -5 || true
# the sentinel + the alert thread through the postmortem timeline
grep -q "health_nonfinite" /tmp/_health_pm.out
grep -q "nonfinite_loss" /tmp/_health_pm.out
grep -q "training health:" /tmp/_health_pm.out

echo "== tier 1d (device): recompile sentinel smoke (steady state + shape-churn drill) =="
# ISSUE 18 phase 1 — steady state: a real master + PS + worker deepfm
# job under the device-obs layer (EDL_DEVICE_OBS default-on). Every
# jitted step fn may compile once (warmup); ZERO recompiles after
# that, and the master's /statusz must carry a populated `device`
# section built from the worker's piggybacked telemetry.
DEVICE_DIR="$(mktemp -d)"
export DEVICE_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, subprocess, sys, tempfile, threading, time, socket
sys.path.insert(0, "tests")
from test_utils import create_ctr_recordio
from elasticdl_tpu.common.grpc_utils import find_free_port

events_dir = os.path.join(os.environ["DEVICE_DIR"], "events")
os.makedirs(events_dir)
os.environ["EDL_EVENTS_DIR"] = events_dir
os.environ.pop("EDL_FAULT_SPEC", None)

train = tempfile.mkdtemp()
create_ctr_recordio(train + "/f0.rec", num_records=256, seed=0)
pport = find_free_port()
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--opt_type", "adam", "--opt_args", "lr=0.01", "--use_async", "1",
], env={**os.environ, "JAX_PLATFORMS": "cpu"})

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(pport)
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from elasticdl_tpu.observability import device as device_obs

master = Master(
    "elasticdl_tpu.models.deepfm", training_data=train,
    records_per_task=64, num_epochs=1,
    port=find_free_port(), metrics_port=find_free_port(),
)
master.prepare()
mc = MasterClient("localhost:%d" % master._port, worker_id=0)
mc.reset_worker()
worker = Worker(
    mc, "elasticdl_tpu.models.deepfm",
    RecordIODataReader(data_dir=train), minibatch_size=32,
    wait_sleep_secs=0.1, ps_addrs=["localhost:%d" % pport],
)
wt = threading.Thread(target=worker.run, daemon=True)
wt.start()
rc = master.run(poll_secs=0.2, timeout_secs=240)
wt.join(timeout=60)
ps.terminate(); ps.wait(timeout=30)
assert rc == 0, "steady-state job did not complete: rc=%r" % rc
stats = device_obs.compile_stats()
assert stats, "no instrumented jit wrappers registered"
bad = {fn: s for fn, s in stats.items() if s["recompiles"] != 0}
assert not bad, "post-warmup recompiles in steady state: %r" % bad
assert any(s["compiles"] >= 1 for s in stats.values()), stats
snap = master.fleet_monitor.snapshot()
dev = snap.get("device") or {}
assert "worker-0" in dev, "statusz device section empty: %r" % snap.keys()
assert dev["worker-0"]["xla_compiles"] >= 1, dev
assert dev["worker-0"]["xla_recompiles"] == 0, dev
print("device steady-state OK: %d step fns, %d compiles, 0 recompiles"
      % (len(stats), sum(s["compiles"] for s in stats.values())))
PYEOF
# ISSUE 18 phase 2 — shape-churn drill: the first 4 train batches each
# lose a DIFFERENT number of trailing rows (testing/faults.py
# shape-churn spec), so every churned batch is a fresh signature and a
# full XLA recompile. The master's recompile_storm detector must RAISE
# while the churn is live and CLEAR as the recency window drains; the
# sentinels must journal each recompile with its shape provenance.
DEVICE_DRILL_DIR="$(mktemp -d)"
export DEVICE_DRILL_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, subprocess, sys, tempfile, threading, time, socket
import urllib.request
sys.path.insert(0, "tests")
from test_utils import create_ctr_recordio
from elasticdl_tpu.common.grpc_utils import find_free_port

events_dir = os.path.join(os.environ["DEVICE_DRILL_DIR"], "events")
os.makedirs(events_dir)
os.environ["EDL_EVENTS_DIR"] = events_dir
# the injection: the first 4 train batches churn shape (each drops a
# different row count); a short recency window so the clear is
# observable inside the smoke's budget
os.environ["EDL_FAULT_SPEC"] = "worker-0:train_step:shape-churn:4"
os.environ["EDL_RECOMPILE_STORM_MIN"] = "3"
os.environ["EDL_RECOMPILE_STORM_SECS"] = "30"

train = tempfile.mkdtemp()
create_ctr_recordio(train + "/f0.rec", num_records=512, seed=0)
pport = find_free_port()
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--opt_type", "adam", "--opt_args", "lr=0.01", "--use_async", "1",
], env={**os.environ, "JAX_PLATFORMS": "cpu", "EDL_FAULT_SPEC": ""})

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(pport)
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from elasticdl_tpu.observability import device as device_obs
from elasticdl_tpu.testing import faults

faults.set_role("worker-0")
statz = find_free_port()
master = Master(
    "elasticdl_tpu.models.deepfm", training_data=train,
    records_per_task=64, num_epochs=1,
    port=find_free_port(), metrics_port=statz,
)
master.prepare()
mc = MasterClient("localhost:%d" % master._port, worker_id=0)
mc.reset_worker()
worker = Worker(
    mc, "elasticdl_tpu.models.deepfm",
    RecordIODataReader(data_dir=train), minibatch_size=32,
    wait_sleep_secs=0.1, ps_addrs=["localhost:%d" % pport],
)
wt = threading.Thread(target=worker.run, daemon=True)
wt.start()
rc_box = {}
mt = threading.Thread(
    target=lambda: rc_box.update(
        rc=master.run(poll_secs=0.2, timeout_secs=240)
    ),
    daemon=True,
)
mt.start()
# the raise window: poll /alerts until recompile_storm fires
alert = None
deadline = time.time() + 180
while time.time() < deadline and mt.is_alive():
    try:
        alerts = json.load(urllib.request.urlopen(
            "http://127.0.0.1:%d/alerts" % statz, timeout=5))
    except Exception:
        time.sleep(0.5); continue
    hit = [a for a in alerts if a["alert"] == "recompile_storm"]
    if hit:
        alert = hit[0]
        break
    time.sleep(0.5)
mt.join(timeout=300)
wt.join(timeout=60)
ps.terminate(); ps.wait(timeout=30)
if alert is None:
    # the deepfm smoke can finish inside a couple of poll intervals;
    # the monitor outlives the run and its recency window is 30 s, so
    # a direct detector pass still observes the raise deterministically
    hit = [a for a in master.fleet_monitor.alerts()
           if a["alert"] == "recompile_storm"]
    alert = hit[0] if hit else None
assert alert is not None, "recompile_storm never raised on /alerts"
assert alert["recompiles_in_window"] >= 3, alert
assert rc_box.get("rc") == 0, "drill job did not complete: %s" % rc_box
# the clear: the monitor outlives the run; keep evaluating until the
# 30 s recency window drains and the alert self-clears
cleared = False
deadline = time.time() + 90
while time.time() < deadline:
    firing = master.fleet_monitor.alerts()
    if not any(a["alert"] == "recompile_storm" for a in firing):
        cleared = True
        break
    time.sleep(1.0)
assert cleared, "recompile_storm never cleared after the churn window"
# the sentinel really counted the churn, with provenance attached
stats = device_obs.compile_stats()
total_recompiles = sum(s["recompiles"] for s in stats.values())
assert total_recompiles >= 3, stats
print("device drill OK: storm raised (%d recompiles in window) and "
      "cleared; %d sentinel recompiles"
      % (alert["recompiles_in_window"], total_recompiles))
PYEOF
python scripts/postmortem.py "$DEVICE_DRILL_DIR/events" 2>/dev/null | tee /tmp/_device_pm.out | head -8 || true
# each recompile journaled with shape provenance, and the storm's
# raise AND clear thread through the postmortem timeline
grep -q "xla_recompile" "$DEVICE_DRILL_DIR"/events/*.ndjson
grep -q "signature" "$DEVICE_DRILL_DIR"/events/*.ndjson
grep -q "recompile_storm" /tmp/_device_pm.out
grep -q "alert_cleared" "$DEVICE_DRILL_DIR"/events/*.ndjson
grep -q "device runtime:" /tmp/_device_pm.out

echo "== tier 1e: chaos smoke (EDL_FAULT_SPEC + control-plane crash recovery) =="
# a live local master+PS+worker job under deterministic fault injection
# (docs/FAULT_TOLERANCE.md): the PS answers UNAVAILABLE for its first
# pushes (the worker's jittered retry rides through), the master
# SIGKILLs itself mid-epoch (kill-once) and is relaunched to replay its
# EDL_STATE_DIR journal. The job must complete with every task done
# exactly once, and the postmortem must thread the recovery events.
# (The gRPC-free local executor can't host interceptor faults; this is
# the smallest real-wire topology.)
CHAOS_DIR="$(mktemp -d)"
export CHAOS_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, signal, socket, subprocess, sys, tempfile, threading, time
sys.path.insert(0, "tests")
# trim the post-job retry tail BEFORE master_client is imported (the
# budget is read at import time)
os.environ["EDL_MASTER_RETRY_BUDGET_SECS"] = "60"
from test_utils import create_ctr_recordio
from elasticdl_tpu.common.grpc_utils import find_free_port

chaos = os.environ["CHAOS_DIR"]
events_dir = os.path.join(chaos, "events")
state_dir = os.path.join(chaos, "state")
os.makedirs(events_dir); os.makedirs(state_dir)
train = tempfile.mkdtemp()
create_ctr_recordio(train + "/f0.rec", num_records=512, seed=0)
mport, pport = find_free_port(), find_free_port()
base_env = {**os.environ, "JAX_PLATFORMS": "cpu",
            "EDL_EVENTS_DIR": events_dir}
master_cmd = [
    sys.executable, "-m", "elasticdl_tpu.master.main",
    "--model_zoo", "elasticdl_tpu.models.deepfm",
    "--training_data", train, "--records_per_task", "64",
    "--num_epochs", "1", "--port", str(mport),
    "--task_timeout_secs", "60",
]
master = subprocess.Popen(master_cmd, env={
    **base_env, "EDL_STATE_DIR": state_dir,
    # deterministic: the 4th task report SIGKILLs the master mid-epoch
    "EDL_FAULT_SPEC": "master:report_task_result:kill-once:4",
})
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--opt_type", "adam", "--opt_args", "lr=0.01",
], env={
    **base_env,
    # deterministic burst: first 3 pushes fail UNAVAILABLE; the
    # worker's full-jitter retry must ride through without burning
    # task retries
    "EDL_FAULT_SPEC": "ps-0:push_gradients:unavailable:3",
})

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(mport); wait_port(pport)
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
mc = MasterClient("localhost:%d" % mport, worker_id=0)
mc.reset_worker()
worker = Worker(
    mc, "elasticdl_tpu.models.deepfm",
    RecordIODataReader(data_dir=train), minibatch_size=64,
    wait_sleep_secs=0.1, ps_addrs=["localhost:%d" % pport],
)
runner = threading.Thread(target=worker.run, daemon=True)
runner.start()
# the injected kill-once takes the master down mid-epoch...
master.wait(timeout=180)
assert master.returncode != 0, "master survived its kill-once fault"
# ...and the relaunch (fault spec cleared) replays the state journal
master = subprocess.Popen(master_cmd, env={
    **base_env, "EDL_STATE_DIR": state_dir,
})
rc = master.wait(timeout=300)
assert rc == 0, "relaunched master did not finish the job (rc=%s)" % rc
runner.join(timeout=150)
assert not runner.is_alive(), "worker never finished"
ps.terminate(); ps.wait(timeout=30)
# done-exactly-once accounting straight from the state journal
ops = []
with open(os.path.join(state_dir, "master.journal.ndjson")) as f:
    for line in f:
        try:
            ops.append(json.loads(line))
        except ValueError:
            pass  # torn tail from the SIGKILL
created = {t[0] for op in ops if op["op"] == "tasks_created"
           for t in op["tasks"]}
done = [op["task"] for op in ops if op["op"] == "done"]
assert sorted(done) == sorted(created), (len(done), len(created))
assert len([op for op in ops if op["op"] == "master_restarted"]) == 2
print("chaos smoke OK: %d tasks done exactly once across a master kill"
      % len(done))
PYEOF
python scripts/postmortem.py "$CHAOS_DIR/events" 2>/dev/null | tee /tmp/_chaos_pm.out | head -5 || true
# the recovery events thread through the postmortem timeline
grep -q "master_restarted" /tmp/_chaos_pm.out
grep -q "task_dispatch" /tmp/_chaos_pm.out
grep -q "worker_register" /tmp/_chaos_pm.out

echo "== tier 1e (overload): PS pushback + breaker drill on live /alerts =="
# ISSUE 19: a live master+PS+worker deepfm job while a noise-gradient
# storm saturates the PS's single admission slot through a bounded
# slow-apply window (the `overload` fault kind). The ps_overload and
# circuit_open alerts must RAISE on the live /alerts while the storm
# runs and CLEAR after it stops — with the job still running; the
# worker-side breaker must open on an injected UNAVAILABLE burst and
# end the run re-closed; pushback must show in the PS admission books
# (/statusz overload section) and the client pacing books; and the job
# itself must complete rc 0 — degraded, never failed.
OVLD_DIR="$(mktemp -d)"
export OVLD_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, socket, subprocess, sys, tempfile, threading, time, urllib.request
sys.path.insert(0, "tests")
events_dir = os.path.join(os.environ["OVLD_DIR"], "events")
os.makedirs(events_dir)
os.environ["EDL_EVENTS_DIR"] = events_dir
# short recency window so raise AND clear both land inside one job
os.environ["EDL_HEALTH_ALERT_SECS"] = "5"
# a 2-failure breaker with a quick probe window
os.environ["EDL_CIRCUIT_FAILURES"] = "2"
os.environ["EDL_CIRCUIT_RESET_SECS"] = "0.5"
# the drill measures alerts and pacing, not token accounting (that
# edge is unit-tested): keep the bucket out of the way
os.environ["EDL_RETRY_BUDGET_TOKENS"] = "1000"
# client-side burst: the first 6 pushes out of this process fail
# UNAVAILABLE — the breaker must open, probe, and re-close
os.environ["EDL_FAULT_SPEC"] = "worker-0:push_gradients:unavailable:6"

import numpy as np
from test_utils import create_ctr_recordio
from elasticdl_tpu.common import overload
from elasticdl_tpu.common.grpc_utils import (
    build_channel, find_free_port, retry_call,
)
from elasticdl_tpu.common.tensor_utils import serialize_indexed_slices
from elasticdl_tpu.observability import events
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.proto.services import PserverStub
from elasticdl_tpu.testing import faults

events.configure("worker-0")
faults.set_role("worker-0")

train = tempfile.mkdtemp()
# enough tasks that the job comfortably outlives the storm — the
# CLEAR half of the drill needs live /alerts after the storm ends
create_ctr_recordio(train + "/f0.rec", num_records=8192, seed=0)
mport, pport, statz = find_free_port(), find_free_port(), find_free_port()
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--master_addr", "localhost:%d" % mport,
    "--opt_type", "adam", "--opt_args", "lr=0.01", "--use_async", "1",
], env={**os.environ, "JAX_PLATFORMS": "cpu",
        # TWO admission slots + a bounded slow-apply window: the
        # storm's two pushers plus the worker exceed the slots and
        # draw RESOURCE_EXHAUSTED with a retry-after hint calibrated
        # from observed apply latency — but once the storm stops, a
        # lone retrying push admits next to the worker's, so the
        # rejection counters actually stop moving and the alert can
        # clear (one slot would make the trailing storm push lose the
        # slot race to the worker for tens of seconds)
        "EDL_PS_MAX_PENDING_APPLIES": "2",
        "EDL_FAULT_SPEC": "ps-0:push_gradients:overload:0.4:40"})

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(pport)
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker

master = Master(
    "elasticdl_tpu.models.deepfm", training_data=train,
    records_per_task=64, num_epochs=1, port=mport, metrics_port=statz,
)
master.prepare()
mc = MasterClient("localhost:%d" % mport, worker_id=0)
mc.reset_worker()
worker = Worker(
    mc, "elasticdl_tpu.models.deepfm",
    RecordIODataReader(data_dir=train), minibatch_size=32,
    wait_sleep_secs=0.1, ps_addrs=["localhost:%d" % pport],
)
wt = threading.Thread(target=worker.run, daemon=True)
wt.start()
rc_box = {}
mt = threading.Thread(
    target=lambda: rc_box.update(
        rc=master.run(poll_secs=0.2, timeout_secs=300)
    ),
    daemon=True,
)
mt.start()

# the storm: two noise-table pushers contending for the PS's one
# admission slot while applies run 0.4 s each — rejections are
# structural, not timing luck. The noise table is disjoint from the
# model's, so training state is untouched.
addr = "localhost:%d" % pport
storm_channel = build_channel(addr)
storm_stub = PserverStub(storm_channel)
info = pb.Model()
info.embedding_table_infos.add(name="noise", dim=4, initializer="0.0")
storm_stub.push_embedding_table_infos(info, timeout=30)

storm_stop = threading.Event()

def storm(seed):
    rng = np.random.RandomState(seed)
    # the storm runs until both alerts are observed (storm_stop), not
    # for a fixed count: late storm pushes wait out doubled pushback
    # hints (~5 s apiece), so a fixed-length storm would starve the
    # clear window's runway. 40 is the never-raised backstop.
    for _ in range(40):
        if storm_stop.is_set():
            return
        request = pb.PushGradientsRequest()
        request.gradients.version = 0
        serialize_indexed_slices(
            rng.randn(64, 4).astype(np.float32),
            np.arange(64, dtype=np.int64),
            request.gradients.embedding_tables["noise"], packed=True,
        )
        retry_call(
            lambda r=request: storm_stub.push_gradients(r, timeout=30),
            "storm push", budget_secs=120.0, target=addr,
        )
        time.sleep(0.1)

storms = [threading.Thread(target=storm, args=(s,), daemon=True)
          for s in (11, 12)]
for s in storms:
    s.start()

def poll_alerts():
    return json.load(urllib.request.urlopen(
        "http://127.0.0.1:%d/alerts" % statz, timeout=5))

raised = set()
deadline = time.time() + 120
while time.time() < deadline and mt.is_alive():
    try:
        alerts = poll_alerts()
    except Exception:
        time.sleep(0.5); continue
    raised |= {a["alert"] for a in alerts
               if a["alert"] in ("ps_overload", "circuit_open")}
    if raised == {"ps_overload", "circuit_open"}:
        break
    time.sleep(0.5)
assert raised == {"ps_overload", "circuit_open"}, raised
# pushback visible in the live /statusz overload section
statusz = json.load(urllib.request.urlopen(
    "http://127.0.0.1:%d/statusz" % statz, timeout=5))
ps_view = statusz["overload"]["ps"]
assert any(v["ps_overload_rejections"] >= 1 for v in ps_view.values()), ps_view

storm_stop.set()
for s in storms:
    s.join(timeout=180)
# raise AND clear: the storm stopped and the slow window is spent, so
# the rejection/open counters stop moving and both alerts must clear
# within EDL_HEALTH_ALERT_SECS — while the job is still running
cleared = False
deadline = time.time() + 120
while time.time() < deadline and mt.is_alive():
    try:
        alerts = poll_alerts()
    except Exception:
        time.sleep(0.5); continue
    if not [a for a in alerts
            if a["alert"] in ("ps_overload", "circuit_open")]:
        cleared = True
        break
    time.sleep(0.5)
assert cleared, "overload alerts never cleared while the job ran"
mt.join(timeout=300)
wt.join(timeout=60)
ps.terminate(); ps.wait(timeout=30)
assert rc_box.get("rc") == 0, rc_box
stats = overload.client_stats()
assert stats["pushback_waits"] >= 1, stats
assert stats["circuit_open_count"] >= 1, stats
assert stats["circuits_not_closed"] == [], stats
print("overload drill OK: pushback waits %d, breaker opens %d, "
      "alerts raised+cleared, rc 0"
      % (stats["pushback_waits"], stats["circuit_open_count"]))
PYEOF
python scripts/postmortem.py "$OVLD_DIR/events" 2>/dev/null | tee /tmp/_ovld_pm.out | head -5 || true
# the overload incident threads through the postmortem timeline
grep -q "ps_overload_enter" /tmp/_ovld_pm.out
grep -q "circuit_open" /tmp/_ovld_pm.out

echo "== tier 1e+: scale-down under SIGTERM (graceful drain) =="
# ISSUE 7: a live master + worker; the worker is SIGTERMed mid-job
# (what a scale-down pod delete / spot preemption delivers). Its
# SIGTERM chain (flight-recorder dump -> worker/drain.py) must finish
# the current task, deregister (the drain ack), and exit 0 — and the
# master must remove it with NO task_requeue for the drained worker's
# last task; a replacement worker then finishes the job.
DRAIN_DIR="$(mktemp -d)"
export DRAIN_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, signal, socket, subprocess, sys, tempfile, time
sys.path.insert(0, "tests")
from test_utils import create_mnist_recordio, load_journal
from elasticdl_tpu.common.grpc_utils import find_free_port

events_dir = os.path.join(os.environ["DRAIN_DIR"], "events")
os.makedirs(events_dir)
train = tempfile.mkdtemp()
create_mnist_recordio(train + "/f0.rec", num_records=768, seed=0)
mport = find_free_port()
base_env = {**os.environ, "JAX_PLATFORMS": "cpu",
            "EDL_EVENTS_DIR": events_dir,
            "EDL_DRAIN_DEADLINE_SECS": "120"}
master = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.master.main",
    "--model_zoo", "elasticdl_tpu.models.mnist",
    "--training_data", train, "--records_per_task", "64",
    "--num_epochs", "1", "--port", str(mport),
    "--task_timeout_secs", "120",
], env=base_env)

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

def spawn_worker(idx):
    return subprocess.Popen([
        sys.executable, "-m", "elasticdl_tpu.worker.main",
        "--master_addr", "localhost:%d" % mport,
        "--worker_id", str(idx),
        "--model_zoo", "elasticdl_tpu.models.mnist",
        "--training_data", train, "--minibatch_size", "32",
    ], env=base_env)

wait_port(mport)
victim = spawn_worker(0)
# SIGTERM once the victim holds a task mid-job (dispatch journaled,
# job not yet near its end)
deadline = time.time() + 120
while time.time() < deadline:
    reports = [e for e in load_journal(events_dir)
               if e["event"] == "task_report"]
    if len(reports) >= 2:
        break
    time.sleep(0.5)
assert len(reports) >= 2, "victim made no progress"
victim.send_signal(signal.SIGTERM)
rc = victim.wait(timeout=120)
assert rc == 0, "drained worker exited rc=%s (graceful exit expected)" % rc
merged = load_journal(events_dir)
acks = [e for e in merged if e["event"] == "drain_ack"]
assert acks, "no drain_ack journaled"
assert any(a.get("worker") == 0 for a in acks), acks
# done-exactly-once: the drained worker's last task completed inside
# the drain, so NOTHING the victim held was requeued
requeues = [e for e in merged if e["event"] == "task_requeue"]
assert requeues == [], requeues
# a replacement finishes the job; the master exits 0
finisher = spawn_worker(1)
rc = master.wait(timeout=300)
assert rc == 0, "master did not finish the job (rc=%s)" % rc
# generous: on a loaded 1-core box the finisher's post-job exit (retry
# budget against the gone master) can straggle past 120s
finisher.wait(timeout=240)
print("drain smoke OK: SIGTERM -> drain_ack, zero requeues")
PYEOF
# the drain threads through the postmortem timeline too
python scripts/postmortem.py "$DRAIN_DIR/events" 2>/dev/null | tee /tmp/_drain_pm.out | head -5 || true
grep -q "worker_draining" /tmp/_drain_pm.out
grep -q "drain_ack" /tmp/_drain_pm.out

echo "== tier 1e++: serving smoke (PS + serve role over a fresh export) =="
# ISSUE 8: the full serving topology as subprocesses — a real PS seeded
# with trained embedding rows, a serve role loading a fresh
# train/export.py artifact. Predict RPCs answer through the
# micro-batcher; a past-deadline request is SHED server-side (the shed
# counter moves — it was never served late); /metrics and /readyz
# answer; SIGTERM drains cleanly (admissions stop, queue flushes,
# serve_drained journaled, exit 0).
SERVE_DIR="$(mktemp -d)"
export SERVE_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import os, signal, subprocess, sys, tempfile, time, urllib.request
sys.path.insert(0, "tests")
import numpy as np
from test_utils import create_ctr_recordio, load_journal
from elasticdl_tpu.common.grpc_utils import find_free_port

events_dir = os.path.join(os.environ["SERVE_DIR"], "events")
os.makedirs(events_dir)
train = tempfile.mkdtemp()
create_ctr_recordio(train + "/f0.rec", num_records=128, seed=0)

# train briefly in-process, export the dense bundle
from elasticdl_tpu.train.local_executor import LocalExecutor
from elasticdl_tpu.train.export import export_train_state
executor = LocalExecutor(
    "elasticdl_tpu.models.deepfm", training_data=train,
    minibatch_size=32, num_epochs=1,
)
executor.train()
export_dir = os.path.join(os.environ["SERVE_DIR"], "export")
export_train_state(executor.state, export_dir)

base_env = {**os.environ, "JAX_PLATFORMS": "cpu",
            "EDL_EVENTS_DIR": events_dir}
pport, sport, mport = find_free_port(), find_free_port(), find_free_port()
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--opt_type", "adam", "--opt_args", "lr=0.001", "--use_async", "1",
], env=base_env)

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        import socket
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(pport)
# seed the PS with the trained rows (the deepfm tables live on the PS
# in the distributed topology; locally they trained in-process)
from elasticdl_tpu.worker.ps_client import PSClient
from elasticdl_tpu.models import deepfm
seed_client = PSClient(["localhost:%d" % pport])
specs = deepfm.sparse_embedding_specs(batch_size=32)
seed_client.push_embedding_table_infos(
    [(s.name, s.dim, str(float(s.init_scale))) for s in specs]
)
store = executor.trainer.preparer._ps.store
seed_client.push_embedding_rows({
    s.name: store.export_table(s.name) for s in specs
})

serve = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.serve.main", "--serve_id", "0",
    "--port", str(sport), "--model_zoo", "elasticdl_tpu.models.deepfm",
    "--export_dir", export_dir, "--ps_addrs", "localhost:%d" % pport,
    "--metrics_port", str(mport),
    "--max_batch", "32", "--max_delay_ms", "60", "--queue_depth", "64",
    "--deadline_ms", "2000",
], env=base_env)
wait_port(sport)
# readiness flips once the export is loaded
deadline = time.time() + 120
ready = False
while time.time() < deadline:
    try:
        ready = urllib.request.urlopen(
            "http://localhost:%d/readyz" % mport, timeout=2
        ).status == 200
        if ready:
            break
    except Exception:
        pass
    time.sleep(0.3)
assert ready, "serve role never became ready"

from elasticdl_tpu.serve.client import ServeClient
import grpc
client = ServeClient("localhost:%d" % sport)
rng = np.random.RandomState(0)
# generous first deadline: the first request compiles the forward
for i, budget in enumerate([120, 10, 10, 10, 10]):
    ids = rng.randint(0, 1000, size=(4, 10)).astype(np.int64)
    outputs, step, _ = client.predict({"ids": ids}, deadline_secs=budget)
    assert outputs["output"].shape == (4,)
    assert np.isfinite(outputs["output"]).all()
print("serving smoke: %d predicts OK (model step %d)" % (i + 1, step))

# a request whose budget (20 ms) is INSIDE the 60 ms formation window
# must be shed server-side, never served late
try:
    client.predict({"ids": ids}, deadline_secs=0.02)
    raise AssertionError("past-deadline request was served")
except grpc.RpcError as e:
    assert e.code() == grpc.StatusCode.DEADLINE_EXCEEDED, e.code()
time.sleep(1.0)  # let the batcher's shed land in /metrics
metrics = urllib.request.urlopen(
    "http://localhost:%d/metrics" % mport, timeout=5
).read().decode()
for series in (
    "edl_serve_request_seconds", "edl_serve_model_info",
    'edl_serve_requests_shed_total{reason="deadline"} 1',
    "edl_serve_batch_size",
):
    assert series in metrics, "missing series: %s" % series
print("serving smoke: past-deadline request shed server-side")

serve.send_signal(signal.SIGTERM)
rc = serve.wait(timeout=60)
assert rc == 0, "serve role exited rc=%s (clean drain expected)" % rc
merged = load_journal(events_dir, prefix="serve")
names = [e["event"] for e in merged]
assert "model_loaded" in names, names
drained = [e for e in merged if e["event"] == "serve_drained"]
assert drained and drained[0]["reason"] == "sigterm", merged
assert drained[0]["served"] >= 5
ps.terminate(); ps.wait(timeout=30)
print("serving smoke OK: clean SIGTERM drain journaled")
PYEOF

echo "== tier 1e++ (fleet): serving-fleet smoke (router + 2 replicas + real PS) =="
# ISSUE 17: the fleet topology as real subprocesses — a router_main
# role self-managing two serve-replica subprocesses over a seeded PS
# and a versioned export root. One replica is SIGKILLed mid-traffic:
# ZERO client requests may fail (affinity failover + the autoscaler's
# below-floor replacement), the loss and every scale decision are
# journaled with reasons, and a v2 export canary-promotes under live
# traffic. scripts/postmortem.py then threads the whole incident into
# one timeline.
FLEET_DIR="$(mktemp -d)"
export FLEET_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.request
sys.path.insert(0, "tests")
import numpy as np
from test_utils import create_ctr_recordio, load_journal
from elasticdl_tpu.common.grpc_utils import find_free_port

events_dir = os.path.join(os.environ["FLEET_DIR"], "events")
root = os.path.join(os.environ["FLEET_DIR"], "exports")
os.makedirs(events_dir); os.makedirs(root)
train = tempfile.mkdtemp()
create_ctr_recordio(train + "/f0.rec", num_records=128, seed=0)

from elasticdl_tpu.train.local_executor import LocalExecutor
from elasticdl_tpu.train.export import export_train_state
from elasticdl_tpu.serve.model import export_signature
executor = LocalExecutor(
    "elasticdl_tpu.models.deepfm", training_data=train,
    minibatch_size=32, num_epochs=1,
)
executor.train()
export_train_state(executor.state, os.path.join(root, "v00001"))

base_env = {
    **os.environ, "JAX_PLATFORMS": "cpu", "EDL_EVENTS_DIR": events_dir,
    # tight fleet clocks so the smoke converges fast; the scale
    # cooldown still outlasts a replica cold start (spawn-storm guard)
    "EDL_ROUTER_HEARTBEAT_SECS": "1",
    "EDL_ROUTER_REPLICA_TIMEOUT_SECS": "15",
    "EDL_SERVE_SCALE_COOLDOWN_SECS": "45",
    "EDL_CANARY_FRACTION": "0.5",
    "EDL_CANARY_MIN_REQUESTS": "15",
    "EDL_CANARY_TIMEOUT_SECS": "600",
}
pport, rport, mport = find_free_port(), find_free_port(), find_free_port()
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--opt_type", "adam", "--opt_args", "lr=0.001", "--use_async", "1",
], env=base_env)

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(pport)
from elasticdl_tpu.worker.ps_client import PSClient
from elasticdl_tpu.models import deepfm
seed_client = PSClient(["localhost:%d" % pport])
specs = deepfm.sparse_embedding_specs(batch_size=32)
seed_client.push_embedding_table_infos(
    [(s.name, s.dim, str(float(s.init_scale))) for s in specs]
)
store = executor.trainer.preparer._ps.store
seed_client.push_embedding_rows({
    s.name: store.export_table(s.name) for s in specs
})

router = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.serve.router_main",
    "--router_id", "0", "--port", str(rport),
    "--min_replicas", "2", "--max_replicas", "3",
    "--export_root", root,
    "--replica_args",
    "--model_zoo elasticdl_tpu.models.deepfm "
    "--ps_addrs localhost:%d --max_batch 32 --max_delay_ms 5 "
    "--queue_depth 256" % pport,
    "--metrics_port", str(mport),
], env=base_env)
wait_port(rport)

def routerz():
    return json.loads(urllib.request.urlopen(
        "http://localhost:%d/routerz" % mport, timeout=5
    ).read())

def wait_fleet(cond, what, timeout=300):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if cond(routerz()):
                return
        except Exception:
            pass
        time.sleep(0.5)
    raise TimeoutError(what)

# the router's own floor check places the initial pair (that grow is
# journaled like any other decision)
wait_fleet(
    lambda s: len(s["replicas"]) >= 2
    and all(v["loaded_stamp"] for v in s["replicas"].values()),
    "2 replicas registered + loaded",
)

from elasticdl_tpu.serve.client import ServeClient
client = ServeClient("localhost:%d" % rport)
rng = np.random.RandomState(0)

def fire(key, budget=60):
    ids = rng.randint(0, 1000, size=(4, 10)).astype(np.int64)
    outputs, _, stamp = client.predict(
        {"ids": ids}, deadline_secs=budget, affinity_key=key
    )
    assert np.isfinite(outputs["output"]).all()
    return stamp

# warm both replicas' compiled forwards: distinct keys spread over the
# ring; generous budget — the first hit per replica pays its jit
for key in range(16):
    fire(key, budget=180)
print("fleet smoke: fleet warmed through the router")

# SIGKILL one replica mid-traffic. ZERO failures allowed: its keys
# fail over to ring successors and the floor replaces it.
victim = sorted(routerz()["replicas"])[0]
os.kill(int(victim.rsplit("-", 1)[1]), signal.SIGKILL)
for key in range(30):
    fire(key, budget=120)
print("fleet smoke: 30/30 predicts OK across the SIGKILL")
wait_fleet(
    lambda s: victim not in s["replicas"] and len(s["replicas"]) >= 2
    and all(v["loaded_stamp"] for v in s["replicas"].values()),
    "below-floor replacement", timeout=300,
)
print("fleet smoke: %s replaced (floor restored)" % victim)

# v2 export -> canary promote under live traffic (the judge needs both
# arms' books filled, so keep firing while it deliberates)
for batch in executor._batches(executor._train_reader, "training"):
    executor.state, _ = executor.trainer.train_step(
        executor.state, batch
    )
    break
export_train_state(executor.state, os.path.join(root, "v00002"))
v2 = export_signature(os.path.join(root, "v00002"))
deadline = time.time() + 600
key = 0
while time.time() < deadline:
    if routerz()["canary"]["incumbent"]["stamp"] == v2:
        break
    fire(key % 509, budget=120)
    key += 1
    time.sleep(0.05)
else:
    raise TimeoutError("canary never promoted v00002")
print("fleet smoke: canary promoted v00002 under live traffic")

client.close()
router.send_signal(signal.SIGTERM)
rc = router.wait(timeout=120)
assert rc == 0, "router exited rc=%s (clean drain expected)" % rc
ps.terminate(); ps.wait(timeout=30)

merged = load_journal(events_dir)
names = [e["event"] for e in merged]
lost = [e for e in merged if e["event"] == "replica_lost"
        and e.get("replica") == victim]
assert lost, "replica_lost for %s not journaled: %s" % (
    victim, sorted(set(names)))
grows = [e for e in merged if e["event"] == "scale_decision"
         and e.get("tag") == "serve" and e.get("direction") == "grow"]
assert len(grows) >= 2 and all(e.get("reasons") for e in grows), grows
assert any(str(r).startswith("below_floor")
           for e in grows for r in e.get("reasons", [])), grows
promoted = [e for e in merged if e["event"] == "canary_promoted"]
assert promoted and promoted[0].get("reasons"), sorted(set(names))
assert "canary_started" in names and "replica_registered" in names, (
    sorted(set(names)))
print("fleet smoke OK: kill -> failover -> replacement -> promote")
PYEOF
# the postmortem must thread the fleet incident into one timeline
python scripts/postmortem.py "$FLEET_DIR/events" 2>/dev/null \
  | tee /tmp/_fleet_postmortem.out | head -5 || true
grep -q "replica_lost" /tmp/_fleet_postmortem.out
grep -q "canary_promoted" /tmp/_fleet_postmortem.out

echo "== tier 1e+++: UDS local transport smoke (co-located worker+PS) =="
# ISSUE 11: a real master+PS+worker deepfm job with the PS and worker
# sharing EDL_PS_UDS_DIR — the worker's PS channel must ride the unix
# socket (asserted before the job starts), the job must complete, and
# the TCP fallback must serve the same exchange with the env unset.
UDS_DIR="$(mktemp -d)"
export UDS_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import os, socket, subprocess, sys, tempfile, threading, time
sys.path.insert(0, "tests")
from test_utils import create_ctr_recordio
from elasticdl_tpu.common.grpc_utils import (
    find_free_port, maybe_uds_addr, uds_socket_path,
)

uds_dir = os.path.join(os.environ["UDS_DIR"], "sock")
train = tempfile.mkdtemp()
create_ctr_recordio(train + "/f0.rec", num_records=256, seed=0)
mport, pport = find_free_port(), find_free_port()
base_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
master = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.master.main",
    "--model_zoo", "elasticdl_tpu.models.deepfm",
    "--training_data", train, "--records_per_task", "64",
    "--num_epochs", "1", "--port", str(mport),
    "--task_timeout_secs", "60",
], env=base_env)
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--opt_type", "adam", "--opt_args", "lr=0.01", "--use_async", "1",
], env={**base_env, "EDL_PS_UDS_DIR": uds_dir})

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(mport); wait_port(pport)
# the socket must exist and the client-side rewrite must take it
os.environ["EDL_PS_UDS_DIR"] = uds_dir
path = uds_socket_path(pport)
deadline = time.time() + 30
while not os.path.exists(path) and time.time() < deadline:
    time.sleep(0.2)
assert os.path.exists(path), "PS never bound its unix socket"
assert maybe_uds_addr("localhost:%d" % pport) == "unix:" + path

from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
mc = MasterClient("localhost:%d" % mport, worker_id=0)
mc.reset_worker()
worker = Worker(
    mc, "elasticdl_tpu.models.deepfm",
    RecordIODataReader(data_dir=train), minibatch_size=64,
    wait_sleep_secs=0.1, ps_addrs=["localhost:%d" % pport],
)
runner = threading.Thread(target=worker.run, daemon=True)
runner.start()
rc = master.wait(timeout=300)
assert rc == 0, "UDS job did not finish (rc=%s)" % rc
runner.join(timeout=120)

# TCP fallback: env unset -> the rewrite declines, the same PS still
# serves the exchange over its TCP listener
del os.environ["EDL_PS_UDS_DIR"]
assert maybe_uds_addr("localhost:%d" % pport) is None
import numpy as np
from elasticdl_tpu.worker.ps_client import PSClient
tcp_client = PSClient(["localhost:%d" % pport])
rows = tcp_client.pull_embedding_batch(
    {"deepfm_emb": np.arange(4, dtype=np.int64)}
)
assert rows["deepfm_emb"].shape[0] == 4
ps.terminate(); ps.wait(timeout=30)
print("UDS smoke OK: job over unix socket, fallback over TCP")
PYEOF

echo "== tier 1e++++: streaming smoke (synthetic clickstream, lifecycle PS) =="
# ISSUE 12: a real master+PS+worker job over an unbounded-vocab
# synthetic clickstream with the embedding lifecycle enabled. Hard
# assertions: the job drains to rc 0 once the bounded stream closes, a
# watermark-cadence sparse checkpoint lands at the PS, lifecycle
# evictions fire (journaled tombstones), an evicted id re-admits
# cleanly through fresh traffic, and the master's /statusz shows the
# lifecycle gauges beside the stream watermark.
STREAM_DIR="$(mktemp -d)"
export STREAM_DIR
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json, os, socket, subprocess, sys, threading, time, urllib.request
sys.path.insert(0, "tests")
from elasticdl_tpu.common.grpc_utils import find_free_port

base = os.environ["STREAM_DIR"]
spool = os.path.join(base, "spool"); os.makedirs(spool)
events_dir = os.path.join(base, "events")
ckpt = os.path.join(base, "ps-ckpt"); os.makedirs(ckpt)
mport, pport, statz = find_free_port(), find_free_port(), find_free_port()
env = {
    **os.environ, "JAX_PLATFORMS": "cpu",
    "EDL_EVENTS_DIR": events_dir,
    "EDL_STREAM": "synthetic",
    # sized so the job runs tens of seconds: the PS's 5 s poll must
    # observe INTERMEDIATE watermarks (checkpoint cadence) and sweep
    # mid-stream, and the backlog cap must keep minting progressive
    # (an uncapped feeder would mint+close the whole bounded stream
    # in one tick)
    "EDL_STREAM_TOTAL_RECORDS": "16384",
    "EDL_STREAM_WINDOW_RECORDS": "256",
    "EDL_STREAM_MAX_BACKLOG": "1024",
    "EDL_STREAM_FEATURES": "6",
    "EDL_STREAM_HOT_VOCAB": "400",
    "EDL_STREAM_DRIFT": "20",
    "EDL_STREAM_CHECKPOINT_EVERY": "2048",
    "EDL_EMB_ADMIT_K": "2",
    "EDL_EMB_MAX_ROWS": "256",
    "EDL_EMB_SWEEP_SECS": "1",
}
master = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.master.main",
    "--model_zoo", "elasticdl_tpu.models.deepfm",
    "--training_data", spool, "--records_per_task", "128",
    "--num_epochs", "1", "--port", str(mport),
    "--task_timeout_secs", "60", "--metrics_port", str(statz),
], env=env)
ps = subprocess.Popen([
    sys.executable, "-m", "elasticdl_tpu.ps.server", "--ps_id", "0",
    "--num_ps_pods", "1", "--port", str(pport),
    "--master_addr", "localhost:%d" % mport,
    "--opt_type", "adam", "--opt_args", "lr=0.01", "--use_async", "1",
    "--checkpoint_dir", ckpt, "--checkpoint_steps", "0",
], env=env)

def wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port)); return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)

wait_port(mport); wait_port(pport)
os.environ.update({k: env[k] for k in env if k.startswith("EDL_")})
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
mc = MasterClient("localhost:%d" % mport, worker_id=0)
mc.reset_worker()
worker = Worker(
    mc, "elasticdl_tpu.models.deepfm",
    RecordIODataReader(data_dir=spool), minibatch_size=32,
    wait_sleep_secs=0.1, ps_addrs=["localhost:%d" % pport],
)
runner = threading.Thread(target=worker.run, daemon=True)
runner.start()

# mid-job: the fleet /statusz must show the PS lifecycle gauges and
# the stream section (the PS telemetry rides its 5 s liveness poll)
statusz = None
deadline = time.time() + 180
while time.time() < deadline:
    try:
        body = json.load(urllib.request.urlopen(
            "http://127.0.0.1:%d/statusz" % statz, timeout=5))
    except Exception:
        time.sleep(1.0); continue
    entry = body.get("fleet", {}).get("ps-0")
    if entry and entry.get("ps_resident_rows", 0) > 0 and body.get("stream"):
        statusz = body
        break
    if master.poll() is not None:
        break
    time.sleep(1.0)
assert statusz is not None, "/statusz never showed lifecycle gauges"
assert statusz["stream"]["minted_records"] > 0, statusz["stream"]
print("statusz OK: ps_resident_rows=%d tracked=%d watermark=%d" % (
    statusz["fleet"]["ps-0"]["ps_resident_rows"],
    statusz["fleet"]["ps-0"]["ps_tracked_ids"],
    statusz["stream"]["watermark"]))

rc = master.wait(timeout=420)
assert rc == 0, "streaming job did not drain cleanly (rc=%s)" % rc
runner.join(timeout=120)

# flight record: tombstones + a watermark-cadence sparse checkpoint
from test_utils import load_journal
events = load_journal(events_dir)
kinds = {}
for e in events:
    kinds.setdefault(e.get("event"), []).append(e)
assert "row_admitted" in kinds, sorted(kinds)
assert "row_evicted" in kinds, sorted(kinds)
stream_ckpts = [e for e in kinds.get("checkpoint_saved", ())
                if e.get("kind") == "sparse_stream"]
assert stream_ckpts, "no watermark-cadence sparse checkpoint landed"
assert any(e.get("kind") == "closed"
           for e in kinds.get("stream_watermark", ())), "stream never closed"
assert os.listdir(ckpt), "checkpoint dir empty"

# an evicted id re-admits cleanly through fresh traffic (the PS
# outlives the master by its master-gone grace window)
import numpy as np
from elasticdl_tpu.worker.ps_client import PSClient
evicted = kinds["row_evicted"][0]
table, victim = evicted["table"], int(evicted["ids"][0])
client = PSClient(["localhost:%d" % pport], worker_id=9)
grads = {table: (np.full((1, 8 if table == "deepfm_emb" else 1), 0.1,
                         np.float32), np.array([victim], np.int64))}
for _ in range(6):
    client.push_gradients(grads, model_version=0)
    rows = client.pull_embedding_vectors(table, np.array([victim], np.int64))
    if not np.allclose(rows, 0.0):
        break
assert not np.allclose(rows, 0.0), "evicted id never re-admitted"
print("re-admission OK: %s/%d trains again after eviction" % (table, victim))

ps.terminate(); ps.wait(timeout=30)
print("streaming smoke OK: watermark checkpoints + tombstones + /statusz")
PYEOF
python scripts/postmortem.py "$STREAM_DIR/events" 2>/dev/null | tee /tmp/_stream_pm.out | head -5 || true
grep -q "row_evicted" /tmp/_stream_pm.out
grep -q "stream:" /tmp/_stream_pm.out

echo "== tier 1f: wire-path perf smoke (micro + EDL_WIRE_DTYPE opt-in) =="
# Microbenchmark of the ISSUE-5 wire fast paths vs the legacy paths
# they replaced: packed ids_blob vs repeated-varint serialization,
# sort+reduceat dedup vs np.add.at, vectorized numpy-store apply vs
# the per-id loop. Numbers are REPORT-ONLY (journaled below, never
# gated on — absolute timings flake across boxes); the script
# hard-fails only when a fast path measures >3x SLOWER than its legacy
# twin in the same run, which is a real regression, not noise.
python scripts/bench_wire_micro.py | tee /tmp/_wire_micro.json
printf '{"ts": "%s", "wire_micro": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_wire_micro.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "wire-micro numbers journaled to /tmp/ci_wire_micro.jsonl"

# Native PS data plane bench (ISSUE 11): identical duplicate-heavy
# Zipfian wire payloads through the native single-call pipeline vs
# the numpy pipeline it replaces. Absolute rows/sec are report-only
# (journaled below); the script hard-fails when the in-run native
# apply speedup drops below its 2x floor — the acceptance gate, and
# far stricter than the lane's usual >3x-regression rule.
python scripts/bench_ps_apply.py | tee /tmp/_ps_apply.json
printf '{"ts": "%s", "ps_apply": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_ps_apply.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "ps-apply bench journaled to /tmp/ci_wire_micro.jsonl"

# Serving-tier bench (ISSUE 8): open-loop Zipfian load at fixed QPS
# through the real gRPC serve stack, with a mid-run version swap.
# p50/p99 latency and QPS/chip are REPORT-ONLY (journaled below); the
# script hard-fails only on the swap contract — a request failed or
# shed across the run, the swap never completing, or the new version
# taking no traffic.
JAX_PLATFORMS=cpu python scripts/bench_serving.py | tee /tmp/_serving.json
printf '{"ts": "%s", "serving": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_serving.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "serving bench journaled to /tmp/ci_wire_micro.jsonl"

# Serving-FLEET bench (ISSUE 17): the same open-loop load pointed at
# the router fronting 4 serve-replica subprocesses over a real PS and
# a versioned export root. Latency/QPS are REPORT-ONLY (journaled
# below; the QPS target auto-scales by CPU count — 1-CPU CI boxes run
# the same protocol at lower pressure); the script hard-fails only on
# the fleet invariants — a failed client request anywhere across the
# replica SIGKILL, the canary promote, or the forced rollback; the
# killed replica not replaced; either canary cycle not completing; or
# a scale/canary decision missing its journaled reasons.
JAX_PLATFORMS=cpu python scripts/bench_serving.py --router --replicas 4 \
  | tee /tmp/_serving_fleet.json
printf '{"ts": "%s", "bench_serving_fleet": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_serving_fleet.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "serving-fleet bench journaled to /tmp/ci_wire_micro.jsonl"

# Device-tier A-B (ISSUE 6): deepfm steps/s with the HBM hot set on vs
# off under an emulated per-row wire cost, plus the warm-phase hit
# rate. Report-only journaled like the wire micro; the script
# hard-fails only on a >3x tier-on regression, a sub-0.9 Zipfian hit
# rate (promotion/demotion policy broke), or flush-parity corruption.
JAX_PLATFORMS=cpu python scripts/bench_device_tier.py | tee /tmp/_device_tier.json
printf '{"ts": "%s", "device_tier": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_device_tier.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "device-tier A-B journaled to /tmp/ci_wire_micro.jsonl"

# Streaming lifecycle bench (ISSUE 12): day-compressed Zipfian
# clickstream with vocab churn through the real PS servicer, lifecycle
# on vs the unbounded baseline. Absolute loss numbers are REPORT-ONLY
# (journaled below); the script hard-fails on the acceptance gates —
# resident rows over the bound, the baseline failing to demonstrate
# unbounded growth, holdout-tail logloss beyond tolerance, or a
# numpy<->native admitted-row parity break.
python scripts/bench_streaming.py | tee /tmp/_streaming.json
printf '{"ts": "%s", "streaming": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_streaming.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "streaming bench journaled to /tmp/ci_wire_micro.jsonl"

# Incremental-checkpoint bench (ISSUE 13): delta-chain durability on a
# Zipfian stream with bounded resident rows. Absolute timings are
# report-only (journaled below); the script hard-fails the acceptance
# gates — delta save under 5x faster than a full save of the same
# store, worker-observed push p99 during off-RPC checkpoints beyond
# 1.5x the no-checkpoint baseline (the real-PS-subprocess measurement;
# the pre-ISSUE-13 inline stall is reported in the same run), or a
# base+delta restore that is not bit-identical to a full-save restore
# on either backend (tombstoned ids staying dead included).
JAX_PLATFORMS=cpu python scripts/bench_checkpoint.py | tee /tmp/_checkpoint.json
printf '{"ts": "%s", "checkpoint": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_checkpoint.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "checkpoint bench journaled to /tmp/ci_wire_micro.jsonl"

# Profiler overhead A/B (ISSUE 14): deepfm steps/s with the 29 Hz
# sampler started vs stopped, interleaved inside ONE process so box
# drift cancels. Absolute steps/s are report-only (journaled below);
# the script hard-fails the acceptance gate — measured overhead above
# 3% (after one re-measure; a real sampler regression fails both
# passes) or a sampler that collected no samples at all.
JAX_PLATFORMS=cpu python scripts/bench_profiler_overhead.py | tee /tmp/_prof_overhead.json
printf '{"ts": "%s", "prof_overhead": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_prof_overhead.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "profiler-overhead A/B journaled to /tmp/ci_wire_micro.jsonl"

# Health-scalar overhead A/B (ISSUE 15): deepfm steps/s with the
# in-graph health scalars + tracker on vs the pre-health program,
# interleaved inside ONE process so box drift cancels. Absolute
# steps/s are report-only (journaled below); the script hard-fails
# the acceptance gate — measured overhead above 2% (after one
# re-measure) or a tracker that saw no batches.
JAX_PLATFORMS=cpu python scripts/bench_health_overhead.py | tee /tmp/_health_overhead.json
printf '{"ts": "%s", "health_overhead": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_health_overhead.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "health-overhead A/B journaled to /tmp/ci_wire_micro.jsonl"

# Device-obs overhead A/B (ISSUE 18): deepfm steps/s with the
# recompile sentinel + HBM/cost accounting on vs raw jax.jit step
# fns, interleaved inside ONE process so box drift cancels. Absolute
# steps/s are report-only (journaled below); the script hard-fails
# the acceptance gate — measured overhead above 2% (after one
# re-measure) or a sentinel that recorded no compiles/cache hits.
JAX_PLATFORMS=cpu python scripts/bench_device_obs_overhead.py | tee /tmp/_device_obs_overhead.json
printf '{"ts": "%s", "device_obs_overhead": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_device_obs_overhead.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "device-obs-overhead A/B journaled to /tmp/ci_wire_micro.jsonl"

# Span-id entropy A/B (ISSUE 15 satellite): buffered 4 KiB entropy
# pool vs the per-call os.urandom it replaced (PR 14's profiler
# measured the syscall at ~5-7% of traced-run host samples).
# Report-only numbers; hard-fails only if the pool fails to beat the
# per-call path or deals a duplicate id.
python scripts/bench_span_entropy.py | tee /tmp/_span_entropy.json
printf '{"ts": "%s", "span_entropy": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_span_entropy.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "span-entropy A/B journaled to /tmp/ci_wire_micro.jsonl"

# Overload containment A/B (ISSUE 19): bounded-retry clients vs a
# naive retry storm against the same saturated PS, plus a flap-window
# breaker recovery drill. Hard gates (attempt amplification, bit-exact
# zero-lost-updates, probe-window recovery) apply when the bench runs
# directly; in CI it journals report-only so the trend watchdog tracks
# the amplification ratio across runs. Reduced window keeps the lane
# cheap.
JAX_PLATFORMS=cpu python scripts/bench_overload.py \
  --slow-secs 4 --pushes 8 --report-only | tee /tmp/_overload.json
printf '{"ts": "%s", "overload": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_overload.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "overload containment A/B journaled to /tmp/ci_wire_micro.jsonl"

echo "== tier 1g: dense data plane smoke (2-process CPU mesh, no PS on the dense path) =="
# Dense-plane contract (ISSUE 20): a real 2-worker jax.distributed
# deepfm job (dp=2 CPU mesh over gloo) against an in-process master
# and a live PS subprocess. Hard gates: the PS's scraped byte counters
# must show embedding-row pushes > 0 while
# edl_ps_push_dense_bytes_total stays exactly 0 (dense gradients
# reduce on-mesh, never over the PS), and both workers must report
# mesh_shape=dp=2 dense-plane telemetry to the FleetMonitor. Timings
# are report-only (journaled below).
JAX_PLATFORMS=cpu python scripts/bench_dense_plane.py | tee /tmp/_dense_plane.json
printf '{"ts": "%s", "dense_plane": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_dense_plane.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "dense-plane smoke journaled to /tmp/ci_wire_micro.jsonl"

# Bench-trend watchdog (ISSUE 14): folds the repo's BENCH_r*.json
# series plus everything this run just journaled above into per-metric
# trajectories and flags any metric >20% worse than its best recorded
# value. REPORT-ONLY (absolute numbers flake across boxes — a flag is
# a prompt to look, not a failure); runs after every journaling bench
# so it sees this run's own numbers, and its report is journaled so
# the watchdog has a history too.
python scripts/bench_trend.py --journal /tmp/ci_wire_micro.jsonl \
  | tee /tmp/_bench_trend.json
printf '{"ts": "%s", "bench_trend": %s}\n' \
  "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$(cat /tmp/_bench_trend.json)" \
  >> /tmp/ci_wire_micro.jsonl
echo "bench-trend report journaled to /tmp/ci_wire_micro.jsonl"

# The reduced-precision wire opt-in must actually train: a sparse
# local-executor run with EDL_WIRE_DTYPE=bfloat16 (LocalPSClient
# round-trips payloads through the wire dtype, emulating exactly the
# rounding a real worker<->PS deployment under the knob sees).
JAX_PLATFORMS=cpu EDL_WIRE_DTYPE=bfloat16 python - <<'PYEOF'
import sys, tempfile
sys.path.insert(0, "tests")
from test_utils import create_ctr_recordio
from elasticdl_tpu.train.local_executor import LocalExecutor

with tempfile.TemporaryDirectory() as tmp:
    create_ctr_recordio(tmp + "/f0.rec", num_records=256, seed=0)
    executor = LocalExecutor(
        "elasticdl_tpu.models.deepfm", training_data=tmp,
        minibatch_size=64, num_epochs=2,
    )
    losses = executor.train()
    assert all(l == l for l in losses), "NaN loss under bfloat16 wire"
    assert losses[-1] < losses[0], (
        "bfloat16 wire run did not learn: %s" % losses
    )
print("EDL_WIRE_DTYPE=bfloat16 opt-in trains OK")
PYEOF

echo "== tier 2a: multi-chip SPMD dryrun (dp/fsdp, tp/sp, ep, pp, pp x tp) =="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== tier 2b: client dry-run job submission =="
JAX_PLATFORMS=cpu python -m elasticdl_tpu.client.main train \
  --model_zoo elasticdl_tpu/models \
  --model_def mnist.custom_model \
  --training_data /tmp/does-not-matter \
  --num_workers 2 --num_ps_pods 1 \
  --image_name elasticdl-tpu:ci \
  --job_name ci-dryrun --dry_run > /dev/null

echo "CI tiers 1-2 OK (tier 1a sanitizers — tsan: $TSAN_STATUS, asan: $ASAN_STATUS)"
