#!/usr/bin/env bash
# One-command local reproduction of CI tiers 1-2
# (.github/workflows/ci.yml; reference pipeline: .travis.yml:30-98).
#
# Lanes (reference parity: the travis fast/slow tier split):
#   scripts/ci.sh        — fast lane: unit suite minus @slow (<5 min)
#   scripts/ci.sh full   — everything, incl. multi-minute live-process
#                          e2es (chaos, multi-worker sparse, convergence)
set -euo pipefail
cd "$(dirname "$0")/.."

LANE="${1:-fast}"

echo "== tier 1a: native store build + TSAN race stress =="
make -C elasticdl_tpu/native
make -C elasticdl_tpu/native tsan
make -C elasticdl_tpu/native asan

echo "== tier 1c: edlint static analysis =="
# zero-findings gate (both lanes): new findings are fixed, suppressed
# with a comment, or baselined with a justification — never ignored.
# Also runs inside the fast suite as tests/test_static_analysis.py
# (-m lint selects just the gate).
python -m elasticdl_tpu.analysis elasticdl_tpu/

if [ "$LANE" = "full" ]; then
  echo "== tier 1b: FULL unit suite (8-virtual-device CPU mesh) =="
  python -m pytest tests/ -x -q
else
  echo "== tier 1b: fast-lane unit suite (pytest -m 'not slow') =="
  python -m pytest tests/ -x -q -m "not slow"
fi

echo "== tier 2a: multi-chip SPMD dryrun (dp/fsdp, tp/sp, ep, pp, pp x tp) =="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== tier 2b: client dry-run job submission =="
JAX_PLATFORMS=cpu python -m elasticdl_tpu.client.main train \
  --model_zoo elasticdl_tpu/models \
  --model_def mnist.custom_model \
  --training_data /tmp/does-not-matter \
  --num_workers 2 --num_ps_pods 1 \
  --image_name elasticdl-tpu:ci \
  --job_name ci-dryrun --dry_run > /dev/null

echo "CI tiers 1-2 OK"
