"""Native PS data plane microbench: GIL-free deserialize+dedup+apply.

The ISSUE-11 gate for the native embedding store: identical wire
payloads (packed ids_blob + raw gradient rows, duplicate-heavy Zipfian
id stream) pushed through

- the NATIVE pipeline: one ``edl_store_apply_blob`` C call per table
  (deserialize + dedup + optimizer apply with the GIL released), and
- the NUMPY pipeline it replaces: ``unpack_ids`` + ``blob_to_ndarray``
  + fp32 upcast + ``deduplicate_indexed_slices`` +
  ``NumpyEmbeddingStore.push_gradients``,

plus the same A-B for the pull side (``lookup_blob`` with the
wire-dtype cast in C vs lookup + astype + tobytes).

Prints ONE JSON line. Exit 1 when the native apply speedup is below
``--min-speedup`` (default 2.0 — the acceptance floor; CI additionally
journals the absolute numbers report-only). Measured best-of-``reps``
so a loaded box underestimates, never flakes upward.

The parity of the two pipelines is NOT this script's job — that is
bit-exact-tested in tests/test_native_parity.py; this only measures.
"""

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

from elasticdl_tpu.common.tensor_utils import (  # noqa: E402
    blob_to_ndarray,
    deduplicate_indexed_slices,
    serialize_indexed_slices,
    unpack_ids,
)
from elasticdl_tpu.ps.embedding_store import (  # noqa: E402
    NativeEmbeddingStore,
    NumpyEmbeddingStore,
    native_lib,
)


def zipf_ids(rng, n, vocab, a=1.3):
    """Duplicate-heavy Zipfian id stream: the CTR-shaped workload the
    dedup path exists for (a few hot ids dominate every batch)."""
    ids = rng.zipf(a, size=n)
    return np.minimum(ids, vocab).astype(np.int64)


def timeit(fn, reps):
    best = float("inf")
    for _ in range(reps):
        start = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - start)
    return best


def build_store(cls, opt, dim, tables):
    store = cls(seed=11)
    store.set_optimizer(opt, lr=0.01)
    for name in tables:
        store.create_table(name, dim, init_scale=0.05)
    return store


def main():
    parser = argparse.ArgumentParser(__doc__)
    parser.add_argument("--rows", type=int, default=8192,
                        help="ids per push per table")
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--vocab", type=int, default=200000)
    parser.add_argument("--tables", type=int, default=2)
    parser.add_argument("--pushes", type=int, default=8,
                        help="pushes per timed round")
    parser.add_argument("--reps", type=int, default=5,
                        help="timed rounds; best is reported")
    parser.add_argument("--opt", default="adam")
    parser.add_argument("--min-speedup", type=float, default=2.0,
                        help="hard floor for native/numpy apply "
                             "throughput (0 disables the gate)")
    args = parser.parse_args()

    if native_lib() is None:
        # no C++ toolchain on this box: report and succeed — the CI
        # image has one, so the gate still runs where it matters
        print(json.dumps({"skipped": "native store unavailable"}))
        return 0

    tables = ["t%d" % i for i in range(args.tables)]
    rng = np.random.RandomState(0)
    pushes = []
    for _ in range(args.pushes):
        push = {}
        for name in tables:
            ids = zipf_ids(rng, args.rows, args.vocab)
            grads = rng.randn(args.rows, args.dim).astype(np.float32)
            push[name] = serialize_indexed_slices(grads, ids)
        pushes.append(push)
    dup_rate = 1.0 - float(np.mean([
        np.unique(unpack_ids(s)).size / args.rows
        for push in pushes for s in push.values()
    ]))

    native = build_store(NativeEmbeddingStore, args.opt, args.dim, tables)
    ref = build_store(NumpyEmbeddingStore, args.opt, args.dim, tables)

    def native_apply():
        for push in pushes:
            for name, slices in push.items():
                native.push_gradients_blob(
                    name,
                    np.frombuffer(slices.ids_blob, dtype="<i8"),
                    slices.concat_tensors.content,
                    slices.concat_tensors.dtype,
                )

    def numpy_apply():
        for push in pushes:
            for name, slices in push.items():
                values, ids = blob_to_ndarray(slices.concat_tensors), \
                    unpack_ids(slices)
                if values.dtype != np.float32:
                    values = values.astype(np.float32)
                values, ids = deduplicate_indexed_slices(values, ids)
                ref.push_gradients(name, ids, values)

    rows_per_round = args.rows * args.tables * args.pushes
    native_s = timeit(native_apply, args.reps)
    numpy_s = timeit(numpy_apply, args.reps)

    pull_ids = np.unique(zipf_ids(rng, args.rows, args.vocab))

    def native_pull():
        for name in tables:
            native.lookup_blob(name, pull_ids)

    def numpy_pull():
        for name in tables:
            ref.lookup(name, pull_ids).tobytes()

    native_pull_s = timeit(native_pull, args.reps)
    numpy_pull_s = timeit(numpy_pull, args.reps)

    speedup = numpy_s / native_s if native_s > 0 else float("inf")
    out = {
        "rows_per_push": args.rows,
        "dim": args.dim,
        "tables": args.tables,
        "opt": args.opt,
        "duplicate_rate": round(dup_rate, 4),
        "native_apply_rows_per_sec": round(rows_per_round / native_s),
        "numpy_apply_rows_per_sec": round(rows_per_round / numpy_s),
        "apply_speedup": round(speedup, 2),
        "native_pull_rows_per_sec": round(
            pull_ids.size * args.tables * 1.0 / native_pull_s
        ),
        "numpy_pull_rows_per_sec": round(
            pull_ids.size * args.tables * 1.0 / numpy_pull_s
        ),
        "pull_speedup": round(numpy_pull_s / native_pull_s, 2),
    }
    print(json.dumps(out))
    if args.min_speedup > 0 and speedup < args.min_speedup:
        print(
            "FAIL: native apply speedup %.2fx below the %.1fx floor"
            % (speedup, args.min_speedup),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
