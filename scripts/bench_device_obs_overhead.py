#!/usr/bin/env python
"""Device-observability overhead gate (ISSUE 18): deepfm steps/s,
EDL_DEVICE_OBS on vs off.

The recompile-sentinel contract is "watching the compiler costs
nothing you can measure": the instrumented jit wrapper's steady-state
work (clock read, one ``_cache_size()`` probe, counter bumps) must
keep deepfm CTR steps/s within 2% of a run whose step functions are
raw ``jax.jit``. This bench builds TWO trainers in ONE process — the
env gate is read when ``instrumented_jit`` wraps the step fn, so the
"off" trainer is constructed under ``EDL_DEVICE_OBS=0`` and comes out
holding pristine PjitFunctions — and alternates measurement segments
between them (off-on, on-off, ...) so box drift cancels, the same
discipline as ``bench_health_overhead.py``.

Absolute steps/s are REPORT-ONLY (journaled by ci.sh tier 1f like
every bench); the script hard-fails only the acceptance gate:
measured overhead above 2% (with one full re-measure first — a single
GC pause can eat 2% on its own; a real regression fails both passes),
or an instrumented trainer whose sentinel saw no compiles/hits at all
(the A/B would be vacuous).
"""

import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, ".")

GATE = 0.02
WARMUP_STEPS = 12
DISTINCT_BATCHES = 30
SEGMENT_STEPS = 150
SEGMENTS_PER_MODE = 3


def make_batches(n, batch=256, fields=16, vocab=10_000, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = (rng.zipf(1.3, size=(batch, fields)) % vocab).astype(
            np.int64
        )
        out.append({
            "features": {"ids": ids},
            "labels": rng.randint(0, 2, batch).astype(np.float32),
            "_mask": np.ones(batch, np.float32),
        })
    return out


def build_trainer(device_obs):
    """The EDL_DEVICE_OBS gate is consulted at wrapper-creation time
    (trainer construction), so set it in os.environ for the duration
    of the constructor — afterwards the trainer is committed either
    way and the env no longer matters."""
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.ps.local_client import LocalPSClient
    from elasticdl_tpu.train.sparse import SparseTrainer

    # save/restore around construction, not a config read — the knob
    # helpers have no setter  # edlint: disable=knob-registry
    saved = os.environ.get("EDL_DEVICE_OBS")
    os.environ["EDL_DEVICE_OBS"] = "1" if device_obs else "0"
    try:
        return SparseTrainer(
            model=deepfm.custom_model(),
            loss_fn=deepfm.loss,
            optimizer=deepfm.optimizer(),
            specs=deepfm.sparse_embedding_specs(
                num_features=16, batch_size=256
            ),
            ps_client=LocalPSClient(seed=0, opt_type="adam", lr=0.001),
            seed=0,
            health=False,
        )
    finally:
        if saved is None:
            os.environ.pop("EDL_DEVICE_OBS", None)
        else:
            os.environ["EDL_DEVICE_OBS"] = saved


def run_segment(trainer, state, batches):
    start = time.perf_counter()
    for step in range(SEGMENT_STEPS):
        state, loss = trainer.train_step(
            state, batches[step % len(batches)]
        )
    float(loss)  # join any async device work before stopping the clock
    elapsed = time.perf_counter() - start
    return state, SEGMENT_STEPS / elapsed


def measure(trainers, states, batches):
    """Interleaved off/on segments, pair order alternating (same
    rationale as bench_profiler_overhead.measure: a warming/cooling
    box must not hand either mode a systematic position edge)."""
    off = []
    on = []

    def run(mode):
        states[mode], sps = run_segment(
            trainers[mode], states[mode], batches
        )
        (off if mode == "off" else on).append(sps)

    for pair in range(SEGMENTS_PER_MODE):
        first, second = (
            ("off", "on") if pair % 2 == 0 else ("on", "off")
        )
        run(first)
        run(second)
    return statistics.median(off), statistics.median(on)


def main():
    trainers = {
        "off": build_trainer(False), "on": build_trainer(True),
    }
    batches = make_batches(DISTINCT_BATCHES)
    states = {"off": None, "on": None}
    for mode in ("off", "on"):
        for batch in batches[:WARMUP_STEPS]:
            states[mode], loss = trainers[mode].train_step(
                states[mode], batch
            )
        float(loss)

    off_sps, on_sps = measure(trainers, states, batches)
    overhead = 1.0 - on_sps / off_sps
    if overhead > GATE:
        # one re-measure before failing: a GC pause or noisy CI
        # neighbor can eat 2% on its own; a real regression repeats
        off2, on2 = measure(trainers, states, batches)
        if 1.0 - on2 / off2 < overhead:
            off_sps, on_sps = off2, on2
            overhead = 1.0 - on2 / off2

    from elasticdl_tpu.observability import device as device_obs

    stats = device_obs.compile_stats()
    sentinel_events = sum(
        entry["compiles"] + entry["cache_hits"]
        for entry in stats.values()
    )
    for trainer in trainers.values():
        trainer.close()

    result = {
        "deepfm_device_obs_overhead_ratio": round(overhead, 4),
        "deepfm_steps_per_sec_device_obs_off": round(off_sps, 3),
        "deepfm_steps_per_sec_device_obs_on": round(on_sps, 3),
        "device_obs_sentinel_events": sentinel_events,
    }
    print(json.dumps(result))
    if sentinel_events <= 0:
        print(
            "bench_device_obs_overhead: FAIL the instrumented trainer "
            "recorded 0 compiles/cache-hits — the A/B measured nothing",
            file=sys.stderr,
        )
        return 1
    if overhead > GATE:
        print(
            "bench_device_obs_overhead: FAIL %.1f%% overhead exceeds "
            "the %.0f%% contract (off %.2f vs on %.2f steps/s)"
            % (overhead * 100, GATE * 100, off_sps, on_sps),
            file=sys.stderr,
        )
        return 1
    print(
        "device-obs overhead %.2f%% (off %.2f, on %.2f steps/s)"
        % (overhead * 100, off_sps, on_sps),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
