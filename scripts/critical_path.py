#!/usr/bin/env python
"""Critical-path attribution over a merged EDL distributed trace.

Consumes an ``EDL_TRACE_DIR`` (or an already-merged
``merged.trace.json``) whose spans carry the ISSUE-9 trace context
(``trace_id``/``span_id``/``parent_id`` args) and answers the question
the ROADMAP items keep asking: *which segment* of the step / predict
path is hot. For every trace it walks the span tree and attributes
each span's SELF time (duration minus the union of its children's
intervals — the time that span was the deepest thing running) to a
segment:

==================  =====================================================
segment             spans
==================  =====================================================
queue_wait          master ``dispatch`` / ``Master/*`` handler spans;
                    the ``serve_predict`` root's self time (admission
                    queue + batch formation wait)
pull                ``ps_pull`` / ``ps_pull_batch`` client spans and
                    ``Pserver/pull_*`` handler spans
push                ``ps_push`` / ``ps_push_rows`` client spans
apply               ``ps_apply_push`` and ``Pserver/push_*`` handler
                    spans (server-side deserialize + optimizer apply)
compute             the ``train_batch`` root's self time (forward /
                    backward / device step) and ``serve_batch_run``
                    (the batched forward)
compile             ``compile`` spans from the ISSUE-18 recompile
                    sentinel — XLA compiles caught on the step path;
                    a steady-state trace showing this segment IS the
                    recompile storm, attributed to the step it stalled
transfer            ``transfer`` spans (ISSUE 18): explicit host<->
                    device movement — output fetches, device-tier
                    gradient extraction
shed                the full duration of a predict trace whose root
                    failed with RESOURCE_EXHAUSTED / DEADLINE_EXCEEDED
other               anything unrecognized (kept visible, never dropped)
==================  =====================================================

Unmapped spans (``rpc_attempt``, future names) inherit the nearest
mapped ancestor's segment, so retry wire time lands in pull/push where
it belongs. The report gives per-trace-kind (train step / predict)
p50/p99 per segment plus the critical-path breakdown (each segment's
share of total attributed time), and the per-trace role census CI
gates on (a step trace must span worker AND ps).

Report-only by design: CI journals the JSON (tier 1d, like the tier 1f
benches) and asserts only the structural invariants.

**Frame attribution (ISSUE 14).** With ``--frames`` pointing at
``/profilez`` captures (files or a dir of ``*.profile.json``) from the
same run, the report adds a ``frames`` section: the continuous
profiler tags each sample landing inside an open sampled span with
that span's critical-path segment, so every segment above breaks down
into the top-K Python frame stacks that actually burned it —
"``apply`` is 40% of the step" becomes "``apply`` is 40%, and it's
``embedding_store.push_gradients`` → ``np.add.reduceat``".

Usage:
    python scripts/critical_path.py TRACE_DIR [--slowest N] [-o out.json]
        [--frames PROFILES] [--frames-top K]

stdout is the JSON report; the human-readable table goes to stderr.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import merge_trace  # noqa: E402
from merge_trace import (  # noqa: E402 - shared capture helpers
    load_events,
    normalize_role,
    percentile as _percentile,
    role_by_pid,
)

SHED_CODES = ("RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED")

# exact span name -> segment
_SEGMENT_BY_NAME = {
    "dispatch": "queue_wait",
    "ps_pull": "pull",
    "ps_pull_batch": "pull",
    "ps_push": "push",
    "ps_push_rows": "push",
    "ps_apply_push": "apply",
    "serve_batch_run": "compute",
    # device runtime (ISSUE 18): the recompile sentinel's compile
    # spans and explicit host<->device transfer spans
    "compile": "compile",
    "transfer": "transfer",
}

# root-span name -> segment its SELF time belongs to
_ROOT_SELF_SEGMENT = {
    "train_batch": "compute",
    "serve_predict": "queue_wait",
}

_ROOT_KIND = {
    "train_batch": "step",
    "serve_predict": "predict",
}


def segment_of(name):
    """Segment for a span name, or None (= inherit the ancestor's)."""
    seg = _SEGMENT_BY_NAME.get(name)
    if seg is not None:
        return seg
    if name.startswith("Pserver/pull"):
        return "pull"
    if name.startswith("Pserver/push"):
        return "apply"
    if name.startswith("Master/"):
        return "queue_wait"
    return None


def _union_secs(intervals):
    """Total length covered by a list of (start, end) intervals."""
    total = 0.0
    last_end = None
    for start, end in sorted(intervals):
        if last_end is None or start > last_end:
            total += end - start
            last_end = end
        elif end > last_end:
            total += end - last_end
            last_end = end
    return total


def analyze_trace(spans, roles_of_pids):
    """Attribution for ONE trace's spans: (record dict) or None when
    the trace has no identifiable root."""
    by_id = {}
    for event in spans:
        span_id = event["args"].get("span_id")
        if span_id:
            by_id[span_id] = event
    children = {}
    roots = []
    for event in spans:
        parent = event["args"].get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(event)
        else:
            roots.append(event)
    if not roots:
        return None
    roots.sort(key=lambda e: e["ts"])
    root = roots[0]
    root_name = root["name"]
    duration_ms = root.get("dur", 0.0) / 1e3
    roles = set()
    for event in spans:
        role = event["args"].get("role") or roles_of_pids.get(
            event.get("pid"), ""
        )
        if role:
            roles.add(normalize_role(role))

    segments = {}

    code = root["args"].get("code")
    if root_name == "serve_predict" and code in SHED_CODES:
        segments["shed"] = duration_ms
        return {
            "trace_id": root["args"].get("trace_id", ""),
            "kind": _ROOT_KIND.get(root_name, "other"),
            "root": root_name,
            "duration_ms": duration_ms,
            "roles": sorted(roles),
            "segments": segments,
            "shed": True,
        }

    def attribute(event, inherited):
        name = event["name"]
        seg = segment_of(name)
        if seg is None:
            seg = (
                _ROOT_SELF_SEGMENT.get(name)
                if event is root
                else inherited
            ) or "other"
        start = event["ts"]
        end = start + event.get("dur", 0.0)
        kids = children.get(event["args"].get("span_id"), [])
        intervals = []
        for kid in kids:
            kid_start = max(start, kid["ts"])
            kid_end = min(end, kid["ts"] + kid.get("dur", 0.0))
            if kid_end > kid_start:
                intervals.append((kid_start, kid_end))
        # ts/dur are microseconds; self time = span minus the union of
        # its children's (clipped) intervals
        self_ms = max(
            0.0, (end - start) - _union_secs(intervals)
        ) / 1e3
        segments[seg] = segments.get(seg, 0.0) + self_ms
        for kid in kids:
            attribute(kid, seg)

    # attribute every top-level span (the root plus any span whose
    # parent lived in a process that never flushed — clock-aligned
    # orphans still count rather than vanish)
    for top in roots:
        attribute(top, None)
    return {
        "trace_id": root["args"].get("trace_id", ""),
        "kind": _ROOT_KIND.get(root_name, "other"),
        "root": root_name,
        "duration_ms": duration_ms,
        "roles": sorted(roles),
        "segments": segments,
        "shed": False,
    }


def _summarize(records):
    durations = [r["duration_ms"] for r in records]
    segment_values = {}
    for record in records:
        for seg, ms in record["segments"].items():
            segment_values.setdefault(seg, []).append(ms)
    total_attributed = sum(sum(v) for v in segment_values.values())
    segments = {}
    for seg, values in sorted(segment_values.items()):
        seg_total = sum(values)
        # traces where the segment never appeared count as 0 for the
        # percentiles: "pull was 0 in half the steps" is signal
        padded = values + [0.0] * (len(records) - len(values))
        segments[seg] = {
            "p50_ms": round(_percentile(padded, 0.50), 3),
            "p99_ms": round(_percentile(padded, 0.99), 3),
            "mean_ms": round(seg_total / len(records), 3),
            "share": round(
                seg_total / total_attributed if total_attributed else 0.0,
                4,
            ),
        }
    multi_role = sum(1 for r in records if len(r["roles"]) >= 2)
    all_roles = sorted({role for r in records for role in r["roles"]})
    return {
        "count": len(records),
        "p50_ms": round(_percentile(durations, 0.50), 3),
        "p99_ms": round(_percentile(durations, 0.99), 3),
        "roles": all_roles,
        "multi_role_traces": multi_role,
        "segments": segments,
    }


def build_report(events, slowest=10):
    roles_of_pids = role_by_pid(events)
    by_trace = {}
    for event in events:
        if event.get("ph") != "X":
            continue
        trace_id = (event.get("args") or {}).get("trace_id")
        if not trace_id:
            continue
        by_trace.setdefault(trace_id, []).append(event)
    records = []
    for spans in by_trace.values():
        record = analyze_trace(spans, roles_of_pids)
        if record is not None:
            records.append(record)
    report = {
        "traces": len(records),
        "slowest": sorted(
            records, key=lambda r: -r["duration_ms"]
        )[:slowest],
    }
    for kind in ("step", "predict"):
        of_kind = [r for r in records if r["kind"] == kind]
        if of_kind:
            report[kind] = _summarize(of_kind)
    other = [r for r in records if r["kind"] == "other"]
    if other:
        report["other"] = _summarize(other)
    return report


def load_profiles(path_spec):
    """/profilez capture dicts from a comma-separated list of files
    and/or directories (discovery + tolerant load shared with
    scripts/profile_report.py)."""
    import profile_report

    paths = [p.strip() for p in path_spec.split(",") if p.strip()]
    return [
        capture
        for _path, capture in profile_report.load_captures(
            profile_report.discover(paths)
        )
    ]


def frames_by_segment(profiles, top=3):
    """{segment: [{stack, count, roles}]}: the top-K span-tagged frame
    stacks per critical-path segment, merged across roles. Untagged
    samples (no open span at sample time) are excluded — they have no
    segment to attribute to."""
    tally = {}  # segment -> stack tuple -> [count, roles set]
    for profile in profiles:
        role = profile.get("role", "?")
        for entry in profile.get("stacks", ()):
            segment = entry.get("segment")
            if not segment:
                continue
            stack = tuple(entry.get("stack", ()))
            if not stack:
                continue
            bucket = tally.setdefault(segment, {})
            slot = bucket.get(stack)
            if slot is None:
                bucket[stack] = [int(entry.get("count", 0)), {role}]
            else:
                slot[0] += int(entry.get("count", 0))
                slot[1].add(role)
    return {
        segment: [
            {
                "stack": list(stack),
                "count": count,
                "roles": sorted(roles),
            }
            for stack, (count, roles) in sorted(
                bucket.items(), key=lambda kv: (-kv[1][0], kv[0])
            )[:top]
        ]
        for segment, bucket in sorted(tally.items())
    }


def render_text(report, out=sys.stderr):
    print("critical-path attribution: %d trace(s)" % report["traces"],
          file=out)
    for kind in ("step", "predict", "other"):
        summary = report.get(kind)
        if not summary:
            continue
        print(
            "%s: n=%d p50=%.2fms p99=%.2fms roles=%s (%d multi-role)"
            % (kind, summary["count"], summary["p50_ms"],
               summary["p99_ms"], ",".join(summary["roles"]),
               summary["multi_role_traces"]),
            file=out,
        )
        for seg, stats in sorted(
            summary["segments"].items(), key=lambda kv: -kv[1]["share"]
        ):
            print(
                "  %-12s %5.1f%%  p50=%8.3fms  p99=%8.3fms"
                % (seg, stats["share"] * 100, stats["p50_ms"],
                   stats["p99_ms"]),
                file=out,
            )
    for record in report["slowest"][:5]:
        print(
            "  slow %s %s %.2fms %s"
            % (record["root"], record["trace_id"][:16],
               record["duration_ms"], record["roles"]),
            file=out,
        )
    frames = report.get("frames")
    if frames:
        print("segment frame stacks (continuous profiler):", file=out)
        for segment, stacks in frames.items():
            print("  %s:" % segment, file=out)
            for entry in stacks:
                # leaf-most frames carry the signal; elide long roots
                stack = entry["stack"]
                shown = ";".join(stack[-4:])
                if len(stack) > 4:
                    shown = "...;" + shown
                print(
                    "    %6d  %s" % (entry["count"], shown), file=out
                )


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "trace_path",
        help="EDL_TRACE_DIR of the run, or a merged.trace.json",
    )
    parser.add_argument("--slowest", type=int, default=10,
                        help="slowest-N traces to include (default 10)")
    parser.add_argument("-o", "--output", default="",
                        help="also write the JSON report here")
    parser.add_argument(
        "--frames", default="",
        help="comma-separated /profilez capture files or dirs of "
             "*.profile.json from the same run: break each segment "
             "down into its top span-tagged frame stacks (ISSUE 14)",
    )
    parser.add_argument("--frames-top", type=int, default=3,
                        help="frame stacks per segment (default 3)")
    args = parser.parse_args(argv)
    events = load_events(args.trace_path)
    report = build_report(events, slowest=args.slowest)
    if args.frames:
        report["frames"] = frames_by_segment(
            load_profiles(args.frames), top=args.frames_top
        )
    render_text(report)
    text = json.dumps(report)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
