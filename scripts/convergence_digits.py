#!/usr/bin/env python
"""Real-data convergence artifact: the mnist zoo CNN on sklearn's
scanned handwritten digits (1,797 real images, Optical Recognition of
Handwritten Digits, UCI).

The reference published convergence-under-elasticity curves on real
workloads (docs/benchmark/report_cn.md:106-117); this is the
counterpart this environment can run with zero egress (the full MNIST
download is unreachable). Digits are upsampled 8x8 -> 28x28 so the
stock ``elasticdl_tpu.models.mnist`` CNN runs unmodified.

Writes docs/CONVERGENCE.md with the loss curve and held-out accuracy.
Run: JAX_PLATFORMS=cpu python scripts/convergence_digits.py
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def write_digits_recordio(images, labels, path):
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import write_records

    payloads = []
    for image, label in zip(images, labels):
        big = np.kron(image, np.ones((4, 4)))[2:30, 2:30]  # 8x8 -> 28x28
        big = (big / 16.0 * 255.0).clip(0, 255)
        payloads.append(encode_example({
            "image": big.astype(np.uint8),
            "label": np.int64(label),
        }))
    write_records(path, payloads)


def main():
    from sklearn import datasets

    from elasticdl_tpu.train.local_executor import LocalExecutor

    digits = datasets.load_digits()
    images, labels = digits.images, digits.target
    rng = np.random.RandomState(0)
    order = rng.permutation(len(images))
    images, labels = images[order], labels[order]
    n_train = 1500
    root = tempfile.mkdtemp(prefix="digits_")
    train_dir = os.path.join(root, "train")
    valid_dir = os.path.join(root, "valid")
    os.makedirs(train_dir)
    os.makedirs(valid_dir)
    write_digits_recordio(
        images[:n_train], labels[:n_train],
        os.path.join(train_dir, "f0.rec"),
    )
    write_digits_recordio(
        images[n_train:], labels[n_train:],
        os.path.join(valid_dir, "f0.rec"),
    )

    epochs = 20
    executor = LocalExecutor(
        "elasticdl_tpu.models.mnist",
        training_data=train_dir,
        validation_data=valid_dir,
        minibatch_size=64,
        num_epochs=epochs,
    )
    losses = executor.train()
    summary = executor.evaluate()
    accuracy = float(summary["accuracy"])

    steps_per_epoch = max(1, len(losses) // epochs)
    curve = [
        (epoch, float(np.mean(
            losses[epoch * steps_per_epoch:(epoch + 1) * steps_per_epoch]
        )))
        for epoch in range(epochs)
    ]
    doc = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "CONVERGENCE.md",
    )
    with open(doc, "w") as f:
        f.write(
            "# Real-data convergence: handwritten digits\n\n"
            "Produced by `scripts/convergence_digits.py` — the stock\n"
            "`elasticdl_tpu.models.mnist` CNN trained on sklearn's\n"
            "scanned handwritten digits (1,797 real 8x8 images, UCI\n"
            "optdigits; upsampled to 28x28), %d train / %d held out.\n\n"
            "**Held-out accuracy: %.4f** after %d epochs.\n\n"
            "| epoch | mean train loss |\n|---|---|\n"
            % (n_train, len(images) - n_train, accuracy, epochs)
        )
        for epoch, loss_value in curve:
            f.write("| %d | %.4f |\n" % (epoch + 1, loss_value))
        f.write(
            "\nReference counterpart: convergence curves on real"
            " workloads in docs/benchmark/report_cn.md:106-117.\n"
        )
    print("accuracy %.4f -> %s" % (accuracy, doc))
    assert accuracy >= 0.97, "digits convergence regressed: %f" % accuracy


if __name__ == "__main__":
    main()
