"""Elastic co-scheduling makespan experiment (reference §B parity).

Reference result (BASELINE.md §B, report_cn.md:66-88, data/1c,1s.csv):
two training jobs on a fixed-size cluster — gang scheduling makes job 2
wait for job 1's resources (makespan ~795 s); elastic scheduling starts
job 2 immediately on leftover slots and shrinks job 1 (makespan
~580 s, job-2 wait ~0).

This reproduces the same scenario with this framework's actual
runtime: a fixed pool of WORKER SLOTS (default 4), two DeepFM jobs
(each its own in-process master + task queue + 2 PS OS processes),
workers as real OS processes occupying slots.

- gang:    job 1 takes all slots; job 2 waits until job 1 completes,
           then takes all slots.
- elastic: job 1 starts on all slots; when job 2 arrives (T_ARRIVE
           seconds in), the scheduler SIGKILLs half of job 1's workers
           (their in-flight tasks are recovered by the liveness
           monitor) and starts job 2 on the freed slots; whichever job
           finishes first hands its slots back to the other.
- autoscale (--mode autoscale, ISSUE 7): no hardcoded kills — each
           job runs the real ElasticController + DrainManager
           (master/autoscaler.py). Job arrival/completion only moves
           the jobs' max_workers budgets; the controllers decide when
           to grow (sustained backlog per worker), when to shrink
           (over budget / idle tail), and WHO to shrink (slowest
           step-time EWMA), and scale-down victims drain gracefully:
           SIGTERM -> finish current task -> join async push ->
           deregister. Every resize lands in the event journal as a
           scale_decision with the signals that fired.
- mesh (--mode mesh, ISSUE 20): the multihost correctness gate. One
           job whose workers form a ``jax.distributed`` mesh (the
           GSPMD dense data plane) is grown dp=2 -> dp=3 and then
           shrunk back mid-run; each resize is a mesh-epoch restart,
           and the gate asserts zero lost/duplicated training steps
           plus a ``mesh_epoch_restart`` journal entry with a reason
           for every transition.

Prints one JSON line: makespans, job-2 wait, and the speedup of the
chosen elastic mode over gang. CPU backend; runs in ~4-8 min.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"


class SlotPool:
    """The fixed worker-slot budget both jobs share in autoscale mode.
    A slot stays occupied until its worker PROCESS exits — a draining
    victim holds its slot through the flush, so the arriving job's
    growth is honestly gated on the drain completing."""

    def __init__(self, slots):
        self.slots = slots
        self.jobs = []
        # both jobs' controller threads reserve slots concurrently; an
        # unlocked check-then-spawn would let them oversubscribe the
        # budget (and score the autoscale run on more capacity than
        # the gang baseline it must beat)
        self.lock = threading.Lock()

    def register(self, job):
        self.jobs.append(job)

    def available(self):
        return self.slots - sum(j.live_workers() for j in self.jobs)


class _ProcScaler:
    """ElasticController's scaler protocol over a Job's worker
    subprocesses: scale_up spawns (bounded by the shared SlotPool),
    remove_worker delivers SIGTERM — the worker's graceful-drain hook
    (worker/drain.py) takes it from there."""

    def __init__(self, job):
        self._job = job

    def worker_ids(self):
        return [
            idx for idx, proc in self._job.workers.items()
            if proc.poll() is None
        ]

    def scale_up(self, count):
        if self._job.pool is not None:
            # atomic check-then-spawn: spawn_worker registers the proc
            # in job.workers, so the next holder sees the slots taken
            with self._job.pool.lock:
                count = min(count, max(0, self._job.pool.available()))
                return [
                    self._job.spawn_worker() for _ in range(count)
                ]
        return [self._job.spawn_worker() for _ in range(count)]

    def remove_worker(self, worker_id):
        proc = self._job.workers.get(worker_id)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            return True
        return False


class Job:
    """One training job: in-process master + PS subprocesses + a set of
    worker subprocesses this script (or, in autoscale mode, the job's
    own ElasticController) grows/shrinks."""

    def __init__(self, name, train_dir, tmp, records_per_task=256,
                 num_epochs=2, autoscale=False, pool=None,
                 max_workers=4, scale_step=2):
        from elasticdl_tpu.common.grpc_utils import (
            build_server, find_free_port,
        )
        from elasticdl_tpu.data.readers import RecordIODataReader
        from elasticdl_tpu.master.servicer import MasterServicer
        from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
        from elasticdl_tpu.master.task_monitor import TaskMonitor
        from elasticdl_tpu.proto.services import (
            add_master_servicer_to_server,
        )
        from scripts.convergence_elastic import _spawn_ps, _wait_port

        self.name = name
        self.tmp = tmp
        self.train_dir = train_dir
        self.pool = pool
        reader = RecordIODataReader(data_dir=train_dir)
        self.dispatcher = TaskDispatcher(
            training_shards=reader.create_shards(),
            records_per_task=records_per_task,
            num_epochs=num_epochs,
            seed=0,
        )
        # autoscale mode (ISSUE 7): this job's resizes are decided by
        # the real control loop — fleet telemetry in, scale_decision
        # events out, scale-down via graceful drain
        self.controller = None
        self.drain = None
        fleet = None
        if autoscale:
            from elasticdl_tpu.master.autoscaler import (
                DrainManager, ElasticController,
            )
            from elasticdl_tpu.master.fleet import FleetMonitor

            fleet = FleetMonitor()
        self.servicer = MasterServicer(
            self.dispatcher, None, fleet_monitor=fleet
        )
        if autoscale:
            self.drain = DrainManager(
                self.dispatcher, servicer=self.servicer, fleet=fleet,
                deadline_secs=30.0,
            )
            self.servicer.drain_manager = self.drain
            self.controller = ElasticController(
                self.dispatcher,
                _ProcScaler(self),
                self.drain,
                fleet=fleet,
                min_workers=1,
                max_workers=max_workers,
                step=scale_step,
                cooldown_secs=3.0,
                hold_secs=1.0,
                backlog_per_worker=2.0,
                # local subprocess workers skip the pod-boot +
                # jit-compile wait the production default budgets for
                gain_settle_secs=15.0,
                tag=name,
            )
        self.monitor = TaskMonitor(
            self.dispatcher, self.servicer,
            liveness_timeout_secs=8.0, scan_interval_secs=0.5,
            drain_manager=self.drain, autoscaler=self.controller,
        )
        self.server = build_server()
        add_master_servicer_to_server(self.servicer, self.server)
        self.master_port = find_free_port()
        self.server.add_insecure_port("localhost:%d" % self.master_port)
        self.server.start()
        self.monitor.start()
        ports = [find_free_port() for _ in range(2)]
        self.ps_procs = [
            _spawn_ps(i, 2, p, 0.01) for i, p in enumerate(ports)
        ]
        for p in ports:
            _wait_port(p)
        self.ps_addrs = ",".join("localhost:%d" % p for p in ports)
        self.workers = {}
        self.next_idx = 0
        self.started = time.time()
        self.finished_at = None
        if pool is not None:
            pool.register(self)

    def spawn_worker(self):
        from scripts.convergence_elastic import _spawn_worker

        idx = self.next_idx
        self.next_idx += 1
        self.workers[idx] = _spawn_worker(
            idx, self.master_port, self.ps_addrs, self.train_dir,
            os.path.join(self.tmp, "%s_w%d.log" % (self.name, idx)),
        )
        return idx

    def kill_worker(self):
        live = sorted(
            i for i, p in self.workers.items() if p.poll() is None
        )
        if not live:
            return  # job already drained; nothing to yield
        proc = self.workers.pop(live[0])
        proc.send_signal(signal.SIGKILL)
        try:
            proc.wait(timeout=10)  # reap — no zombie for the run's rest
        except Exception:
            pass

    def live_workers(self):
        return sum(1 for p in self.workers.values() if p.poll() is None)

    def finished(self):
        if self.dispatcher.finished():
            if self.finished_at is None:
                self.finished_at = time.time()
            return True
        return False

    def shutdown(self):
        for p in self.workers.values():
            if p.poll() is None:
                p.kill()
        for p in self.ps_procs:
            p.terminate()
        for p in self.ps_procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        self.monitor.stop()
        self.server.stop(0)


def run_gang(train1, train2, tmp, slots, **job_kw):
    """Job 2 waits for all of job 1's slots."""
    t0 = time.time()
    job1 = Job("gang1", train1, tmp, **job_kw)
    for _ in range(slots):
        job1.spawn_worker()
    job2_arrives = t0 + 10.0
    try:
        while not job1.finished():
            time.sleep(0.5)
        t1_done = time.time()
        # job 2 cannot start before it arrives, even if job 1 finished
        # first (a tiny-input run would otherwise report negative wait)
        while time.time() < job2_arrives:
            time.sleep(0.2)
        job2 = Job("gang2", train2, tmp, **job_kw)
        job2_start = time.time()
        for _ in range(slots):
            job2.spawn_worker()
        try:
            while not job2.finished():
                time.sleep(0.5)
        finally:
            job2.shutdown()
        end = time.time()
        return {
            "makespan_s": round(end - t0, 1),
            "job1_s": round(t1_done - t0, 1),
            "job2_wait_s": round(job2_start - job2_arrives, 1),
        }
    finally:
        job1.shutdown()


def run_elastic(train1, train2, tmp, slots, **job_kw):
    """Job 2 starts the moment it arrives; job 1 shrinks to make room,
    then regrows when a job completes."""
    t0 = time.time()
    job1 = Job("el1", train1, tmp, **job_kw)
    for _ in range(slots):
        job1.spawn_worker()
    job2 = None
    handed1 = handed2 = False
    job2_arrives = t0 + 10.0
    half = slots // 2
    try:
        while True:
            now = time.time()
            if job2 is None and now >= job2_arrives:
                for _ in range(half):
                    job1.kill_worker()
                job2 = Job("el2", train2, tmp, **job_kw)
                job2_start = time.time()
                for _ in range(half):
                    job2.spawn_worker()
            done1 = job1.finished()
            done2 = job2.finished() if job2 is not None else False
            # hand slots back ONCE per direction: near job end workers
            # exit naturally as the queue drains, and re-topping every
            # poll tick would churn ~12 s-boot processes for nothing
            if done1 and job2 is not None and not done2 and not handed2:
                for _ in range(slots - job2.live_workers()):
                    job2.spawn_worker()
                handed2 = True
            if done2 and not done1 and not handed1:
                for _ in range(slots - job1.live_workers()):
                    job1.spawn_worker()
                handed1 = True
            if done1 and done2:
                break
            time.sleep(0.5)
        end = time.time()
        return {
            "makespan_s": round(end - t0, 1),
            "job1_s": round(job1.finished_at - t0, 1),
            "job2_wait_s": round(job2_start - job2_arrives, 1),
        }
    finally:
        job1.shutdown()
        if job2 is not None:
            job2.shutdown()


def run_autoscale(train1, train2, tmp, slots, **job_kw):
    """ISSUE 7: the autoscaler, not this script, makes every resize.
    This harness only moves the jobs' max_workers BUDGETS (job 2
    arriving halves job 1's; a completion hands the ceiling back) —
    the controllers do the rest: grow on sustained backlog, shrink the
    over-budget job by draining its slowest workers gracefully, shrink
    the idle tail at each job's end."""
    t0 = time.time()
    pool = SlotPool(slots)
    half = slots // 2
    job1 = Job(
        "as1", train1, tmp, autoscale=True, pool=pool,
        max_workers=slots, scale_step=max(1, half), **job_kw
    )
    job2 = None
    job2_arrives = t0 + 10.0
    job2_start = None
    handed1 = handed2 = False
    try:
        while True:
            now = time.time()
            if job2 is None and now >= job2_arrives:
                # budget move: job 1 is now over budget and its
                # controller drains victims; job 2's controller grows
                # into the slots the drains free up
                job1.controller.set_limits(max_workers=slots - half)
                job2 = Job(
                    "as2", train2, tmp, autoscale=True, pool=pool,
                    max_workers=half, scale_step=max(1, half),
                    **job_kw
                )
                job2_start = time.time()
            done1 = job1.finished()
            done2 = job2.finished() if job2 is not None else False
            if done1 and job2 is not None and not done2 and not handed2:
                job2.controller.set_limits(max_workers=slots)
                handed2 = True
            if done2 and not done1 and not handed1:
                job1.controller.set_limits(max_workers=slots)
                handed1 = True
            if done1 and done2:
                break
            time.sleep(0.5)
        end = time.time()
        return {
            "makespan_s": round(end - t0, 1),
            "job1_s": round(job1.finished_at - t0, 1),
            "job2_wait_s": round(job2_start - job2_arrives, 1),
        }
    finally:
        job1.shutdown()
        if job2 is not None:
            job2.shutdown()


def run_mesh_elastic(train, tmp, records, records_per_task, num_epochs,
                     events_dir, deadline_secs=600.0):
    """ISSUE 20: elasticity under the GSPMD dense data plane. The
    scenarios above treat each worker as an independent consumer (its
    own singleton mesh); the dense data plane makes the WORKER SET one
    ``jax.distributed`` mesh, so a resize is a mesh-epoch restart —
    checkpoint sharded dense state, re-form the world, resume. This
    scenario drives one multihost job through a mid-run GROW (a third
    worker joins: dp=2 -> dp=3) and a mid-run SHRINK (that worker is
    SIGKILLed; the liveness monitor evicts it: dp=3 -> dp=2) and
    asserts the elasticity contract mechanically:

    - the job finishes with every training task completed EXACTLY once
      across both restarts (lost work would stall ``finished()``;
      duplicated work would over-count done tasks — the dispatcher
      requeues in-flight tasks on an epoch change and drops stale
      double-reports, and this is where that is proven end-to-end);
    - every mesh transition lands in the event journal as
      ``mesh_epoch_restart`` with old/new world sizes and a reason:
      the run must contain the grow to world 3 (``worker_join:...``)
      and the eviction shrink (``worker_death:...``).
    """
    import math

    from elasticdl_tpu.common.grpc_utils import (
        build_server, find_free_port,
    )
    from elasticdl_tpu.data.readers import RecordIODataReader
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.master.rendezvous import MeshRendezvous
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.master.task_monitor import TaskMonitor
    from elasticdl_tpu.proto.services import (
        add_master_servicer_to_server,
    )
    from scripts.bench_dense_plane import (
        _spawn_worker as _spawn_mh_worker,
    )
    from scripts.convergence_elastic import _spawn_ps, _wait_port
    from tests.test_utils import load_journal

    t0 = time.time()
    reader = RecordIODataReader(data_dir=train)
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=records_per_task,
        num_epochs=num_epochs,
        seed=0,
    )
    fleet = FleetMonitor()
    rendezvous = MeshRendezvous()
    servicer = MasterServicer(
        dispatcher, None, rendezvous=rendezvous, fleet_monitor=fleet
    )
    monitor = TaskMonitor(
        dispatcher, servicer, rendezvous=rendezvous,
        # restart-tolerant budgets (tests/test_multihost_e2e.py): the
        # liveness timeout must exceed a worker's relaunch latency, and
        # the grace window must cover the whole-world restart after
        # each epoch bump, or eviction churn cascades
        liveness_timeout_secs=30.0,
        scan_interval_secs=0.5,
        mesh_restart_grace_secs=25.0,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()
    monitor.start()
    ports = [find_free_port() for _ in range(2)]
    ps_procs = [_spawn_ps(i, 2, p, 0.01) for i, p in enumerate(ports)]
    for p in ports:
        _wait_port(p)
    ps_addrs = ",".join("localhost:%d" % p for p in ports)
    coordinator_port = find_free_port()
    ckpt_dir = os.path.join(tmp, "mesh_ckpt")
    logs = {i: os.path.join(tmp, "mesh_w%d.log" % i) for i in range(3)}
    workers = {}
    relaunches = {0: 0, 1: 0, 2: 0}
    members = {0, 1}

    def done_tasks():
        return dispatcher.stats()["done"].get("training", 0)

    def spawn(i):
        workers[i] = _spawn_mh_worker(
            i, master_port, coordinator_port, train, ps_addrs,
            ckpt_dir, logs[i],
        )

    def supervise():
        # pod-manager stand-in: every epoch bump makes the surviving
        # workers exit for restart (worker/main.py EPOCH_RESTART_EXIT
        # path), and late jax.distributed joiners can abort fatally —
        # relaunch members until the run completes
        for i in list(members):
            proc = workers.get(i)
            if proc is not None and proc.poll() is None:
                continue
            relaunches[i] += 1
            if relaunches[i] >= 20:
                raise SystemExit(
                    "FAIL: mesh worker %d restart-looped; log tail:\n%s"
                    % (i, open(logs[i]).read()[-2500:])
                )
            spawn(i)

    max_world = 0
    grown_done = shrunk_at = None
    phase = "warmup"
    try:
        spawn(0)
        spawn(1)
        deadline = t0 + deadline_secs
        while time.time() < deadline:
            supervise()
            world = len(rendezvous.hosts())
            max_world = max(max_world, world)
            done = done_tasks()
            if phase == "warmup" and world == 2 and done >= 2:
                # GROW mid-run: a new host registers; the rendezvous
                # bumps the epoch and the live workers restart into
                # the dp=3 world
                members.add(2)
                spawn(2)
                phase = "growing"
            elif phase == "growing":
                # don't shrink until the dp=3 world has actually
                # FORMED — a worker reporting mesh_shape=dp=3 has
                # completed the jax.distributed join and rebuilt its
                # trainer. Killing a member while the world is still
                # re-forming is a different (supported, watchdogged)
                # scenario, but this gate must exercise a clean
                # grown-then-shrunk cycle to prove the step
                # accounting, not a join race.
                dp3 = any(
                    entry.get("mesh_shape") == "dp=3"
                    for entry in fleet.snapshot().get(
                        "dense_plane", {}
                    ).values()
                )
                if dp3:
                    grown_done = done
                    phase = "grown"
            elif phase == "grown" and done >= grown_done + 2:
                # SHRINK mid-run: hard-kill the third worker (no
                # graceful leave) — the liveness monitor must evict it
                # and bump the epoch back down to dp=2
                members.discard(2)
                proc = workers.get(2)
                if proc is not None and proc.poll() is None:
                    proc.send_signal(signal.SIGKILL)
                    try:
                        proc.wait(timeout=10)
                    except Exception:
                        pass
                shrunk_at = done
                phase = "shrunk"
            if dispatcher.finished():
                break
            time.sleep(0.5)
        elapsed = time.time() - t0
        if not dispatcher.finished():
            raise SystemExit(
                "FAIL: mesh job never finished in %.0fs (phase %s); "
                "worker log tail:\n%s"
                % (deadline_secs, phase, open(logs[0]).read()[-2500:])
            )
        if dispatcher.job_failed():
            raise SystemExit("FAIL: mesh job failed")
        done = done_tasks()
    finally:
        for proc in workers.values():
            if proc.poll() is None:
                proc.kill()
        for p in ps_procs:
            p.terminate()
        for p in ps_procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        monitor.stop()
        server.stop(0)

    expected = int(math.ceil(records / float(records_per_task))) \
        * num_epochs
    restarts = [
        e for e in load_journal(events_dir)
        if e.get("event") == "mesh_epoch_restart"
    ]
    grows = [
        e for e in restarts
        if e.get("new_world", 0) > e.get("old_world", 0)
    ]
    shrinks = [
        e for e in restarts
        if e.get("new_world", 0) < e.get("old_world", 0)
    ]
    result = {
        "elapsed_s": round(elapsed, 1),
        "expected_tasks": expected,
        "done_tasks": done,
        "max_world": max_world,
        "mesh_epoch": rendezvous.mesh_epoch,
        "epoch_restarts": len(restarts),
        "grow_reasons": sorted({e.get("reason", "") for e in grows}),
        "shrink_reasons": sorted(
            {e.get("reason", "") for e in shrinks}
        ),
        "relaunches": dict(relaunches),
    }
    failures = []
    if done != expected:
        failures.append(
            "%s steps: %d training tasks done, %d expected"
            % ("LOST" if done < expected else "DUPLICATED",
               done, expected)
        )
    if shrunk_at is None:
        failures.append(
            "job finished before the shrink was exercised (phase %s; "
            "raise --records)" % phase
        )
    if not any(
        e.get("new_world") == 3
        and e.get("reason", "").startswith("worker_join")
        for e in grows
    ):
        failures.append(
            "no worker_join grow to world 3 journaled: %r" % restarts
        )
    if not any(
        e.get("reason", "").startswith(("worker_death", "worker_leave"))
        for e in shrinks
    ):
        failures.append(
            "no worker_death/worker_leave shrink journaled: %r"
            % restarts
        )
    if any(not e.get("reason") for e in restarts):
        failures.append(
            "mesh_epoch_restart journaled WITHOUT a reason: %r"
            % restarts
        )
    return result, failures


def _load_scale_decisions(events_dir):
    from tests.test_utils import load_journal

    decisions = []
    drain_acks = 0
    for event in load_journal(events_dir):
        if event.get("event") == "scale_decision":
            decisions.append({
                k: event.get(k)
                for k in ("tag", "direction", "delta",
                          "workers", "queue_depth", "reasons")
            })
        elif event.get("event") == "drain_ack":
            drain_acks += 1
    return decisions, drain_acks


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--records", type=int, default=4096)
    parser.add_argument("--records_per_task", type=int, default=256)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument(
        "--mode",
        choices=("both", "elastic", "autoscale", "mesh", "all"),
        default="both",
        help="both = gang + hardcoded elastic (the §B reproduction); "
        "autoscale = gang + the ISSUE-7 control loop making every "
        "resize; mesh = the ISSUE-20 multihost grow/shrink "
        "correctness gate (no gang baseline — it asserts zero "
        "lost/duplicated steps, not makespan); all = everything",
    )
    args = parser.parse_args()

    from tests.test_utils import create_ctr_recordio

    tmp = tempfile.mkdtemp(prefix="edl_makespan_")
    dirs = []
    for i in (1, 2):
        d = os.path.join(tmp, "train%d" % i)
        os.makedirs(d)
        create_ctr_recordio(
            os.path.join(d, "f0.rec"), num_records=args.records, seed=i
        )
        dirs.append(d)

    job_kw = dict(
        records_per_task=args.records_per_task,
        num_epochs=args.num_epochs,
    )
    want_elastic = args.mode in ("both", "elastic", "all")
    want_autoscale = args.mode in ("autoscale", "all")
    want_mesh = args.mode in ("mesh", "all")
    want_gang = want_elastic or want_autoscale
    events_dir = None
    if want_autoscale or want_mesh:
        # the acceptance contract: every resize must be explained by a
        # journal event — scale_decision for the autoscale lane,
        # mesh_epoch_restart (with reasons) for the mesh lane
        events_dir = os.path.join(tmp, "events")
        os.makedirs(events_dir, exist_ok=True)
        # unconditional: an inherited EDL_EVENTS_DIR (e.g. ci.sh's
        # earlier tiers export one) would point the acceptance gate at
        # a shared journal full of other runs' scale events
        os.environ["EDL_EVENTS_DIR"] = events_dir
        from elasticdl_tpu.observability import events

        events.configure("bench-master")

    summary = {"slots": args.slots, "mode": args.mode}
    gang = None
    if want_gang:
        gang = run_gang(dirs[0], dirs[1], tmp, args.slots, **job_kw)
        print("[gang]      %s" % gang, flush=True)
        summary["gang"] = gang
    if want_elastic:
        elastic = run_elastic(
            dirs[0], dirs[1], tmp, args.slots, **job_kw
        )
        print("[elastic]   %s" % elastic, flush=True)
        summary["elastic"] = elastic
        summary["makespan_speedup"] = round(
            gang["makespan_s"] / elastic["makespan_s"], 2
        )
    if want_autoscale:
        autoscale = run_autoscale(
            dirs[0], dirs[1], tmp, args.slots, **job_kw
        )
        print("[autoscale] %s" % autoscale, flush=True)
        decisions, drain_acks = _load_scale_decisions(events_dir)
        for decision in decisions:
            print("[scale_decision] %s" % json.dumps(decision),
                  flush=True)
        summary["autoscale"] = autoscale
        summary["autoscale_speedup"] = round(
            gang["makespan_s"] / autoscale["makespan_s"], 2
        )
        summary["scale_decisions"] = decisions
        summary["drain_acks"] = drain_acks
        summary["beats_gang"] = (
            autoscale["makespan_s"] < gang["makespan_s"]
        )
    mesh_failures = []
    if want_mesh:
        mesh, mesh_failures = run_mesh_elastic(
            dirs[0], tmp, args.records, args.records_per_task,
            args.num_epochs, events_dir,
        )
        print("[mesh]      %s" % mesh, flush=True)
        summary["mesh"] = mesh

    print(json.dumps(summary))
    if mesh_failures:
        raise SystemExit("FAIL: " + "; ".join(mesh_failures))
    if want_autoscale:
        # the autoscaled run must beat the static gang baseline AND be
        # able to explain every resize — a silent scaler is a bug even
        # when it happens to win
        if not summary["beats_gang"]:
            raise SystemExit(
                "FAIL: autoscale makespan %.1fs did not beat gang "
                "%.1fs"
                % (autoscale["makespan_s"], gang["makespan_s"])
            )
        if not decisions:
            raise SystemExit(
                "FAIL: no scale_decision events journaled"
            )


if __name__ == "__main__":
    main()
