"""Elastic co-scheduling makespan experiment (reference §B parity).

Reference result (BASELINE.md §B, report_cn.md:66-88, data/1c,1s.csv):
two training jobs on a fixed-size cluster — gang scheduling makes job 2
wait for job 1's resources (makespan ~795 s); elastic scheduling starts
job 2 immediately on leftover slots and shrinks job 1 (makespan
~580 s, job-2 wait ~0).

This reproduces the same scenario with this framework's actual
runtime: a fixed pool of WORKER SLOTS (default 4), two DeepFM jobs
(each its own in-process master + task queue + 2 PS OS processes),
workers as real OS processes occupying slots.

- gang:    job 1 takes all slots; job 2 waits until job 1 completes,
           then takes all slots.
- elastic: job 1 starts on all slots; when job 2 arrives (T_ARRIVE
           seconds in), the scheduler SIGKILLs half of job 1's workers
           (their in-flight tasks are recovered by the liveness
           monitor) and starts job 2 on the freed slots; whichever job
           finishes first hands its slots back to the other.

Prints one JSON line: makespans, job-2 wait, and the elastic speedup.
CPU backend; runs in ~4-8 min.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"


class Job:
    """One training job: in-process master + PS subprocesses + a set of
    worker subprocesses this script grows/shrinks."""

    def __init__(self, name, train_dir, tmp, records_per_task=256,
                 num_epochs=2):
        from elasticdl_tpu.common.grpc_utils import (
            build_server, find_free_port,
        )
        from elasticdl_tpu.data.readers import RecordIODataReader
        from elasticdl_tpu.master.servicer import MasterServicer
        from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
        from elasticdl_tpu.master.task_monitor import TaskMonitor
        from elasticdl_tpu.proto.services import (
            add_master_servicer_to_server,
        )
        from scripts.convergence_elastic import _spawn_ps, _wait_port

        self.name = name
        self.tmp = tmp
        self.train_dir = train_dir
        reader = RecordIODataReader(data_dir=train_dir)
        self.dispatcher = TaskDispatcher(
            training_shards=reader.create_shards(),
            records_per_task=records_per_task,
            num_epochs=num_epochs,
            seed=0,
        )
        self.servicer = MasterServicer(self.dispatcher, None)
        self.monitor = TaskMonitor(
            self.dispatcher, self.servicer,
            liveness_timeout_secs=8.0, scan_interval_secs=0.5,
        )
        self.server = build_server()
        add_master_servicer_to_server(self.servicer, self.server)
        self.master_port = find_free_port()
        self.server.add_insecure_port("localhost:%d" % self.master_port)
        self.server.start()
        self.monitor.start()
        ports = [find_free_port() for _ in range(2)]
        self.ps_procs = [
            _spawn_ps(i, 2, p, 0.01) for i, p in enumerate(ports)
        ]
        for p in ports:
            _wait_port(p)
        self.ps_addrs = ",".join("localhost:%d" % p for p in ports)
        self.workers = {}
        self.next_idx = 0
        self.started = time.time()
        self.finished_at = None

    def spawn_worker(self):
        from scripts.convergence_elastic import _spawn_worker

        idx = self.next_idx
        self.next_idx += 1
        self.workers[idx] = _spawn_worker(
            idx, self.master_port, self.ps_addrs, self.train_dir,
            os.path.join(self.tmp, "%s_w%d.log" % (self.name, idx)),
        )

    def kill_worker(self):
        live = sorted(
            i for i, p in self.workers.items() if p.poll() is None
        )
        if not live:
            return  # job already drained; nothing to yield
        proc = self.workers.pop(live[0])
        proc.send_signal(signal.SIGKILL)
        try:
            proc.wait(timeout=10)  # reap — no zombie for the run's rest
        except Exception:
            pass

    def live_workers(self):
        return sum(1 for p in self.workers.values() if p.poll() is None)

    def finished(self):
        if self.dispatcher.finished():
            if self.finished_at is None:
                self.finished_at = time.time()
            return True
        return False

    def shutdown(self):
        for p in self.workers.values():
            if p.poll() is None:
                p.kill()
        for p in self.ps_procs:
            p.terminate()
        for p in self.ps_procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        self.monitor.stop()
        self.server.stop(0)


def run_gang(train1, train2, tmp, slots, **job_kw):
    """Job 2 waits for all of job 1's slots."""
    t0 = time.time()
    job1 = Job("gang1", train1, tmp, **job_kw)
    for _ in range(slots):
        job1.spawn_worker()
    job2_arrives = t0 + 10.0
    try:
        while not job1.finished():
            time.sleep(0.5)
        t1_done = time.time()
        # job 2 cannot start before it arrives, even if job 1 finished
        # first (a tiny-input run would otherwise report negative wait)
        while time.time() < job2_arrives:
            time.sleep(0.2)
        job2 = Job("gang2", train2, tmp, **job_kw)
        job2_start = time.time()
        for _ in range(slots):
            job2.spawn_worker()
        try:
            while not job2.finished():
                time.sleep(0.5)
        finally:
            job2.shutdown()
        end = time.time()
        return {
            "makespan_s": round(end - t0, 1),
            "job1_s": round(t1_done - t0, 1),
            "job2_wait_s": round(job2_start - job2_arrives, 1),
        }
    finally:
        job1.shutdown()


def run_elastic(train1, train2, tmp, slots, **job_kw):
    """Job 2 starts the moment it arrives; job 1 shrinks to make room,
    then regrows when a job completes."""
    t0 = time.time()
    job1 = Job("el1", train1, tmp, **job_kw)
    for _ in range(slots):
        job1.spawn_worker()
    job2 = None
    handed1 = handed2 = False
    job2_arrives = t0 + 10.0
    half = slots // 2
    try:
        while True:
            now = time.time()
            if job2 is None and now >= job2_arrives:
                for _ in range(half):
                    job1.kill_worker()
                job2 = Job("el2", train2, tmp, **job_kw)
                job2_start = time.time()
                for _ in range(half):
                    job2.spawn_worker()
            done1 = job1.finished()
            done2 = job2.finished() if job2 is not None else False
            # hand slots back ONCE per direction: near job end workers
            # exit naturally as the queue drains, and re-topping every
            # poll tick would churn ~12 s-boot processes for nothing
            if done1 and job2 is not None and not done2 and not handed2:
                for _ in range(slots - job2.live_workers()):
                    job2.spawn_worker()
                handed2 = True
            if done2 and not done1 and not handed1:
                for _ in range(slots - job1.live_workers()):
                    job1.spawn_worker()
                handed1 = True
            if done1 and done2:
                break
            time.sleep(0.5)
        end = time.time()
        return {
            "makespan_s": round(end - t0, 1),
            "job1_s": round(job1.finished_at - t0, 1),
            "job2_wait_s": round(job2_start - job2_arrives, 1),
        }
    finally:
        job1.shutdown()
        if job2 is not None:
            job2.shutdown()


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--records", type=int, default=4096)
    parser.add_argument("--records_per_task", type=int, default=256)
    parser.add_argument("--num_epochs", type=int, default=2)
    args = parser.parse_args()

    from tests.test_utils import create_ctr_recordio

    tmp = tempfile.mkdtemp(prefix="edl_makespan_")
    dirs = []
    for i in (1, 2):
        d = os.path.join(tmp, "train%d" % i)
        os.makedirs(d)
        create_ctr_recordio(
            os.path.join(d, "f0.rec"), num_records=args.records, seed=i
        )
        dirs.append(d)

    job_kw = dict(
        records_per_task=args.records_per_task,
        num_epochs=args.num_epochs,
    )
    gang = run_gang(dirs[0], dirs[1], tmp, args.slots, **job_kw)
    print("[gang]    %s" % gang, flush=True)
    elastic = run_elastic(dirs[0], dirs[1], tmp, args.slots, **job_kw)
    print("[elastic] %s" % elastic, flush=True)

    print(json.dumps({
        "slots": args.slots,
        "gang": gang,
        "elastic": elastic,
        "makespan_speedup": round(
            gang["makespan_s"] / elastic["makespan_s"], 2
        ),
    }))


if __name__ == "__main__":
    main()
