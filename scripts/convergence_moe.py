"""MoE expert-balance convergence experiment (round-4 VERDICT item 5).

Question: does the Switch-style aux loss (ops/moe.py top_k_routing)
actually keep expert dispatch balanced over a REAL training run — and
what happens without it? Trains the same small MoeTransformerLM twice
(aux_loss_weight=0.01 vs 0.0) on a learnable synthetic LM task, then
measures routing balance post-hoc by capturing the router logits with
flax ``capture_intermediates``.

Metrics per arm:
- ce_first/ce_last: cross-entropy at start/end (both arms must learn);
- balance = E * sum_e f_e * p_e (1.0 = perfectly uniform; E = fully
  collapsed), f_e = first-choice token fraction, p_e = mean router prob;
- max_share: largest single expert's first-choice share (uniform = 1/E).

Prints one JSON line. CPU-runnable (tiny shapes); the companion perf
bench (scripts/bench_moe.py) needs the chip.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.models import moe_transformer
from elasticdl_tpu.train.optimizers import create_optimizer
from elasticdl_tpu.worker.trainer import JaxTrainer

VOCAB = 64
NUM_EXPERTS = 4


def make_batch(rng, batch=16, seq=32):
    """Learnable LM stream: next token = (t + stride) % VOCAB with the
    stride switching by region — enough structure that CE falls well
    below uniform."""
    starts = rng.randint(0, VOCAB, size=(batch, 1))
    strides = rng.choice([1, 3, 7], size=(batch, 1))
    pos = np.arange(seq)[None, :]
    tokens = (starts + strides * pos) % VOCAB
    return {
        "features": tokens.astype(np.int32),
        "labels": tokens.astype(np.int32),
        "_mask": np.ones((batch,), np.float32),
    }


def routing_balance(model, params, batch):
    """Post-hoc balance from captured router logits."""
    _, intermediates = model.apply(
        {"params": params},
        batch["features"],
        training=False,
        capture_intermediates=lambda mdl, _: mdl.name == "router",
    )
    flat = jax.tree_util.tree_leaves_with_path(intermediates)
    balances, max_shares = [], []
    for _path, logits in flat:
        logits = np.asarray(logits, np.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        first = np.asarray(jnp.argmax(probs, axis=-1)).reshape(-1)
        f_e = np.bincount(first, minlength=NUM_EXPERTS) / first.size
        p_e = np.asarray(probs).reshape(-1, NUM_EXPERTS).mean(axis=0)
        balances.append(float(NUM_EXPERTS * np.sum(f_e * p_e)))
        max_shares.append(float(f_e.max()))
    return float(np.mean(balances)), float(np.max(max_shares))


def _collapse_routers(params, bias=3.0):
    """Bias every router kernel toward expert 0 — the adversarial init.

    From a random init this tiny task never collapses on its own (both
    arms stay near balance=1.0; measured), so the discriminating
    question is RECOVERY: routing collapse is an attractor (expert 0
    hoards tokens, gets all the gradient, stays best) and only the aux
    loss provides a force out of it."""

    def visit(tree):
        for key, value in tree.items():
            if key == "router":
                kernel = np.array(value["kernel"])  # writable copy
                kernel[:, 1:] -= bias / max(1, kernel.shape[0]) ** 0.5
                value["kernel"] = jnp.asarray(kernel)
            elif isinstance(value, dict):
                visit(value)

    import flax

    params = flax.core.unfreeze(jax.tree_util.tree_map(np.asarray, params))
    visit(params)
    return params


def run_arm(aux_weight, steps, seed=0, collapsed_init=True):
    model = moe_transformer.MoeTransformerLM(
        vocab_size=VOCAB,
        num_layers=2,
        num_heads=2,
        embed_dim=32,
        num_experts=NUM_EXPERTS,
        top_k=2,
        aux_loss_weight=aux_weight,
        attention_impl="xla",
    )
    trainer = JaxTrainer(
        model,
        moe_transformer.loss,
        create_optimizer("Adam", learning_rate=0.01),
        seed=0,
    )
    rng = np.random.RandomState(seed)
    state = None
    ce_first = ce_last = None
    balance0 = share0 = None
    for i in range(steps):
        batch = make_batch(rng)
        if i == 0:
            state = trainer.ensure_state(state, batch)
            if collapsed_init:
                from elasticdl_tpu.train.train_state import TrainState

                state = TrainState(
                    step=state.step,
                    params=_collapse_routers(state.params),
                    model_state=state.model_state,
                    opt_state=state.opt_state,
                )
            balance0, share0 = routing_balance(
                model, state.params, make_batch(np.random.RandomState(999))
            )
        state, loss = trainer.train_step(state, batch)
        if i == 0:
            ce_first = float(loss)
        ce_last = float(loss)
    probe = make_batch(np.random.RandomState(999))
    balance, max_share = routing_balance(model, state.params, probe)
    return {
        "aux_weight": aux_weight,
        "ce_first": round(ce_first, 4),
        "ce_last": round(ce_last, 4),
        "balance_init": round(balance0, 4),
        "max_expert_share_init": round(share0, 4),
        "balance": round(balance, 4),
        "max_expert_share": round(max_share, 4),
    }


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=200)
    args = parser.parse_args()
    arms = [run_arm(0.01, args.steps), run_arm(0.0, args.steps)]
    print(json.dumps({
        "experiment": "moe_expert_balance",
        "num_experts": NUM_EXPERTS,
        "steps": args.steps,
        "with_aux": arms[0],
        "without_aux": arms[1],
    }))


if __name__ == "__main__":
    main()
