"""Dense data plane smoke: 2-process jax.distributed CPU mesh, real
master + PS + workers — dense gradients provably never touch the PS.

The ISSUE 20 acceptance lane (ci.sh tier 1g). The reference framework's
two dense strategies both put every dense byte on the wire every step
(push_gradient to the PS, or Horovod allreduce over the NIC). The GSPMD
rebuild keeps dense parameters and optimizer state sharded over the
mesh — the jitted step reduces gradients as compiler-inserted
collectives — and the PS serves only sparse embedding rows. This smoke
asserts that split MECHANICALLY, not by code inspection:

- a real 2-worker DeepFM job (``jax.distributed`` spanning the two
  worker processes, dp=2 mesh, lockstep rounds) trains to completion
  against an in-process master and a live PS subprocess;
- the PS's byte counters are scraped off its /metrics port at the end:
  ``edl_ps_push_bytes_total`` (embedding-row payload) must be nonzero —
  the sparse plane really rode the PS — while
  ``edl_ps_push_dense_bytes_total`` (dense TensorBlobs arriving over
  push_gradients, the reference's dense path) must be exactly 0;
- the master's FleetMonitor must have seen both workers report the
  dense-plane telemetry (mesh_shape=dp=2, collective_bytes_per_step)
  — the same fields /statusz and postmortem.py surface;
- the mesh epoch must not have moved: this is the steady-state lane
  (elastic reshape correctness is bench_elastic_makespan's job).

Prints one JSON line. CPU backend; runs in ~1-3 min.
"""

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"


def _spawn_worker(idx, master_port, coordinator_port, train_dir,
                  ps_addrs, ckpt_dir, log_path):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        EDL_FAULTHANDLER="1",
        PYTHONPATH=REPO,
        # one virtual device per worker process: the global mesh is the
        # 2-process dp=2 mesh, every dense reduction crosses processes
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    log = open(log_path, "ab")
    log.write(b"\n===== incarnation spawn =====\n")
    log.flush()
    return subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.worker.main",
         "--master_addr", "localhost:%d" % master_port,
         "--worker_id", str(idx),
         "--model_zoo", "elasticdl_tpu.models.deepfm",
         "--training_data", train_dir,
         "--minibatch_size", "64",
         "--multihost", "1",
         "--coordinator_port", str(coordinator_port),
         "--worker_host", "localhost:%d" % (63000 + idx),
         "--ps_addrs", ps_addrs,
         "--checkpoint_dir", ckpt_dir,
         "--checkpoint_steps", "4",
         "--report_version_steps", "2"],
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )


def _scrape_counters(metrics_port):
    """Sum each byte counter's series off the PS /metrics exposition.
    Returns {metric_name: summed_value}; a registered-but-untouched
    unlabeled counter renders an explicit 0 line (servicer touches the
    dense series at construction exactly so this scrape can tell
    'provably zero' from 'not exported')."""
    body = urllib.request.urlopen(
        "http://localhost:%d/metrics" % metrics_port, timeout=10
    ).read().decode()
    wanted = ("edl_ps_push_bytes_total", "edl_ps_push_dense_bytes_total",
              "edl_ps_pull_bytes_total")
    sums = {}
    for line in body.splitlines():
        if line.startswith("#"):
            continue
        for name in wanted:
            if line.startswith(name) and (
                line[len(name):len(name) + 1] in ("", " ", "{")
            ):
                sums[name] = sums.get(name, 0.0) + float(line.split()[-1])
    return sums, body


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--records", type=int, default=2048)
    parser.add_argument("--records_per_task", type=int, default=256)
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--deadline_secs", type=float, default=420.0)
    args = parser.parse_args()

    from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
    from elasticdl_tpu.data.readers import RecordIODataReader
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.master.rendezvous import MeshRendezvous
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.master.task_monitor import TaskMonitor
    from elasticdl_tpu.proto.services import add_master_servicer_to_server
    from tests.test_utils import create_ctr_recordio, spawn_ps_process

    tmp = tempfile.mkdtemp(prefix="edl_dense_plane_")
    train_dir = os.path.join(tmp, "train")
    os.makedirs(train_dir)
    create_ctr_recordio(
        os.path.join(train_dir, "f0.rec"), num_records=args.records,
        seed=0,
    )

    reader = RecordIODataReader(data_dir=train_dir)
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=args.records_per_task,
        num_epochs=args.num_epochs,
        seed=0,
    )
    fleet = FleetMonitor()
    rendezvous = MeshRendezvous()
    servicer = MasterServicer(
        dispatcher, None, rendezvous=rendezvous, fleet_monitor=fleet
    )
    monitor = TaskMonitor(
        dispatcher, servicer, rendezvous=rendezvous,
        # same budgets as tests/test_multihost_e2e.py: must exceed a
        # worker's relaunch latency or the restart gap itself evicts
        # members and churns the epoch this lane asserts is quiet
        liveness_timeout_secs=30.0,
        scan_interval_secs=0.5,
        mesh_restart_grace_secs=25.0,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()
    monitor.start()

    metrics_port = find_free_port()
    ps_proc, ps_port = spawn_ps_process(
        log_path=os.path.join(tmp, "ps.log"),
        extra=("--metrics_port", str(metrics_port)),
    )
    ps_addrs = "localhost:%d" % ps_port
    coordinator_port = find_free_port()
    ckpt_dir = os.path.join(tmp, "ckpt")
    logs = {i: os.path.join(tmp, "worker%d.log" % i) for i in (0, 1)}
    workers = {}
    relaunches = {0: 0, 1: 0}
    max_hosts_seen = 0
    try:
        for i in (0, 1):
            workers[i] = _spawn_worker(
                i, master_port, coordinator_port, train_dir, ps_addrs,
                ckpt_dir, logs[i],
            )

        def supervise():
            # pod-manager stand-in: a late jax.distributed joiner can
            # abort fatally against a not-yet-ready coordinator; the
            # recovery model is relaunch-and-rejoin (test_multihost_e2e)
            for i, proc in list(workers.items()):
                if proc.poll() is None:
                    continue
                relaunches[i] += 1
                if relaunches[i] >= 8:
                    raise SystemExit(
                        "FAIL: worker %d restart-looped; log tail:\n%s"
                        % (i, open(logs[i]).read()[-2500:])
                    )
                workers[i] = _spawn_worker(
                    i, master_port, coordinator_port, train_dir,
                    ps_addrs, ckpt_dir, logs[i],
                )

        started = time.time()
        deadline = started + args.deadline_secs
        while time.time() < deadline and not dispatcher.finished():
            supervise()
            max_hosts_seen = max(max_hosts_seen, len(rendezvous.hosts()))
            time.sleep(0.5)
        elapsed = time.time() - started
        if not dispatcher.finished():
            raise SystemExit(
                "FAIL: job never finished in %.0fs; worker log tail:\n%s"
                % (args.deadline_secs, open(logs[0]).read()[-2500:])
            )
        if dispatcher.job_failed():
            raise SystemExit("FAIL: job failed")

        counters, raw = _scrape_counters(metrics_port)
        snapshot = fleet.snapshot()
    finally:
        for proc in workers.values():
            if proc.poll() is None:
                proc.kill()
        ps_proc.terminate()
        try:
            ps_proc.wait(timeout=10)
        except Exception:
            ps_proc.kill()
        monitor.stop()
        server.stop(0)

    sparse_bytes = counters.get("edl_ps_push_bytes_total", 0.0)
    dense_bytes = counters.get("edl_ps_push_dense_bytes_total")
    dense_plane = snapshot.get("dense_plane", {})
    summary = {
        "elapsed_s": round(elapsed, 1),
        "workers": 2,
        "max_hosts_seen": max_hosts_seen,
        "mesh_epoch": rendezvous.mesh_epoch,
        "ps_push_bytes": int(sparse_bytes),
        "ps_push_dense_bytes": (
            None if dense_bytes is None else int(dense_bytes)
        ),
        "ps_pull_bytes": int(
            counters.get("edl_ps_pull_bytes_total", 0.0)
        ),
        "dense_plane": dense_plane,
        "relaunches": dict(relaunches),
    }
    print(json.dumps(summary))

    failures = []
    if max_hosts_seen != 2:
        failures.append(
            "mesh never spanned 2 processes (max hosts %d)"
            % max_hosts_seen
        )
    if sparse_bytes <= 0:
        failures.append("no embedding-row push bytes reached the PS")
    if dense_bytes is None:
        failures.append(
            "edl_ps_push_dense_bytes_total missing from /metrics:\n%s"
            % raw[:1500]
        )
    elif dense_bytes != 0:
        failures.append(
            "DENSE GRADIENTS HIT THE PS: %d bytes over push_gradients"
            % dense_bytes
        )
    reported = [
        entry for entry in dense_plane.values()
        if entry.get("mesh_shape") == "dp=2"
    ]
    if not reported:
        failures.append(
            "no worker reported dense-plane telemetry with mesh dp=2: %r"
            % dense_plane
        )
    elif not any(
        entry.get("collective_bytes_per_step", 0) > 0 for entry in reported
    ):
        failures.append(
            "collective_bytes_per_step never reported >0: %r" % dense_plane
        )
    if failures:
        raise SystemExit("FAIL: " + "; ".join(failures))


if __name__ == "__main__":
    main()
