"""Capture a jax.profiler trace of the ResNet50 train step on the real
chip and print a per-HLO-category breakdown (the evidence behind
docs/PERF_RESNET.md).

Usage: python scripts/profile_resnet.py [--out /tmp/edl_trace]
"""

import argparse
import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="/tmp/edl_trace")
    parser.add_argument("--batch_size", type=int, default=256)
    parser.add_argument("--steps", type=int, default=5)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.models import resnet
    from elasticdl_tpu.train.optimizers import create_optimizer
    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    model = resnet.resnet50(num_classes=1000, stem="space_to_depth")
    tx = create_optimizer(
        "Momentum", learning_rate=0.1, momentum=0.9, nesterov=True
    )
    train_step = make_train_step(
        model, resnet.loss, tx, compute_dtype=jnp.bfloat16
    )

    def run_steps(state, batch, n):
        def body(state, _):
            state, loss = train_step(state, batch)
            return state, loss
        return jax.lax.scan(body, state, None, length=n)

    run = jax.jit(run_steps, static_argnums=(2,), donate_argnums=(0,))
    rng = np.random.RandomState(0)
    batch = {
        "features": jnp.asarray(
            rng.rand(args.batch_size, 224, 224, 3), jnp.float32
        ),
        "labels": jnp.asarray(
            rng.randint(0, 1000, size=args.batch_size), jnp.int32
        ),
        "_mask": jnp.ones((args.batch_size,), jnp.float32),
    }
    state = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )
    state, losses = run(state, batch, args.steps)
    float(losses[-1])  # fence warmup

    from scripts.trace_summary import capture_trace

    def _once():
        _, traced_losses = run(state, batch, args.steps)
        float(traced_losses[-1])  # fetch fences remote execution

    capture_trace(_once, args.out, args.steps)


if __name__ == "__main__":
    main()
