"""Capture a jax.profiler trace of the ResNet50 train step on the real
chip and print a per-HLO-category breakdown (the evidence behind
docs/PERF_RESNET.md).

Usage: python scripts/profile_resnet.py [--out /tmp/edl_trace]
"""

import argparse
import collections
import glob
import gzip
import json
import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="/tmp/edl_trace")
    parser.add_argument("--batch_size", type=int, default=256)
    parser.add_argument("--steps", type=int, default=5)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.models import resnet
    from elasticdl_tpu.train.optimizers import create_optimizer
    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    model = resnet.resnet50(num_classes=1000, stem="space_to_depth")
    tx = create_optimizer(
        "Momentum", learning_rate=0.1, momentum=0.9, nesterov=True
    )
    train_step = make_train_step(
        model, resnet.loss, tx, compute_dtype=jnp.bfloat16
    )

    def run_steps(state, batch, n):
        def body(state, _):
            state, loss = train_step(state, batch)
            return state, loss
        return jax.lax.scan(body, state, None, length=n)

    run = jax.jit(run_steps, static_argnums=(2,), donate_argnums=(0,))
    rng = np.random.RandomState(0)
    batch = {
        "features": jnp.asarray(
            rng.rand(args.batch_size, 224, 224, 3), jnp.float32
        ),
        "labels": jnp.asarray(
            rng.randint(0, 1000, size=args.batch_size), jnp.int32
        ),
        "_mask": jnp.ones((args.batch_size,), jnp.float32),
    }
    state = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )
    state, losses = run(state, batch, args.steps)
    float(losses[-1])  # fence warmup

    jax.profiler.start_trace(args.out)
    state, losses = run(state, batch, args.steps)
    float(losses[-1])  # device->host fetch fences remote execution
    jax.profiler.stop_trace()

    path = sorted(
        glob.glob(args.out + "/plugins/profile/*/*.trace.json.gz")
    )[-1]
    with gzip.open(path) as f:
        data = json.load(f)
    # pid of the TPU device track
    tpu_pid = None
    for e in data["traceEvents"]:
        if e.get("ph") == "M" and e.get("name") == "process_name" and \
                "TPU" in str(e.get("args", {}).get("name", "")):
            tpu_pid = e["pid"]
    ops = [
        e for e in data["traceEvents"]
        if e.get("ph") == "X" and e.get("pid") == tpu_pid
        and "hlo_category" in e.get("args", {})
        and not e["name"].startswith("while")
    ]
    total = sum(e["dur"] for e in ops)
    cat = collections.Counter()
    catb = collections.Counter()
    for e in ops:
        c = e["args"]["hlo_category"]
        cat[c] += e["dur"]
        catb[c] += int(e["args"].get("bytes_accessed", 0))
    print(
        "device time: %.1f ms / %d steps; bytes accessed %.1f GB/step"
        % (total / 1e3, args.steps, sum(catb.values()) / args.steps / 1e9)
    )
    for c, d in cat.most_common(12):
        bw = catb[c] / (d / 1e6) / 1e9 if d else 0
        print(
            "%5.1f%%  %8.1fms  bw=%6.0f GB/s  %s"
            % (d / total * 100, d / 1e3, bw, c)
        )
    print("trace at:", path)


if __name__ == "__main__":
    main()
