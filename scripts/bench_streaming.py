#!/usr/bin/env python
"""Streaming-training proof scenario (ISSUE 12, tier 1f).

A day-compressed simulated clickstream — Zipfian ids whose hot set
DRIFTS every window, so the union vocabulary grows without bound —
trained through the real PS servicer twice:

- **baseline**: a plain store, no lifecycle — every novel id
  materializes a row forever (the pre-ISSUE-12 behavior);
- **lifecycle**: frequency admission (``admit_k``) + TTL/LFU eviction
  bounding resident rows at ``max_rows``.

The model is an embedding-only logistic regressor (one table, logit =
sum over fields of the row mean), trained with hand-derived BCE
gradients pushed through ``push_gradients`` — so admission drops and
eviction tombstones act on REAL gradient traffic, and pulls ride the
real cold-row path.

Hard gates (the acceptance criteria; everything else is report-only):

1. **bounded memory**: lifecycle resident rows <= max_rows after the
   final sweep, while the baseline grew past ``unbounded_factor`` x
   that bound (the "baseline grows unbounded" assertion);
2. **holdout-tail quality**: BCE logloss on the UNSEEN tail windows
   under the lifecycle store within ``loss_tolerance`` (relative) of
   the unbounded baseline, and both better than predicting the base
   rate (the stream was actually learned);
3. **backend parity**: replaying the identical stream on the native
   store's lifecycle yields bit-exact admitted rows vs numpy (skipped
   with a loud note when no native lib is available).

Output: one JSON object on stdout (journaled by ci.sh tier 1f).
Exit 1 when a gate fails.
"""

import json
import sys

import numpy as np

sys.path.insert(0, ".")  # run from the repo root, like ci.sh does

from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.common.tensor_utils import (
    blob_to_ndarray,
    serialize_indexed_slices,
)
from elasticdl_tpu.ps.embedding_store import (
    NumpyEmbeddingStore,
    native_lib,
)
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.stream.lifecycle import EmbeddingLifecycle
from elasticdl_tpu.stream.source import SyntheticClickstreamSource

DIM = 4
FIELDS = 4
WINDOW_RECORDS = 256
TRAIN_WINDOWS = 120
EVAL_WINDOWS = 12
HOT_VOCAB = 1500
DRIFT = 30                 # hot-set slide per window (vocab churn)
ZIPF_A = 1.3
# every training step pulls THEN pushes an id's occurrences, so one
# appearance already counts two sightings; 4 means "appears at least
# twice (or more than once in a window) before a row materializes" —
# one-shot tail ids stay sketch-only and their gradients drop
ADMIT_K = 4
MAX_ROWS = 2000
TTL_WINDOWS = 40           # synthetic seconds == windows
SWEEP_EVERY = 5
LR = 0.5
LOSS_TOLERANCE = 0.10      # lifecycle tail logloss within 10% of baseline
UNBOUNDED_FACTOR = 2.0     # baseline must outgrow the bound by this


class _Run:
    def __init__(self, backend, lifecycle_on, clock):
        if backend == "native":
            from elasticdl_tpu.ps.embedding_store import (
                NativeEmbeddingStore,
            )

            self.store = NativeEmbeddingStore(seed=0)
        else:
            self.store = NumpyEmbeddingStore(seed=0)
        self.store.set_optimizer("sgd", lr=LR)
        self.lifecycle = None
        if lifecycle_on:
            self.lifecycle = EmbeddingLifecycle(
                self.store, admit_k=ADMIT_K, max_rows=MAX_ROWS,
                ttl_secs=float(TTL_WINDOWS), clock=clock,
            )
        self.servicer = PserverServicer(
            self.store, use_async=True, lifecycle=self.lifecycle,
            staleness_modulation=False,
        )
        infos = pb.Model()
        infos.embedding_table_infos.add(
            name="emb", dim=DIM, initializer="zeros"
        )
        self.servicer.push_embedding_table_infos(infos)

    def pull(self, ids):
        """[n] ids -> [n, DIM] rows through the real pull path (cold
        rows for pre-admission ids included)."""
        request = pb.PullEmbeddingVectorsRequest(name="emb")
        request.ids_blob = np.ascontiguousarray(
            ids, dtype="<i8"
        ).tobytes()
        return blob_to_ndarray(
            self.servicer.pull_embedding_vectors(request)
        )

    def train_window(self, ids, labels):
        """One window: forward from pulled rows, BCE gradient wrt each
        row, one push (the servicer dedups + applies)."""
        flat = ids.reshape(-1)
        rows = self.pull(flat).reshape(ids.shape[0], FIELDS, DIM)
        logits = rows.mean(axis=2).sum(axis=1)
        p = 1.0 / (1.0 + np.exp(-logits))
        # dL/d row[f, d] = (p - y) / DIM for every field's row
        g = ((p - labels) / DIM).astype(np.float32)
        grads = np.repeat(g, FIELDS)[:, None] * np.ones(
            (1, DIM), np.float32
        )
        request = pb.PushGradientsRequest()
        serialize_indexed_slices(
            grads, flat, request.gradients.embedding_tables["emb"]
        )
        self.servicer.push_gradients(request)

    def eval_tail(self, windows):
        """Holdout-tail quality: (logloss, AUC). AUC beside logloss
        (ROADMAP item 4 headroom): logloss rewards calibration, AUC
        rewards RANKING — an eviction policy that keeps calibrated
        head rows but scrambles tail ordering shows up only here."""
        total, n = 0.0, 0
        scores, targets = [], []
        for ids, labels in windows:
            flat = ids.reshape(-1)
            rows = self.pull(flat).reshape(ids.shape[0], FIELDS, DIM)
            logits = rows.mean(axis=2).sum(axis=1)
            p = np.clip(
                1.0 / (1.0 + np.exp(-logits)), 1e-7, 1.0 - 1e-7
            )
            total += float(-(
                labels * np.log(p) + (1 - labels) * np.log(1 - p)
            ).sum())
            n += labels.size
            scores.append(logits)
            targets.append(labels)
        return total / max(1, n), _auc(
            np.concatenate(scores), np.concatenate(targets)
        )


def _auc(scores, labels):
    """ROC AUC via the rank-sum identity (average ties), no sklearn."""
    labels = np.asarray(labels) > 0.5
    pos = int(labels.sum())
    neg = labels.size - pos
    if pos == 0 or neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(scores.size, dtype=np.float64)
    ranks[order] = np.arange(1, scores.size + 1)
    # average ranks across ties so equal scores split the credit
    sorted_scores = scores[order]
    i = 0
    while i < scores.size:
        j = i
        while (j + 1 < scores.size
               and sorted_scores[j + 1] == sorted_scores[i]):
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = 0.5 * (i + 1 + j + 1)
        i = j + 1
    rank_sum = float(ranks[labels].sum())
    return (rank_sum - pos * (pos + 1) / 2.0) / (pos * neg)


def run_stream(backend, lifecycle_on, source):
    clock = [0.0]
    run = _Run(backend, lifecycle_on, clock=lambda: clock[0])
    for w in range(TRAIN_WINDOWS):
        clock[0] = float(w)
        ids, labels = source.window_examples(w)
        run.train_window(ids, labels)
        if run.lifecycle is not None and (w + 1) % SWEEP_EVERY == 0:
            run.servicer.lifecycle_tick()
    if run.lifecycle is not None:
        clock[0] = float(TRAIN_WINDOWS)
        run.servicer.lifecycle_tick()
    return run


def main():
    source = SyntheticClickstreamSource(
        "/tmp/_bench_streaming_unused_spool",
        records_per_window=WINDOW_RECORDS, num_features=FIELDS,
        hot_vocab=HOT_VOCAB, zipf_a=ZIPF_A, drift_per_window=DRIFT,
        seed=11,
    )
    holdout = [
        source.window_examples(w)
        for w in range(TRAIN_WINDOWS, TRAIN_WINDOWS + EVAL_WINDOWS)
    ]
    base_rate = float(np.mean([labels.mean() for _, labels in holdout]))
    p0 = min(max(base_rate, 1e-7), 1 - 1e-7)
    base_rate_logloss = float(
        -(p0 * np.log(p0) + (1 - p0) * np.log(1 - p0))
    )

    baseline = run_stream("numpy", lifecycle_on=False, source=source)
    lifecycle = run_stream("numpy", lifecycle_on=True, source=source)

    baseline_rows = baseline.store.table_size("emb")
    lifecycle_rows = lifecycle.store.table_size("emb")
    # snapshot the trained state BEFORE eval: holdout pulls are
    # sightings too (the real serving path), and the parity replay
    # below trains only — it must compare against end-of-training
    lifecycle_export = lifecycle.store.export_table_full("emb")
    baseline_loss, baseline_auc = baseline.eval_tail(holdout)
    lifecycle_loss, lifecycle_auc = lifecycle.eval_tail(holdout)
    stats = lifecycle.lifecycle.stats()

    failures = []
    if lifecycle_rows > MAX_ROWS:
        failures.append(
            "resident rows %d exceed the %d bound"
            % (lifecycle_rows, MAX_ROWS)
        )
    if baseline_rows < UNBOUNDED_FACTOR * MAX_ROWS:
        failures.append(
            "baseline only grew to %d rows (< %.1fx bound %d): the "
            "stream no longer exercises unbounded growth"
            % (baseline_rows, UNBOUNDED_FACTOR, MAX_ROWS)
        )
    if lifecycle_loss > baseline_loss * (1.0 + LOSS_TOLERANCE):
        failures.append(
            "holdout-tail logloss regressed: lifecycle %.4f vs "
            "baseline %.4f (tolerance %.0f%%)"
            % (lifecycle_loss, baseline_loss, 100 * LOSS_TOLERANCE)
        )
    if baseline_loss >= base_rate_logloss:
        failures.append(
            "baseline never beat the base rate (%.4f >= %.4f): the "
            "stream is not learnable, the quality gate is vacuous"
            % (baseline_loss, base_rate_logloss)
        )

    # backend parity: identical stream through the native lifecycle
    parity = "skipped (no native lib)"
    if native_lib() is not None:
        native = run_stream("native", lifecycle_on=True, source=source)
        want = lifecycle_export
        got = native.store.export_table_full("emb")
        order_w = np.argsort(want[0])
        order_g = np.argsort(got[0])
        if (
            want[0].shape == got[0].shape
            and (want[0][order_w] == got[0][order_g]).all()
            and (want[1][order_w] == got[1][order_g]).all()
            and (want[2][order_w] == got[2][order_g]).all()
        ):
            parity = "bit-exact (%d rows)" % want[0].size
        else:
            parity = "MISMATCH"
            failures.append(
                "numpy<->native lifecycle parity broke: %d vs %d rows"
                % (want[0].size, got[0].size)
            )

    report = {
        "train_windows": TRAIN_WINDOWS,
        "records": TRAIN_WINDOWS * WINDOW_RECORDS,
        "distinct_id_space": HOT_VOCAB + TRAIN_WINDOWS * DRIFT,
        "max_rows_bound": MAX_ROWS,
        "baseline_resident_rows": int(baseline_rows),
        "lifecycle_resident_rows": int(lifecycle_rows),
        "rows_admitted": stats["rows_admitted"],
        "rows_evicted_ttl": stats["rows_evicted_ttl"],
        "rows_evicted_lfu": stats["rows_evicted_lfu"],
        "grad_rows_dropped": stats["grad_rows_dropped"],
        "holdout_tail_logloss_baseline": round(baseline_loss, 5),
        "holdout_tail_logloss_lifecycle": round(lifecycle_loss, 5),
        "base_rate_logloss": round(base_rate_logloss, 5),
        # ranking quality beside calibration (report-only: the gate
        # stays on logloss; AUC is the ROADMAP item-4 headroom metric)
        "holdout_tail_auc_baseline": round(baseline_auc, 5),
        "holdout_tail_auc_lifecycle": round(lifecycle_auc, 5),
        "parity": parity,
        "failures": failures,
    }
    print(json.dumps(report))
    if failures:
        for failure in failures:
            print("bench_streaming GATE FAILED: %s" % failure,
                  file=sys.stderr)
        return 1
    print(
        "bench_streaming OK: rows %d (bound %d) vs unbounded %d; "
        "tail logloss %.4f vs %.4f; parity %s"
        % (lifecycle_rows, MAX_ROWS, baseline_rows, lifecycle_loss,
           baseline_loss, parity),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
