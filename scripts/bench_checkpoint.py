#!/usr/bin/env python
"""Incremental-checkpoint proof scenario (ISSUE 13, tier 1f).

A Zipfian push stream over a bounded resident-row set (the shape the
streaming lifecycle guarantees) against a PS whose durability is the
new delta-chain + off-RPC checkpoint machinery, measured three ways:

1. **delta vs full save cost**: wall time of a delta save (dirty rows
   from one Zipfian window) vs a full save of the same store — the
   O(dirty) vs O(resident) claim. Hard gate: delta >= ``MIN_SPEEDUP``x
   faster on the numpy backend (native reported too).
2. **push p99 during checkpoints**: worker-observed push latency
   through the real servicer while checkpoints run off-RPC
   (EDL_CKPT_ASYNC=1) vs a no-checkpoint baseline — hard gate: p99
   within ``P99_FACTOR``x of baseline. The pre-ISSUE-13 inline mode is
   measured in the same run (report-only) to show the stall the
   checkpoint thread removes.
3. **restore equivalence**: base + deltas (with ``drop_rows``
   tombstones) restores bit-identically to a full save of the same
   live store on BOTH backends, and tombstoned ids stay dead. Hard
   gate.

Output: one JSON object on stdout (journaled by ci.sh tier 1f).
Exit 1 when a gate fails.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")  # run from the repo root, like ci.sh does

from elasticdl_tpu.common.tensor_utils import serialize_indexed_slices
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver
from elasticdl_tpu.ps.embedding_store import (
    NumpyEmbeddingStore,
    native_lib,
)

DIM = 16
RESIDENT_ROWS = 60000          # bounded resident set (lifecycle bound)
WINDOW_IDS = 2000              # Zipfian draws per push window
ZIPF_A = 1.3
SAVE_REPEATS = 3               # best-of per timing
MIN_SPEEDUP = 5.0              # delta save must beat full by this
P99_FACTOR = 1.5               # async push p99 vs no-ckpt baseline
P99_PUSHES = 400
P99_CKPT_STEPS = 25            # checkpoint cadence during the p99 run
RESTORE_WINDOWS = 6            # delta windows in the parity scenario


def make_store(backend, seed=0):
    if backend == "native":
        from elasticdl_tpu.ps.embedding_store import NativeEmbeddingStore

        store = NativeEmbeddingStore(seed=seed)
    else:
        store = NumpyEmbeddingStore(seed=seed)
    store.set_optimizer("adam", lr=0.05)
    store.create_table("emb", DIM, init_scale=0.0, initializer="zeros")
    return store


def populate(store, rows=RESIDENT_ROWS, seed=0):
    rng = np.random.RandomState(seed)
    ids = np.arange(rows, dtype=np.int64)
    for start in range(0, rows, 10000):
        chunk = ids[start:start + 10000]
        store.import_table(
            "emb", chunk,
            rng.rand(chunk.size, DIM).astype(np.float32),
        )


def zipf_window(rng, size=WINDOW_IDS, vocab=RESIDENT_ROWS):
    draws = rng.zipf(ZIPF_A, size=size)
    return np.unique((draws - 1) % vocab).astype(np.int64)


def timed(fn, repeats=SAVE_REPEATS):
    best = float("inf")
    for _ in range(repeats):
        start = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - start)
    return best


# ---------------------------------------------------------------------------
# 1. delta vs full save cost


def bench_save_cost(backend, tmp):
    store = make_store(backend)
    populate(store)
    rng = np.random.RandomState(1)
    chain_dir = os.path.join(tmp, "cost-%s" % backend)
    saver = SparseCheckpointSaver(chain_dir, compact_every=10 ** 6)
    version = [0]

    def full_save():
        version[0] += 1
        saver.save(version[0], store, force_full=True)

    full_secs = timed(full_save)
    dirty_rows = []

    def delta_save():
        ids = zipf_window(rng)
        store.push_gradients(
            "emb", ids, rng.randn(ids.size, DIM).astype(np.float32)
        )
        dirty_rows.append(store.dirty_count("emb"))
        version[0] += 1
        result = saver.save(version[0], store)
        assert result.kind == "delta", result

    delta_secs = timed(delta_save)
    return {
        "full_save_secs": round(full_secs, 4),
        "delta_save_secs": round(delta_secs, 4),
        "delta_dirty_rows": int(np.mean(dirty_rows)),
        "speedup": round(full_secs / max(delta_secs, 1e-9), 1),
    }


# ---------------------------------------------------------------------------
# 2. worker-observed push p99 during checkpoints (real PS subprocess:
#    latency includes the wire, the way a worker actually sees it —
#    an in-process loop would divide the checkpoint thread's GIL
#    slices by a strawman sub-millisecond baseline)


def _free_port():
    import socket

    probe = socket.socket()
    probe.bind(("", 0))
    port = probe.getsockname()[1]
    probe.close()
    return port


def _spawn_ps(tmp, mode, ckpt_steps):
    import subprocess

    port = _free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        EDL_CKPT_ASYNC="0" if mode == "inline" else "1",
        EDL_CKPT_COMPACT_EVERY="1000000",
    )
    env.pop("EDL_FAULT_SPEC", None)
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.ps.server",
        "--ps_id", "0", "--num_ps_pods", "1", "--port", str(port),
        "--opt_type", "adam", "--opt_args", "lr=0.05",
        "--use_async", "1", "--seed", "0",
    ]
    if ckpt_steps:
        ckpt_dir = os.path.join(tmp, "p99-ckpt-%s" % mode)
        os.makedirs(ckpt_dir, exist_ok=True)
        cmd += ["--checkpoint_dir", ckpt_dir,
                "--checkpoint_steps", str(ckpt_steps)]
    log = open(os.path.join(tmp, "ps-%s.log" % mode), "wb")
    proc = subprocess.Popen(cmd, env=env, stdout=log, stderr=log)
    import socket

    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            probe = socket.socket()
            probe.connect(("127.0.0.1", port))
            probe.close()
            return proc, port
        except OSError:
            time.sleep(0.2)
    proc.kill()
    raise TimeoutError("PS (%s) never came up" % mode)


def percentile(values, q):
    return float(np.percentile(values, q))


def bench_push_p99(tmp):
    """Three real-PS runs on identical Zipfian traffic: no checkpoints,
    off-RPC checkpoints (the new default), inline checkpoints (the
    pre-ISSUE-13 stall, report-only). The PS is a subprocess and each
    push is a real gRPC round trip — the latency a worker observes."""
    from elasticdl_tpu.worker.ps_client import PSClient

    results = {}
    for mode in ("baseline", "async", "inline"):
        ckpt_steps = 0 if mode == "baseline" else P99_CKPT_STEPS
        proc, port = _spawn_ps(tmp, mode, ckpt_steps)
        try:
            client = PSClient(["localhost:%d" % port], worker_id=0)
            client.push_embedding_table_infos([("emb", DIM, "zeros")])
            # materialize the resident set through real pushes
            rng = np.random.RandomState(0)
            all_ids = np.arange(RESIDENT_ROWS, dtype=np.int64)
            for start in range(0, RESIDENT_ROWS, 10000):
                chunk = all_ids[start:start + 10000]
                grads = {"emb": (
                    rng.rand(chunk.size, DIM).astype(np.float32), chunk
                )}
                assert client.push_gradients(
                    grads, model_version=0
                ).accepted
            rng = np.random.RandomState(42)
            # warmup (also fills the dirty set and, in the checkpointed
            # modes, opens the chain with its first saves)
            for _ in range(30):
                ids = zipf_window(rng)
                client.push_gradients(
                    {"emb": (rng.randn(ids.size, DIM).astype(
                        np.float32), ids)},
                    model_version=0,
                )
            latencies = []
            for _ in range(P99_PUSHES):
                ids = zipf_window(rng)
                grads = {"emb": (
                    rng.randn(ids.size, DIM).astype(np.float32), ids
                )}
                start = time.perf_counter()
                response = client.push_gradients(grads, model_version=0)
                latencies.append(time.perf_counter() - start)
                assert response.accepted
            lat = np.asarray(latencies)
            results[mode] = {
                "p50_ms": round(1e3 * percentile(lat, 50), 3),
                "p99_ms": round(1e3 * percentile(lat, 99), 3),
                "max_ms": round(1e3 * float(lat.max()), 3),
            }
        finally:
            proc.kill()
            proc.wait(timeout=15)
    results["async_vs_baseline_p99"] = round(
        results["async"]["p99_ms"]
        / max(results["baseline"]["p99_ms"], 1e-9), 2,
    )
    results["inline_vs_baseline_p99"] = round(
        results["inline"]["p99_ms"]
        / max(results["baseline"]["p99_ms"], 1e-9), 2,
    )
    return results


# ---------------------------------------------------------------------------
# 3. restore equivalence


def bench_restore_parity(backend, tmp):
    live = make_store(backend)
    populate(live, rows=5000)
    rng = np.random.RandomState(3)
    chain_dir = os.path.join(tmp, "parity-chain-%s" % backend)
    full_dir = os.path.join(tmp, "parity-full-%s" % backend)
    saver = SparseCheckpointSaver(chain_dir, compact_every=100)
    saver.save(1, live, force_full=True)
    dropped = []
    for w in range(RESTORE_WINDOWS):
        ids = zipf_window(rng, size=600, vocab=5000)
        live.push_gradients(
            "emb", ids, rng.randn(ids.size, DIM).astype(np.float32)
        )
        victims = rng.choice(5000, size=20, replace=False).astype(
            np.int64
        )
        live.drop_rows("emb", victims)
        dropped.extend(victims.tolist())
        saver.save(2 + w, live)
    SparseCheckpointSaver(full_dir).save(
        1 + RESTORE_WINDOWS, live, force_full=True
    )

    from_chain = make_store(backend, seed=1)
    from_full = make_store(backend, seed=2)
    SparseCheckpointSaver(chain_dir).restore(from_chain)
    SparseCheckpointSaver(full_dir).restore(from_full)

    def state(store):
        ids, rows, steps = store.export_table_full("emb")
        order = np.argsort(ids)
        return ids[order], rows[order], steps[order]

    a, b = state(from_chain), state(from_full)
    bit_identical = (
        a[0].shape == b[0].shape
        and (a[0] == b[0]).all()
        and (a[1] == b[1]).all()
        and (a[2] == b[2]).all()
    )
    resident = set(a[0].tolist())
    live_resident = set(live.export_table_full("emb")[0].tolist())
    # an id dropped then re-pushed is legitimately resident again —
    # dead means "absent from the live store", and the chain restore
    # must agree exactly
    tombstones_dead = all(
        (d in live_resident) == (d in resident) for d in dropped
    )
    return {
        "rows": int(a[0].size),
        "deltas": RESTORE_WINDOWS,
        "tombstones": len(set(dropped) - live_resident),
        "bit_identical": bool(bit_identical),
        "tombstones_dead": bool(tombstones_dead),
    }


def main():
    import tempfile

    backends = ["numpy"] + (
        ["native"] if native_lib() is not None else []
    )
    report = {"backends": backends}
    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        for backend in backends:
            report["save_cost_" + backend] = bench_save_cost(
                backend, tmp
            )
        speedup = report["save_cost_numpy"]["speedup"]
        if speedup < MIN_SPEEDUP:
            failures.append(
                "delta save only %.1fx faster than full (gate %.0fx)"
                % (speedup, MIN_SPEEDUP)
            )

        report["push_p99"] = bench_push_p99(tmp)
        ratio = report["push_p99"]["async_vs_baseline_p99"]
        if ratio > P99_FACTOR:
            failures.append(
                "push p99 under off-RPC checkpoints %.2fx baseline "
                "(gate %.1fx): the save leaked back onto the push path"
                % (ratio, P99_FACTOR)
            )

        for backend in backends:
            parity = bench_restore_parity(backend, tmp)
            report["restore_parity_" + backend] = parity
            if not parity["bit_identical"]:
                failures.append(
                    "%s: chain restore differs from full-save restore"
                    % backend
                )
            if not parity["tombstones_dead"]:
                failures.append(
                    "%s: a tombstoned id resurrected through the chain"
                    % backend
                )

    report["failures"] = failures
    print(json.dumps(report))
    if failures:
        for failure in failures:
            print("bench_checkpoint GATE FAILED: %s" % failure,
                  file=sys.stderr)
        return 1
    print(
        "bench_checkpoint OK: delta %.1fx faster than full; push p99 "
        "%.2fx baseline under off-RPC checkpoints (inline was %.2fx); "
        "chain restore bit-identical on %s"
        % (speedup, ratio,
           report["push_p99"]["inline_vs_baseline_p99"],
           "+".join(backends)),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
