#!/usr/bin/env python
"""Bench-trend regression watchdog: the trajectory finally gets a watcher.

The repo accumulates two performance records nothing reads:

- ``BENCH_r*.json`` at the repo root — one per growth round, each
  carrying ``parsed.value`` (the headline metric) plus a
  ``parsed.extra`` dict of per-workload numbers;
- the tier-1f CI journal (``/tmp/ci_wire_micro.jsonl`` by default) —
  one line per bench invocation, ``{"ts": ..., "<kind>": {...}}``.

This script folds both into a per-metric trajectory and flags any
metric whose LATEST value regresses more than ``--threshold`` (default
20%) against the best value ever recorded for it. Direction is
inferred from the metric name (``*_ms`` / ``*latency*`` / ``*loss*`` /
``*overhead*`` → lower is better; throughputs / ratios like
``*steps_per_sec`` / ``*mfu*`` / ``*hit_rate*`` → higher is better).

REPORT-ONLY by design, like every tier-1f number: absolute timings
flake across boxes, so a flagged regression is a prompt to look, not a
CI failure. The JSON report goes to stdout (journaled by ci.sh so the
watchdog's own history is greppable); the human table to stderr. Exit
code is 0 even with regressions; 1 only when no data was found at all.

Usage:
    python scripts/bench_trend.py [--repo-root DIR] [--journal FILE]
        [--threshold 0.2] [-o report.json]
"""

import argparse
import glob
import json
import math
import os
import sys

# name fragments that mean "smaller is better"; checked against
# _-separated name tokens so e.g. "examples" does not match "amp"
_LOWER_BETTER_TOKENS = frozenset({
    "ms", "secs", "seconds", "latency", "loss", "logloss", "overhead",
    "lag", "stall", "p50", "p99", "evictions", "misses",
})

# journal kinds that are themselves meta-reports, not bench numbers —
# folding them back in would make the watchdog watch itself
_SKIP_JOURNAL_KINDS = frozenset({
    "bench_trend", "critical_path", "profile_report",
})


def lower_is_better(name):
    tokens = set()
    for part in name.replace(".", "_").split("_"):
        tokens.add(part)
    return bool(tokens & _LOWER_BETTER_TOKENS)


def _flatten(prefix, value, out):
    """Numeric leaves of a nested dict as dotted names (bools and
    strings dropped; lists skipped — per-item series are not trends).
    Non-finite leaves are dropped too: a NaN in a trajectory poisons
    min()/max() and then ``v == best`` matches nothing, so one bad
    bench line would crash the whole tier-1f watchdog."""
    if isinstance(value, bool):
        return
    if isinstance(value, (int, float)):
        if math.isfinite(value):
            out[prefix] = float(value)
    elif isinstance(value, dict):
        for key, sub in value.items():
            _flatten("%s.%s" % (prefix, key) if prefix else str(key),
                     sub, out)


def load_bench_rounds(repo_root):
    """[(label, {metric: value})] from BENCH_r*.json, oldest first."""
    rounds = []
    for path in sorted(glob.glob(
        os.path.join(repo_root, "BENCH_r*.json")
    )):
        try:
            with open(path, "r", encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            print("skipping %s: %s" % (path, e), file=sys.stderr)
            continue
        parsed = payload.get("parsed") or {}
        metrics = {}
        name = parsed.get("metric")
        value = parsed.get("value")
        if (name and isinstance(value, (int, float))
                and not isinstance(value, bool)
                and math.isfinite(value)):
            metrics[str(name)] = float(value)
        extra = parsed.get("extra")
        if isinstance(extra, dict):
            _flatten("", extra, metrics)
        label = os.path.splitext(os.path.basename(path))[0]
        if metrics:
            rounds.append((label, metrics))
    return rounds


def load_journal(path):
    """[(label, {metric: value})] from tier-1f journal lines, in file
    order. Metric names DROP the journal kind prefix (``wire_micro``,
    ``serving``, ...): the bench scripts already namespace their keys
    (``deepfm_ctr_steps_per_sec``, ``serving_p99_ms``), and it is the
    leaf name that must line up with the same metric in the
    ``BENCH_r*.json`` extras for the two sources to form ONE
    trajectory. Torn lines are skipped (the journal is append-only
    across interrupted runs)."""
    entries = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return entries
    for index, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except ValueError:
            continue  # torn tail from an interrupted run
        if not isinstance(record, dict):
            continue
        ts = record.get("ts", "")
        for kind, payload in record.items():
            if kind == "ts" or kind in _SKIP_JOURNAL_KINDS:
                continue
            if not isinstance(payload, dict):
                continue
            metrics = {}
            _flatten("", payload, metrics)
            if metrics:
                entries.append(
                    ("journal[%d] %s %s" % (index, ts, kind), metrics)
                )
    return entries


def build_series(sources):
    """{metric: [(label, value), ...]} in recording order."""
    series = {}
    for label, metrics in sources:
        for name, value in metrics.items():
            series.setdefault(name, []).append((label, value))
    return series


def analyze(series, threshold=0.2):
    """Per-metric verdicts + the regression list."""
    metrics = {}
    regressions = []
    for name, points in sorted(series.items()):
        if len(points) < 2:
            continue  # one point is a value, not a trend
        lower = lower_is_better(name)
        values = [v for _, v in points]
        latest_label, latest = points[-1]
        if lower:
            best = min(values)
            regressing = (
                best > 0 and latest > best * (1.0 + threshold)
            )
            ratio = latest / best if best else 1.0
        else:
            best = max(values)
            regressing = (
                best > 0 and latest < best * (1.0 - threshold)
            )
            ratio = latest / best if best else 1.0
        # default guards StopIteration if a non-finite value ever slips
        # past ingestion (NaN == NaN is False, so it matches nothing)
        best_label = next(
            (l for l, v in points if v == best), latest_label
        )
        entry = {
            "points": len(points),
            "direction": "lower" if lower else "higher",
            "best": best,
            "best_at": best_label,
            "latest": latest,
            "latest_at": latest_label,
            "vs_best": round(ratio, 4),
            "regressing": regressing,
        }
        metrics[name] = entry
        if regressing:
            regressions.append(dict(entry, metric=name))
    return metrics, regressions


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    default_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    parser.add_argument("--repo-root", default=default_root,
                        help="where the BENCH_r*.json series lives")
    parser.add_argument("--journal", default="/tmp/ci_wire_micro.jsonl",
                        help="tier-1f NDJSON bench journal")
    parser.add_argument("--threshold", type=float, default=0.2,
                        help="regression threshold vs best (default "
                             "0.2 = 20%%)")
    parser.add_argument("-o", "--output", default="",
                        help="also write the JSON report here")
    args = parser.parse_args(argv)

    sources = load_bench_rounds(args.repo_root)
    sources += load_journal(args.journal)
    series = build_series(sources)
    if not series:
        print(
            "bench_trend: no BENCH_r*.json under %s and no journal at "
            "%s — nothing to watch" % (args.repo_root, args.journal),
            file=sys.stderr,
        )
        return 1
    metrics, regressions = analyze(series, threshold=args.threshold)
    tracked = len(metrics)
    print(
        "bench-trend: %d metric(s) with >=2 points, %d regressing "
        ">%.0f%% vs best"
        % (tracked, len(regressions), args.threshold * 100),
        file=sys.stderr,
    )
    for entry in regressions:
        print(
            "  REGRESSING %-48s latest %.4g (%s) vs best %.4g (%s), "
            "%.2fx [%s better]"
            % (entry["metric"], entry["latest"], entry["latest_at"],
               entry["best"], entry["best_at"], entry["vs_best"],
               entry["direction"]),
            file=sys.stderr,
        )
    report = {
        "tracked_metrics": tracked,
        "threshold": args.threshold,
        "regressions": regressions,
        "metrics": metrics,
    }
    text = json.dumps(report)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(text)
    print(text)
    # report-only: regressions are flagged, never fatal (tier-1f rule —
    # absolute numbers flake across boxes; the journal keeps the record)
    return 0


if __name__ == "__main__":
    sys.exit(main())
