"""ResNet bench window-length sweep: wall-clock per window = device
time (N steps) + fixed dispatch/fetch overhead. Fitting two window
lengths separates sustained device throughput from tunnel overhead."""
import json
import os
import sys
import time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from elasticdl_tpu.models import resnet
from elasticdl_tpu.train.optimizers import create_optimizer
from elasticdl_tpu.train.step_fns import make_train_step
from elasticdl_tpu.train.train_state import create_train_state

batch_size, image_size = 256, 224
model = resnet.resnet50(num_classes=1000, stem="space_to_depth")
tx = create_optimizer("Momentum", learning_rate=0.1, momentum=0.9, nesterov=True)
train_step = make_train_step(model, resnet.loss, tx, compute_dtype=jnp.bfloat16)

def run_steps(state, batch, n):
    def body(state, _):
        state, loss = train_step(state, batch)
        return state, loss
    return jax.lax.scan(body, state, None, length=n)

run = jax.jit(run_steps, static_argnums=(2,), donate_argnums=(0,))
rng = np.random.RandomState(0)
batch = {
    "features": jnp.asarray(rng.rand(batch_size, image_size, image_size, 3), jnp.float32),
    "labels": jnp.asarray(rng.randint(0, 1000, size=batch_size), jnp.int32),
    "_mask": jnp.ones((batch_size,), jnp.float32),
}
state = create_train_state(model, tx, jax.random.PRNGKey(0), batch["features"])

results = {}
for n in (20, 60):
    state, losses = run(state, batch, n)  # warmup+compile this length
    float(losses[-1])
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        state, losses = run(state, batch, n)
        float(losses[-1])
        best = min(best, time.perf_counter() - t0)
    results[n] = {"window_s": best, "ms_per_step": 1e3 * best / n,
                  "img_per_s": batch_size * n / best}
# overhead model: window = a + b*n  ->  b = device ms/step, a = fixed
b = (results[60]["window_s"] - results[20]["window_s"]) / 40
a = results[20]["window_s"] - 20 * b
results["fit"] = {"device_ms_per_step": 1e3 * b,
                  "fixed_overhead_ms_per_window": 1e3 * a,
                  "device_img_per_s": batch_size / b}
print(json.dumps(results, indent=1))
