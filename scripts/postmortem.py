#!/usr/bin/env python
"""Postmortem: one dead job's artifacts -> one ordered incident timeline.

Merges everything the flight recorder left under ``$EDL_EVENTS_DIR``:

- per-role NDJSON event journals  (``<role>-<pid>.events.ndjson``)
- crash-path ring dumps           (``<role>-<pid>.dump.json``)
- optionally, final Prometheus /metrics snapshots saved as
  ``*.metrics.txt`` (or passed via ``--metrics``), from which the alert
  counters are summarized

into a single timestamp-ordered timeline threaded by the correlation
keys every event carries (``job`` / ``worker`` / ``task`` /
``version``), plus a per-worker incident summary: relaunch epochs,
requeued tasks, alerts raised against it, and its crash dump reason.
One command turns "the job died overnight" into "worker-3 relaunched at
epoch 7, its requeued task t41 stalled round 12, the master alerted
stuck-round 8 s later".

Usage:
    python scripts/postmortem.py EVENTS_DIR [-o incident.json]

The text report goes to stdout, the JSON report to ``-o`` (default
``EVENTS_DIR/incident.json``). Exit code 1 when no events were found.
"""

import argparse
import collections
import glob
import json
import os
import sys


def _parse_ndjson(text):
    """Tolerant NDJSON parse: a torn final line from a SIGKILLed role
    is skipped, not fatal — partial journals are the expected input."""
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except ValueError:
            continue  # torn tail write from a killed role
        if isinstance(record, dict):
            records.append(record)
    return records


def load_journals(events_dir):
    """All journal events, each stamped with its source file."""
    loaded = []
    for path in sorted(glob.glob(
        os.path.join(events_dir, "*.events.ndjson")
    )):
        try:
            with open(path, "r", encoding="utf-8") as f:
                records = _parse_ndjson(f.read())
        except OSError as e:
            print("skipping %s: %s" % (path, e), file=sys.stderr)
            continue
        name = os.path.basename(path)
        for record in records:
            record.setdefault("source", name)
        loaded.extend(records)
    return loaded


def load_dumps(events_dir):
    """Crash-dump events + the dump headers (role, pid, reason)."""
    dump_events = []
    headers = []
    for path in sorted(glob.glob(
        os.path.join(events_dir, "*.dump.json")
    )):
        try:
            with open(path, "r", encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            print("skipping %s: %s" % (path, e), file=sys.stderr)
            continue
        name = os.path.basename(path)
        headers.append(
            {
                "source": name,
                "role": payload.get("role"),
                "pid": payload.get("pid"),
                "reason": payload.get("reason"),
                "dumped_at": payload.get("dumped_at"),
                "events": len(payload.get("events", ())),
            }
        )
        for record in payload.get("events", ()):
            if isinstance(record, dict):
                record.setdefault("source", name)
                dump_events.append(record)
    return dump_events, headers


def dedupe(events):
    """Journal + dump overlap (dumps re-record the journaled tail):
    keep one copy per (role, pid, seq); events without a seq pass
    through untouched. Journal copies win (listed first by caller)."""
    seen = set()
    unique = []
    for event in events:
        key = (event.get("role"), event.get("pid"), event.get("seq"))
        if key[2] is not None:
            if key in seen:
                continue
            seen.add(key)
        unique.append(event)
    return unique


def build_timeline(events):
    """Timestamp-ordered (ties: role, seq) merged event list."""
    return sorted(
        events,
        key=lambda e: (
            e.get("ts", 0.0), str(e.get("role", "")), e.get("seq", 0)
        ),
    )


def load_metrics_snapshots(paths):
    """Alert counters out of saved Prometheus text snapshots:
    {series_line: value} for every edl_master_alerts* sample."""
    counters = {}
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            print("skipping %s: %s" % (path, e), file=sys.stderr)
            continue
        for line in text.splitlines():
            if line.startswith("edl_master_alerts"):
                parts = line.rsplit(None, 1)
                if len(parts) == 2:
                    try:
                        counters[parts[0]] = float(parts[1])
                    except ValueError:
                        pass
    return counters


def summarize(timeline, dump_headers):
    """Per-worker incident summary threaded by the correlation keys."""
    workers = collections.defaultdict(lambda: {
        "registrations": [], "requeued_tasks": [], "alerts": [],
        "presumed_dead": 0, "dump": None,
    })
    rounds = {"opened": 0, "closed": 0, "stale_rejected": 0}
    # embedding lifecycle + streaming (ISSUE 12): tombstone tallies,
    # the per-id eviction index behind "why is this row cold", and the
    # last observed watermark
    lifecycle = {
        "rows_admitted": 0, "rows_evicted_ttl": 0,
        "rows_evicted_lfu": 0,
    }
    evicted_ids = {}  # "table/id" -> last eviction reason
    stream = {"watermark": 0, "checkpoints": 0, "exports": 0,
              "closed": False}
    # training health (ISSUE 15): sentinel events + the health alerts
    # threaded per role, so "did the model break, where, and what did
    # the sentinel do about it" is one summary read
    health = {"nonfinite": 0, "loss_spikes": 0, "grad_explosions": 0,
              "halts": 0, "table_exploding": 0}
    health_roles = {}  # role -> [event kinds in order]
    # device runtime (ISSUE 18): recompile sentinel events + the storm
    # alerts threaded per role, with the LAST recompile's shape
    # provenance kept verbatim — "what shape changed" is the whole
    # debugging story of a recompile storm
    device = {"recompiles": 0, "recompile_storms": 0,
              "hbm_pressure": 0, "compile_secs": 0.0}
    device_roles = {}  # role -> {"recompiles": n, "last_changed": [..]}
    # dense data plane (ISSUE 20): every mesh-epoch restart the elastic
    # controller (or a worker death) forced, with the old -> new mesh
    # shapes kept verbatim — grow/shrink history is the elasticity
    # story of the run
    # "restarts" counts the master's authoritative epoch bumps (the
    # events carrying old/new worlds); "worker_exits" counts the
    # individual workers that journaled their restart-and-rejoin
    mesh = {"restarts": 0, "grows": 0, "shrinks": 0, "worker_exits": 0}
    mesh_transitions = []  # ordered "old -> new (reason)" strings
    job_failed = None
    for event in timeline:
        kind = event.get("event")
        worker = event.get("worker")
        if kind == "worker_register":
            workers[worker]["registrations"].append(event.get("epoch"))
        elif kind == "task_requeue":
            workers[worker]["requeued_tasks"].append(event.get("task"))
        elif kind == "worker_presumed_dead":
            workers[worker]["presumed_dead"] += 1
        elif kind == "alert_raised":
            target = event.get("target")
            try:
                target = int(target)
            except (TypeError, ValueError):
                pass
            workers[target]["alerts"].append(event.get("alert"))
            if event.get("alert") == "recompile_storm":
                device["recompile_storms"] += 1
            elif event.get("alert") == "hbm_pressure":
                device["hbm_pressure"] += 1
        elif kind == "round_open":
            rounds["opened"] += 1
        elif kind == "round_close":
            rounds["closed"] += 1
        elif kind == "stale_push_rejected":
            rounds["stale_rejected"] += 1
        elif kind == "row_admitted":
            lifecycle["rows_admitted"] += int(event.get("count", 0))
        elif kind == "row_evicted":
            reason = event.get("reason", "ttl")
            key = "rows_evicted_%s" % reason
            lifecycle[key] = lifecycle.get(key, 0) + int(
                event.get("count", 0)
            )
            table = event.get("table", "?")
            for row_id in event.get("ids", ()):
                evicted_ids["%s/%s" % (table, row_id)] = reason
        elif kind == "stream_watermark":
            stream["watermark"] = max(
                stream["watermark"], int(event.get("watermark", 0))
            )
            marker = event.get("kind")
            if marker == "checkpoint":
                stream["checkpoints"] += 1
            elif marker == "export":
                stream["exports"] += 1
            elif marker == "closed":
                stream["closed"] = True
        elif kind == "job_failed":
            job_failed = event
        elif kind in (
            "health_nonfinite", "health_loss_spike",
            "health_grad_explosion", "health_halt",
            "health_table_exploding",
        ):
            tally = {
                "health_nonfinite": "nonfinite",
                "health_loss_spike": "loss_spikes",
                "health_grad_explosion": "grad_explosions",
                "health_halt": "halts",
                "health_table_exploding": "table_exploding",
            }[kind]
            health[tally] += 1
            health_roles.setdefault(
                str(event.get("role", "?")), []
            ).append(kind)
        elif kind == "mesh_epoch_restart":
            if "new_world" not in event:
                mesh["worker_exits"] += 1  # a worker's own exit record
                continue
            mesh["restarts"] += 1
            old_world = int(event.get("old_world", 0))
            new_world = int(event.get("new_world", 0))
            if new_world > old_world:
                mesh["grows"] += 1
            elif new_world < old_world:
                mesh["shrinks"] += 1
            mesh_transitions.append(
                "%s -> %s (epoch %s, %s)"
                % (
                    event.get("old_mesh", "?"),
                    event.get("new_mesh", "?"),
                    event.get("epoch", "?"),
                    event.get("reason", "?"),
                )
            )
        elif kind == "xla_recompile":
            device["recompiles"] += 1
            device["compile_secs"] += float(event.get("seconds", 0.0))
            entry = device_roles.setdefault(
                str(event.get("role", "?")),
                {"recompiles": 0, "fns": [], "last_changed": []},
            )
            entry["recompiles"] += 1
            fn = event.get("fn", "?")
            if fn not in entry["fns"]:
                entry["fns"].append(fn)
            entry["last_changed"] = event.get("changed", [])
    for header in dump_headers:
        role = header.get("role") or ""
        # worker dumps are keyed by the role's worker id when present
        for worker, entry in workers.items():
            if role == "worker-%s" % worker:
                entry["dump"] = header.get("reason")
    return {
        "workers": {str(k): v for k, v in sorted(
            workers.items(), key=lambda kv: str(kv[0])
        )},
        "rounds": rounds,
        "lifecycle": lifecycle,
        "evicted_ids": evicted_ids,
        "stream": stream,
        "health": health,
        "health_roles": health_roles,
        "device": device,
        "device_roles": device_roles,
        "mesh": mesh,
        "mesh_transitions": mesh_transitions,
        "job_failed": job_failed,
    }


def render_text(timeline, summary, dump_headers, alert_counters):
    """Human-readable incident report."""
    lines = []
    if timeline:
        t0 = timeline[0].get("ts", 0.0)
        lines.append(
            "incident timeline (%d events, t0=%s):"
            % (len(timeline), t0)
        )
        for event in timeline:
            detail = {
                k: v for k, v in event.items()
                if k not in ("ts", "role", "pid", "seq", "event",
                             "source", "job")
            }
            lines.append(
                "  [%+10.3fs] %-12s %-22s %s"
                % (
                    event.get("ts", t0) - t0,
                    str(event.get("role", "?")),
                    str(event.get("event", "?")),
                    " ".join(
                        "%s=%s" % (k, v) for k, v in sorted(detail.items())
                    ),
                )
            )
    else:
        lines.append("incident timeline: no events found")
    if dump_headers:
        lines.append("crash dumps:")
        for header in dump_headers:
            lines.append(
                "  %s: reason=%s events=%d"
                % (header["source"], header["reason"], header["events"])
            )
    if alert_counters:
        lines.append("alert counters (final /metrics snapshot):")
        for series, value in sorted(alert_counters.items()):
            lines.append("  %s = %g" % (series, value))
    lines.append("per-worker summary:")
    for worker, entry in summary["workers"].items():
        lines.append(
            "  worker %s: epochs=%s requeued=%s alerts=%s "
            "presumed_dead=%d dump=%s"
            % (
                worker, entry["registrations"], entry["requeued_tasks"],
                entry["alerts"], entry["presumed_dead"], entry["dump"],
            )
        )
    if summary["rounds"]["opened"] or summary["rounds"]["stale_rejected"]:
        lines.append("  sync rounds: %r" % (summary["rounds"],))
    lifecycle = summary.get("lifecycle", {})
    if any(lifecycle.values()):
        lines.append("  embedding lifecycle: %r" % (lifecycle,))
    stream = summary.get("stream", {})
    if stream.get("watermark"):
        lines.append(
            "  stream: watermark=%d checkpoints=%d exports=%d "
            "closed=%s"
            % (stream["watermark"], stream["checkpoints"],
               stream["exports"], stream["closed"])
        )
    health = summary.get("health", {})
    if any(health.values()):
        lines.append("  training health: %r" % (health,))
        for role, kinds in sorted(
            summary.get("health_roles", {}).items()
        ):
            lines.append("    %s: %s" % (role, ", ".join(kinds)))
    device = summary.get("device", {})
    if any(device.values()):
        lines.append("  device runtime: %r" % (device,))
        for role, entry in sorted(
            summary.get("device_roles", {}).items()
        ):
            lines.append(
                "    %s: recompiles=%d fns=%s last_changed=%s"
                % (role, entry["recompiles"], ",".join(entry["fns"]),
                   entry["last_changed"])
            )
    mesh = summary.get("mesh", {})
    if mesh.get("restarts") or mesh.get("worker_exits"):
        lines.append(
            "  mesh epochs: restarts=%d grows=%d shrinks=%d "
            "worker_exits=%d"
            % (mesh["restarts"], mesh["grows"], mesh["shrinks"],
               mesh["worker_exits"])
        )
        for transition in summary.get("mesh_transitions", ()):
            lines.append("    %s" % transition)
    if summary["job_failed"]:
        lines.append("  JOB FAILED: %r" % (summary["job_failed"],))
    return "\n".join(lines)


def postmortem(events_dir, metrics_paths=()):
    """The whole pipeline; returns the JSON-ready incident report."""
    journal_events = load_journals(events_dir)
    dump_events, dump_headers = load_dumps(events_dir)
    timeline = build_timeline(dedupe(journal_events + dump_events))
    metrics_paths = list(metrics_paths) or sorted(
        glob.glob(os.path.join(events_dir, "*.metrics.txt"))
    )
    alert_counters = load_metrics_snapshots(metrics_paths)
    summary = summarize(timeline, dump_headers)
    return {
        "events_dir": events_dir,
        "timeline": timeline,
        "dumps": dump_headers,
        "alert_counters": alert_counters,
        "summary": summary,
    }


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("events_dir", help="EDL_EVENTS_DIR of the run")
    parser.add_argument(
        "-o", "--output", default="",
        help="write the JSON report here "
             "(default: EVENTS_DIR/incident.json)",
    )
    parser.add_argument(
        "--metrics", action="append", default=[],
        help="saved /metrics snapshot(s) to fold in (default: "
             "EVENTS_DIR/*.metrics.txt)",
    )
    args = parser.parse_args(argv)
    report = postmortem(args.events_dir, args.metrics)
    out = args.output or os.path.join(args.events_dir, "incident.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2)
    # text to stdout, JSON to the file: both shapes, one command
    print(render_text(
        report["timeline"], report["summary"], report["dumps"],
        report["alert_counters"],
    ))
    print(
        "postmortem: %d events -> %s" % (len(report["timeline"]), out),
        file=sys.stderr,
    )
    return 0 if report["timeline"] else 1


if __name__ == "__main__":
    sys.exit(main())
