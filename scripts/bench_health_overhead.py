#!/usr/bin/env python
"""Health-scalar overhead gate (ISSUE 15): deepfm steps/s, EDL_HEALTH
on vs off.

The training-health contract is "watching the model costs nothing you
can measure": the in-graph health scalars (masked loss, global grad
norm, nonfinite flag) plus the per-batch HealthTracker fold must keep
deepfm CTR steps/s within 2% of a health-disabled run. This bench
builds TWO trainers in ONE process — one with the tracker (extra
jitted outputs + host fold), one compiled exactly as the pre-health
program — and alternates measurement segments between them
(off-on, on-off, ...) so box drift cancels, the same discipline as
``bench_profiler_overhead.py``.

Absolute steps/s are REPORT-ONLY (journaled by ci.sh tier 1f like
every bench); the script hard-fails only the acceptance gate:
measured overhead above 2% (with one full re-measure first — a single
GC pause can eat 2% on its own; a real regression fails both passes),
or a health trainer that tracked no batches at all (the A/B would be
vacuous).
"""

import json
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, ".")

GATE = 0.02
WARMUP_STEPS = 12
DISTINCT_BATCHES = 30
SEGMENT_STEPS = 150
SEGMENTS_PER_MODE = 3


def make_batches(n, batch=256, fields=16, vocab=10_000, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = (rng.zipf(1.3, size=(batch, fields)) % vocab).astype(
            np.int64
        )
        out.append({
            "features": {"ids": ids},
            "labels": rng.randint(0, 2, batch).astype(np.float32),
            "_mask": np.ones(batch, np.float32),
        })
    return out


def build_trainer(health):
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.ps.local_client import LocalPSClient
    from elasticdl_tpu.train.health import HealthTracker
    from elasticdl_tpu.train.sparse import SparseTrainer

    return SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(
            num_features=16, batch_size=256
        ),
        ps_client=LocalPSClient(seed=0, opt_type="adam", lr=0.001),
        seed=0,
        health=HealthTracker(action="alert") if health else False,
    )


def run_segment(trainer, state, batches):
    start = time.perf_counter()
    for step in range(SEGMENT_STEPS):
        state, loss = trainer.train_step(
            state, batches[step % len(batches)]
        )
    float(loss)  # join any async device work before stopping the clock
    elapsed = time.perf_counter() - start
    return state, SEGMENT_STEPS / elapsed


def measure(trainers, states, batches):
    """Interleaved off/on segments, pair order alternating (same
    rationale as bench_profiler_overhead.measure: a warming/cooling
    box must not hand either mode a systematic position edge)."""
    off = []
    on = []

    def run(mode):
        states[mode], sps = run_segment(
            trainers[mode], states[mode], batches
        )
        (off if mode == "off" else on).append(sps)

    for pair in range(SEGMENTS_PER_MODE):
        first, second = (
            ("off", "on") if pair % 2 == 0 else ("on", "off")
        )
        run(first)
        run(second)
    return statistics.median(off), statistics.median(on)


def main():
    trainers = {"off": build_trainer(False), "on": build_trainer(True)}
    batches = make_batches(DISTINCT_BATCHES)
    states = {"off": None, "on": None}
    for mode in ("off", "on"):
        for batch in batches[:WARMUP_STEPS]:
            states[mode], loss = trainers[mode].train_step(
                states[mode], batch
            )
        float(loss)

    off_sps, on_sps = measure(trainers, states, batches)
    overhead = 1.0 - on_sps / off_sps
    if overhead > GATE:
        # one re-measure before failing: a GC pause or noisy CI
        # neighbor can eat 2% on its own; a real regression repeats
        off2, on2 = measure(trainers, states, batches)
        if 1.0 - on2 / off2 < overhead:
            off_sps, on_sps = off2, on2
            overhead = 1.0 - on2 / off2
    tracked = trainers["on"].health.samples
    for trainer in trainers.values():
        trainer.close()

    result = {
        "deepfm_health_overhead_ratio": round(overhead, 4),
        "deepfm_steps_per_sec_health_off": round(off_sps, 3),
        "deepfm_steps_per_sec_health_on": round(on_sps, 3),
        "health_batches_tracked": tracked,
    }
    print(json.dumps(result))
    if tracked <= 0:
        print(
            "bench_health_overhead: FAIL the health trainer tracked 0 "
            "batches — the A/B measured nothing",
            file=sys.stderr,
        )
        return 1
    if overhead > GATE:
        print(
            "bench_health_overhead: FAIL %.1f%% overhead exceeds the "
            "%.0f%% contract (off %.2f vs on %.2f steps/s)"
            % (overhead * 100, GATE * 100, off_sps, on_sps),
            file=sys.stderr,
        )
        return 1
    print(
        "health-scalar overhead %.2f%% (off %.2f, on %.2f steps/s)"
        % (overhead * 100, off_sps, on_sps),
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
