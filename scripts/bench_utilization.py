#!/usr/bin/env python
"""Resource-utilization analogue of the reference's §B cluster
experiment (/root/reference/docs/benchmark/report_cn.md:90-104): the
reference co-located ElasticDL training with an autoscaling NGINX
deployment and measured >90% sustained cluster CPU utilization —
elastic training backfills whatever capacity the foreground service
isn't using, and yields it back when demand returns.

This is the one-box miniature that environment can run (no cluster,
no container runtime, **nproc=1** — see the honesty notes at the
bottom of docs/UTILIZATION.md):

- A FOREGROUND SERVICE process whose CPU demand oscillates
  sinusoidally (duty-cycled busy loop, period --period_secs),
  standing in for the autoscaling NGINX deployment.
- A real training job — master task queue + `worker.main`
  subprocess(es) training the mnist zoo CNN on generated digits
  RecordIO — co-located under one of two policies:

  * **elastic**: workers run at `nice 19`, always schedulable — the
    kernel gives them exactly the cycles the foreground leaves idle
    (the priority mechanics the reference delegated to K8s
    preemption; SURVEY.md §2.10).
  * **gang**: the job runs only when its full share is available —
    whenever foreground demand exceeds --gang_threshold the WHOLE
    worker group is SIGSTOPped (a gang-scheduled job cannot run
    degraded), SIGCONTed when demand falls.

Measured per arm, from /proc/stat and the service's own counters:

- box CPU utilization (mean over the job's lifetime),
- training makespan (task-queue drain time),
- foreground service throughput (work quanta/s — interference probe).

Prints one JSON line; `--write_doc` refreshes docs/UTILIZATION.md.
Smoke-tested in CI (tests/test_utilization.py) with a tiny job.
"""

import argparse
import json
import math
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# --------------------------------------------------------------------
# foreground service: oscillating duty-cycled busy loop
# --------------------------------------------------------------------

FOREGROUND_SRC = r"""
import math, os, sys, time
period = float(sys.argv[1])
out_path = sys.argv[2]
window = 0.1
quanta = 0
start = time.time()
while True:
    t = time.time() - start
    duty = 0.5 + 0.45 * math.sin(2 * math.pi * t / period)
    busy_until = time.time() + window * duty
    while time.time() < busy_until:
        quanta += 1
        x = 1.0
        for _ in range(2000):
            x = x * 1.0000001 + 1e-9
    time.sleep(max(0.0, window * (1.0 - duty)))
    # progress counter, atomically replaced (throughput probe)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        f.write("%d %f" % (quanta, t))
    os.replace(tmp, out_path)
"""


def read_proc_stat():
    with open("/proc/stat") as f:
        fields = f.readline().split()[1:]
    values = [int(v) for v in fields]
    idle = values[3] + values[4]  # idle + iowait
    return sum(values), idle


def foreground_demand(t, period):
    return 0.5 + 0.45 * math.sin(2 * math.pi * t / period)


# --------------------------------------------------------------------
# the training job: real master + worker.main subprocess
# --------------------------------------------------------------------


def make_digits_data(root):
    import numpy as np
    from sklearn import datasets

    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import write_records

    digits = datasets.load_digits()
    os.makedirs(root, exist_ok=True)
    payloads = []
    for image, label in zip(digits.images, digits.target):
        big = np.kron(image, np.ones((4, 4)))[2:30, 2:30]
        big = (big / 16.0 * 255.0).clip(0, 255)
        payloads.append(encode_example({
            "image": big.astype(np.uint8), "label": np.int64(label),
        }))
    write_records(os.path.join(root, "f0.rec"), payloads)


def run_arm(policy, args, train_dir, scratch):
    """One co-located run; returns the measured dict."""
    from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
    from elasticdl_tpu.data.readers import RecordIODataReader
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto.services import add_master_servicer_to_server

    reader = RecordIODataReader(data_dir=train_dir)
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=args.records_per_task,
        num_epochs=args.num_epochs,
        seed=0,
    )
    servicer = MasterServicer(dispatcher, None)
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()

    fg_progress = os.path.join(scratch, "fg_%s.txt" % policy)
    fg = subprocess.Popen(
        [sys.executable, "-c", FOREGROUND_SRC,
         str(args.period_secs), fg_progress],
    )
    # the gang controller must track the FOREGROUND's sinusoid phase —
    # its clock starts at the fg process spawn, NOT at the measurement
    # window onset (which resets `start` below at the first step log)
    fg_start = time.time()

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    worker_cmd = [
        sys.executable, "-m", "elasticdl_tpu.worker.main",
        "--master_addr", "localhost:%d" % port,
        "--worker_id", "0",
        "--model_zoo", "elasticdl_tpu.models.mnist",
        "--training_data", train_dir,
        "--minibatch_size", "64",
        # early + frequent step logs: the first "step" line is the
        # steady-state trigger that starts the measurement window
        "--log_loss_steps", "5",
    ]
    if policy == "elastic":
        worker_cmd = ["nice", "-n", "19"] + worker_cmd
    log = open(os.path.join(scratch, "worker_%s.log" % policy), "wb")
    worker = subprocess.Popen(
        worker_cmd, env=env, stdout=log, stderr=subprocess.STDOUT,
        cwd=REPO,
    )

    total0, idle0 = read_proc_stat()
    start = time.time()
    stopped = False
    measuring = False  # util window starts at the first training step
    deadline = start + args.timeout_secs
    try:
        while not dispatcher.finished() and time.time() < deadline:
            if worker.poll() is not None:
                raise RuntimeError(
                    "worker died rc=%s; log: %s" % (
                        worker.returncode,
                        open(log.name, "rb").read()[-1500:],
                    )
                )
            if not measuring and b"step" in open(log.name, "rb").read():
                # exclude worker startup (imports + jit compile, ~60 s
                # on this box) from the utilization window: the
                # reference's claim is about STEADY-STATE backfill
                total0, idle0 = read_proc_stat()
                start = time.time()
                measuring = True
            if policy == "gang":
                demand = foreground_demand(
                    time.time() - fg_start, args.period_secs
                )
                if demand > args.gang_threshold and not stopped:
                    os.kill(worker.pid, signal.SIGSTOP)
                    stopped = True
                elif demand <= args.gang_threshold and stopped:
                    os.kill(worker.pid, signal.SIGCONT)
                    stopped = False
            time.sleep(0.25)
        finished = dispatcher.finished()
        makespan = time.time() - start
        total1, idle1 = read_proc_stat()
        quanta, fg_secs = 0, makespan
        if os.path.exists(fg_progress):
            parts = open(fg_progress).read().split()
            quanta, fg_secs = int(parts[0]), float(parts[1])
        busy = (total1 - total0) - (idle1 - idle0)
        return {
            "finished": finished,
            "makespan_s": round(makespan, 1),
            "box_cpu_util": round(busy / max(1, total1 - total0), 4),
            "fg_quanta_per_s": round(quanta / max(1e-6, fg_secs), 1),
        }
    finally:
        if stopped:
            os.kill(worker.pid, signal.SIGCONT)
        for proc in (worker, fg):
            if proc.poll() is None:
                proc.kill()
        server.stop(0)


def fg_baseline(args, scratch):
    """Foreground alone: its unimpeded throughput + the box utilization
    its oscillating demand leaves on the table."""
    fg_progress = os.path.join(scratch, "fg_alone.txt")
    fg = subprocess.Popen(
        [sys.executable, "-c", FOREGROUND_SRC,
         str(args.period_secs), fg_progress],
    )
    total0, idle0 = read_proc_stat()
    time.sleep(args.baseline_secs)
    total1, idle1 = read_proc_stat()
    fg.kill()
    quanta, fg_secs = 0, args.baseline_secs
    if os.path.exists(fg_progress):
        parts = open(fg_progress).read().split()
        quanta, fg_secs = int(parts[0]), float(parts[1])
    busy = (total1 - total0) - (idle1 - idle0)
    return {
        "box_cpu_util": round(busy / max(1, total1 - total0), 4),
        "fg_quanta_per_s": round(quanta / max(1e-6, fg_secs), 1),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--period_secs", type=float, default=20.0)
    p.add_argument("--gang_threshold", type=float, default=0.5)
    p.add_argument("--records_per_task", type=int, default=256)
    p.add_argument("--num_epochs", type=int, default=2)
    p.add_argument("--timeout_secs", type=float, default=900.0)
    p.add_argument("--baseline_secs", type=float, default=30.0)
    p.add_argument("--scratch", default="/tmp/edl_utilization")
    p.add_argument("--write_doc", action="store_true")
    args = p.parse_args()

    os.makedirs(args.scratch, exist_ok=True)
    train_dir = os.path.join(args.scratch, "train")
    if not os.path.exists(os.path.join(train_dir, "f0.rec")):
        make_digits_data(train_dir)

    baseline = fg_baseline(args, args.scratch)
    results = {"foreground_alone": baseline}
    for policy in ("elastic", "gang"):
        results[policy] = run_arm(
            policy, args, train_dir, args.scratch
        )
    results["config"] = {
        "period_secs": args.period_secs,
        "gang_threshold": args.gang_threshold,
        "records_per_task": args.records_per_task,
        "num_epochs": args.num_epochs,
        "nproc": os.cpu_count(),
    }
    print(json.dumps(results))
    if args.write_doc:
        write_doc(results)


def write_doc(results):
    doc = os.path.join(REPO, "docs", "UTILIZATION.md")
    cfg = results["config"]
    base = results["foreground_alone"]
    elastic = results["elastic"]
    gang = results["gang"]
    text = """# Resource utilization under co-located load (§B analogue)

Miniature of the reference's cluster-utilization experiment
(`/root/reference/docs/benchmark/report_cn.md:90-104`,
`docs/benchmark/data/2.csv`): there, ElasticDL training co-located
with an autoscaling NGINX deployment kept cluster CPU >90 percent
busy. Here, a real training job (master task queue + `worker.main`
subprocess, mnist zoo CNN on digits RecordIO) is co-located with a
foreground service whose CPU demand oscillates sinusoidally
(period {period:.0f} s), under two policies:

- **elastic** - workers niced to 19: the kernel hands them exactly
  the cycles the service leaves idle, and hands them back on demand
  (the preemption mechanics the reference delegated to K8s priority).
- **gang** - the whole worker group is SIGSTOPped whenever
  foreground demand exceeds {thresh:.0f} percent (a gang-scheduled
  job cannot run degraded) and resumed below it.

Harness: `scripts/bench_utilization.py` (CI smoke:
`tests/test_utilization.py`).

## Measured ({date}, nproc={nproc})

| arm | box CPU util | train makespan | fg throughput (quanta/s) |
|---|---|---|---|
| foreground alone | {base_util:.1f} percent | - | {base_fg} |
| + elastic training | {e_util:.1f} percent | {e_mk:.0f} s | {e_fg} |
| + gang training | {g_util:.1f} percent | {g_mk:.0f} s | {g_fg} |

Reading: the oscillating service alone leaves ~{idle:.0f} percent of
the box idle; co-locating elastic training lifts utilization to
~{e_util:.0f} percent (the reference's headline effect) while the
service keeps {fg_keep:.0f} percent of its solo throughput (values
near or above 100 are run-to-run variance: the niced trainer is
invisible to it). The gang
policy forfeits the trough capacity it is stopped through - same box,
{mk_ratio:.2f}x the makespan.

## Honesty notes

- **nproc=1 in this container**: every process time-slices one core,
  so "utilization" measures how completely the policies fill ONE
  core's idle gaps, not multi-core packing; the foreground and the
  trainer contend for the same caches as well. The shape of the
  result (elastic fills troughs, gang forfeits them) is the part
  that transfers; the absolute percentages are not cluster numbers.
- The gang arm's SIGSTOP policy is a stand-in for gang scheduling's
  all-or-nothing property, not a real scheduler: a cluster gang job
  would also pay queue/restart latency this model omits (it is
  GENEROUS to gang).
- The elastic arm uses OS priorities where the reference used K8s
  priorities + pod preemption; the task queue (master/task
  dispatcher) is identical to the one the cluster path uses.
""".format(
        period=cfg["period_secs"],
        thresh=100 * cfg["gang_threshold"],
        date=time.strftime("%Y-%m-%d"),
        nproc=cfg["nproc"],
        base_util=100 * base["box_cpu_util"],
        base_fg=base["fg_quanta_per_s"],
        e_util=100 * elastic["box_cpu_util"],
        e_mk=elastic["makespan_s"],
        e_fg=elastic["fg_quanta_per_s"],
        g_util=100 * gang["box_cpu_util"],
        g_mk=gang["makespan_s"],
        g_fg=gang["fg_quanta_per_s"],
        idle=100 * (1 - base["box_cpu_util"]),
        fg_keep=100 * elastic["fg_quanta_per_s"]
        / max(1e-9, base["fg_quanta_per_s"]),
        mk_ratio=gang["makespan_s"] / max(1e-9, elastic["makespan_s"]),
    )
    with open(doc, "w") as f:
        f.write(text)
    print("wrote " + doc)


if __name__ == "__main__":
    main()
