"""AllReduce (lockstep SPMD) worker-scaling curve (reference §A parity).

Reference family (BASELINE.md §A / ftlib_benchmark.md:69-86): CIFAR-10
CNN throughput scaling 1 -> 8 AllReduce workers on an on-prem CPU
cluster (cpu=4/mem=8GiB per worker; ResNet50 scaled 4.61x at 8,
MobileNetV2 1.83x). This measures the same shape with this framework's
cross-host data plane: N worker OS processes under live
jax.distributed, the mesh spanning the processes, dp psums riding the
(loopback) DCN, elastic task queue feeding shards — i.e. the
multi-host lockstep trainer, not a simulated mesh.

Caveat printed with the result: all N workers share ONE machine's
cores, so compute contention caps the curve well below a real
cluster's; the number that transfers is the framework overhead (the
collective + consensus + task-queue path), not the hardware scaling.

Prints one JSON line with examples/sec per world size.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"


def _spawn_worker(idx, master_port, coordinator_port, train_dir, tmp,
                  model):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO,
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    log = open(os.path.join(tmp, "w%d.log" % idx), "ab")
    try:
        return subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.worker.main",
         "--master_addr", "localhost:%d" % master_port,
         "--worker_id", str(idx),
         "--model_zoo", model,
         "--training_data", train_dir,
         "--minibatch_size", "64",
         "--multihost", "1",
         "--coordinator_port", str(coordinator_port),
         "--worker_host", "localhost:%d" % (62000 + idx)],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        )
    finally:
        log.close()  # Popen dup'd the fd; don't leak one per relaunch


def run_world(n, train_dir, records, model):
    from elasticdl_tpu.common.grpc_utils import (
        build_server, find_free_port,
    )
    from elasticdl_tpu.data.readers import RecordIODataReader
    from elasticdl_tpu.master.rendezvous import MeshRendezvous
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.master.task_monitor import TaskMonitor
    from elasticdl_tpu.proto.services import add_master_servicer_to_server

    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    tmp = tempfile.mkdtemp(prefix="edl_scale%d_" % n)
    reader = RecordIODataReader(data_dir=train_dir)
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=256,
        num_epochs=1,
        seed=0,
    )
    # (timestamp, cumulative records) at every completed train task —
    # the steady-state rate is fit over the back half, excluding the
    # join/restart storm while the world assembles
    progress = []
    done_records = [0]

    progress_lock = threading.Lock()

    def on_task_done(task):
        # completion callbacks run on concurrent gRPC threads outside
        # the dispatcher lock
        if task.type == pb.TRAINING:
            with progress_lock:
                done_records[0] += task.end - task.start
                progress.append((time.time(), done_records[0]))

    dispatcher.add_task_completed_callback(on_task_done)
    rendezvous = MeshRendezvous()
    servicer = MasterServicer(dispatcher, None, rendezvous=rendezvous)
    monitor = TaskMonitor(
        dispatcher, servicer, rendezvous=rendezvous,
        liveness_timeout_secs=30.0, scan_interval_secs=0.5,
        mesh_restart_grace_secs=25.0,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()
    monitor.start()
    coordinator_port = find_free_port()

    procs = {}
    try:
        for i in range(n):
            procs[i] = _spawn_worker(
                i, master_port, coordinator_port, train_dir, tmp, model
            )

        relaunches = [0]

        def supervise():
            """Pod-manager stand-in: workers exit on every mesh-epoch
            bump while the world assembles (the elastic re-init
            contract) and must be relaunched. Capped: a worker that
            crash-loops at startup must surface its error, not spin."""
            for i, proc in list(procs.items()):
                if proc.poll() is not None:
                    relaunches[0] += 1
                    assert relaunches[0] < 12 * n, (
                        "worker restart loop; see logs under %s" % tmp
                    )
                    procs[i] = _spawn_worker(
                        i, master_port, coordinator_port, train_dir,
                        tmp, model,
                    )

        # the steady-state window starts when the full world has joined
        deadline = time.time() + 600
        while time.time() < deadline and len(rendezvous.hosts()) < n:
            supervise()
            time.sleep(0.2)
        assert len(rendezvous.hosts()) == n, (
            "only %d/%d workers joined" % (len(rendezvous.hosts()), n)
        )
        joined = time.time()
        with progress_lock:
            records_at_join = done_records[0]
        while not dispatcher.finished():
            if dispatcher.job_failed():
                raise RuntimeError("world %d job failed" % n)
            if time.time() > deadline:
                raise TimeoutError("world %d never finished" % n)
            supervise()
            time.sleep(0.2)
        window = time.time() - joined
        # steady-state rate: records completed between the halfway mark
        # and the end (the first half absorbs the join/restart storm).
        # Under the lock: the final task's completion callback may still
        # be appending on a gRPC thread after finished() flips.
        half = records // 2
        with progress_lock:
            steady = [(t, c) for t, c in progress if c >= half]
        if len(steady) >= 2:
            (t0, c0), (t1, c1) = steady[0], steady[-1]
            steady_rate = (c1 - c0) / max(t1 - t0, 1e-6)
        else:
            steady_rate = records / window
        return {
            "workers": n,
            "examples_per_sec_steady": round(steady_rate, 1),
            "examples_per_sec_incl_join": round(
                (records - records_at_join) / window, 1
            ),
            "window_s": round(window, 1),
        }
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
        monitor.stop()
        server.stop(0)


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--worlds", default="1,2,4")
    parser.add_argument("--records", type=int, default=8192)
    parser.add_argument(
        "--model", default="elasticdl_tpu.models.mnist"
    )
    args = parser.parse_args()

    from elasticdl_tpu.data.gen.converters import gen_mnist_recordio

    tmp = tempfile.mkdtemp(prefix="edl_scale_data_")
    train_dir = os.path.join(tmp, "train")
    gen_mnist_recordio(train_dir, num_records=args.records)

    rows = []
    for n in [int(w) for w in args.worlds.split(",")]:
        rows.append(run_world(n, train_dir, args.records, args.model))
        print("[world %d] %s" % (n, rows[-1]), flush=True)
    base_rows = [r for r in rows if r["workers"] == 1]
    if base_rows:
        base = base_rows[0]["examples_per_sec_steady"]
        for row in rows:
            row["scaling_vs_1_worker"] = round(
                row["examples_per_sec_steady"] / base, 2
            )
    print(json.dumps({
        "model": args.model,
        "note": "all workers share one machine's cores; framework-"
                "overhead scaling, not hardware scaling",
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
