"""Shared per-HLO-category breakdown of a jax.profiler trace.

Used by scripts/profile_resnet.py and scripts/bench_transformer_mfu.py
(the evidence generators behind docs/PERF_RESNET.md and
docs/PERF_TRANSFORMER.md).
"""

import collections
import glob
import gzip
import json


def latest_trace_path(trace_dir):
    return sorted(
        glob.glob(trace_dir + "/plugins/profile/*/*.trace.json.gz")
    )[-1]


def capture_trace(run_once, trace_dir, steps):
    """Profile one invocation of ``run_once`` (which must fence device
    execution itself, e.g. by fetching a scalar loss) and print the
    per-HLO-category summary. The single capture protocol shared by the
    bench scripts."""
    import jax

    jax.profiler.start_trace(trace_dir)
    run_once()
    jax.profiler.stop_trace()
    return summarize_trace(trace_dir, steps)


def summarize_trace(trace_dir, steps, top=14):
    """Print device time / bytes / bandwidth / flops by HLO category for
    the newest trace under ``trace_dir``; returns the trace path."""
    path = latest_trace_path(trace_dir)
    with gzip.open(path) as f:
        data = json.load(f)
    tpu_pid = None
    for e in data["traceEvents"]:
        if e.get("ph") == "M" and e.get("name") == "process_name" \
                and "TPU" in str(e.get("args", {}).get("name", "")):
            tpu_pid = e["pid"]
    ops = [
        e for e in data["traceEvents"]
        if e.get("ph") == "X" and e.get("pid") == tpu_pid
        and "hlo_category" in e.get("args", {})
        and not e["name"].startswith("while")
    ]
    total = sum(e["dur"] for e in ops)
    cat = collections.Counter()
    catb = collections.Counter()
    catf = collections.Counter()
    for e in ops:
        c = e["args"]["hlo_category"]
        cat[c] += e["dur"]
        catb[c] += int(e["args"].get("bytes_accessed", 0))
        catf[c] += int(float(e["args"].get("flops", 0)))
    print(
        "device time: %.1f ms / %d steps; bytes %.1f GB/step"
        % (total / 1e3, steps, sum(catb.values()) / steps / 1e9)
    )
    for c, dur in cat.most_common(top):
        bw = catb[c] / (dur / 1e6) / 1e9 if dur else 0
        tf = catf[c] / (dur / 1e6) / 1e12 if dur else 0
        print(
            "%5.1f%%  %8.1fms  bw=%6.0f GB/s  %6.1f TFLOP/s  %s"
            % (dur / total * 100, dur / 1e3, bw, tf, c)
        )
    print("trace at:", path)
    return path
