"""Trace summaries: per-HLO-category (jax.profiler) and per-trace (EDL).

Two halves:

- the original per-HLO-category breakdown of a ``jax.profiler``
  capture, used by scripts/profile_resnet.py and
  scripts/bench_transformer_mfu.py (the evidence generators behind
  docs/PERF_RESNET.md and docs/PERF_TRANSFORMER.md);
- ISSUE 9: a summary of an ``EDL_TRACE_DIR`` capture grouped by the
  propagated ``trace_id`` — per-span-name stats (count / p50 / p99)
  plus a per-trace duration table with the slowest-N traces, each
  with its span count and participating roles. Runnable directly:

      python scripts/trace_summary.py TRACE_DIR [--slowest N]
"""

import argparse
import collections
import glob
import gzip
import json
import os
import sys


def latest_trace_path(trace_dir):
    return sorted(
        glob.glob(trace_dir + "/plugins/profile/*/*.trace.json.gz")
    )[-1]


def capture_trace(run_once, trace_dir, steps):
    """Profile one invocation of ``run_once`` (which must fence device
    execution itself, e.g. by fetching a scalar loss) and print the
    per-HLO-category summary. The single capture protocol shared by the
    bench scripts."""
    import jax

    jax.profiler.start_trace(trace_dir)
    run_once()
    jax.profiler.stop_trace()
    return summarize_trace(trace_dir, steps)


def summarize_trace(trace_dir, steps, top=14):
    """Print device time / bytes / bandwidth / flops by HLO category for
    the newest trace under ``trace_dir``; returns the trace path."""
    path = latest_trace_path(trace_dir)
    with gzip.open(path) as f:
        data = json.load(f)
    tpu_pid = None
    for e in data["traceEvents"]:
        if e.get("ph") == "M" and e.get("name") == "process_name" \
                and "TPU" in str(e.get("args", {}).get("name", "")):
            tpu_pid = e["pid"]
    ops = [
        e for e in data["traceEvents"]
        if e.get("ph") == "X" and e.get("pid") == tpu_pid
        and "hlo_category" in e.get("args", {})
        and not e["name"].startswith("while")
    ]
    total = sum(e["dur"] for e in ops)
    cat = collections.Counter()
    catb = collections.Counter()
    catf = collections.Counter()
    for e in ops:
        c = e["args"]["hlo_category"]
        cat[c] += e["dur"]
        catb[c] += int(e["args"].get("bytes_accessed", 0))
        catf[c] += int(float(e["args"].get("flops", 0)))
    print(
        "device time: %.1f ms / %d steps; bytes %.1f GB/step"
        % (total / 1e3, steps, sum(catb.values()) / steps / 1e9)
    )
    for c, dur in cat.most_common(top):
        bw = catb[c] / (dur / 1e6) / 1e9 if dur else 0
        tf = catf[c] / (dur / 1e6) / 1e12 if dur else 0
        print(
            "%5.1f%%  %8.1fms  bw=%6.0f GB/s  %6.1f TFLOP/s  %s"
            % (dur / total * 100, dur / 1e3, bw, tf, c)
        )
    print("trace at:", path)
    return path


# ---------------------------------------------------------------------------
# EDL distributed-trace summary (ISSUE 9)


def _merge_trace():
    """The sibling merge_trace module, importable whether this module
    was loaded as ``scripts.trace_summary`` or bare ``trace_summary``;
    it owns the shared capture helpers (load_events/percentile/...)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import merge_trace
    finally:
        sys.path.pop(0)
    return merge_trace


def summarize_edl_traces(trace_path, slowest=10):
    """Summary dict for an EDL trace dir (or merged file): per-name
    span stats over EVERY complete span, plus per-trace records for
    spans carrying the propagated trace context, slowest first."""
    mt = _merge_trace()
    events = mt.load_events(str(trace_path))
    roles_of_pids = mt.role_by_pid(events)
    spans = [e for e in events if e.get("ph") == "X"]
    by_name = collections.defaultdict(list)
    by_trace = collections.defaultdict(list)
    for event in spans:
        by_name[event["name"]].append(event.get("dur", 0.0) / 1e3)
        trace_id = (event.get("args") or {}).get("trace_id")
        if trace_id:
            by_trace[trace_id].append(event)
    names = {
        name: {
            "count": len(durs),
            "p50_ms": round(mt.percentile(durs, 0.50), 3),
            "p99_ms": round(mt.percentile(durs, 0.99), 3),
            "total_ms": round(sum(durs), 3),
        }
        for name, durs in by_name.items()
    }
    traces = []
    for trace_id, trace_spans in by_trace.items():
        trace_spans.sort(key=lambda e: e["ts"])
        root = next(
            (e for e in trace_spans if "parent_id" not in e["args"]),
            trace_spans[0],
        )
        roles = set()
        for event in trace_spans:
            role = event["args"].get("role") or roles_of_pids.get(
                event.get("pid"), ""
            )
            if role:
                roles.add(mt.normalize_role(role))
        traces.append({
            "trace_id": trace_id,
            "root": root["name"],
            "duration_ms": round(root.get("dur", 0.0) / 1e3, 3),
            "spans": len(trace_spans),
            "roles": sorted(roles),
        })
    traces.sort(key=lambda t: -t["duration_ms"])
    return {
        "spans": len(spans),
        "names": names,
        "traces": len(traces),
        "slowest": traces[:slowest],
    }


def print_edl_summary(summary):
    print("%d span(s), %d trace(s)" % (summary["spans"],
                                       summary["traces"]))
    print("per-name stats:")
    for name, stats in sorted(
        summary["names"].items(), key=lambda kv: -kv[1]["total_ms"]
    ):
        print(
            "  %-28s n=%-6d p50=%8.3fms  p99=%8.3fms  total=%10.3fms"
            % (name, stats["count"], stats["p50_ms"], stats["p99_ms"],
               stats["total_ms"])
        )
    if summary["slowest"]:
        print("slowest traces:")
        for t in summary["slowest"]:
            print(
                "  %s  %-14s %10.3fms  %2d span(s)  %s"
                % (t["trace_id"][:16], t["root"], t["duration_ms"],
                   t["spans"], ",".join(t["roles"]))
            )


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Summarize an EDL_TRACE_DIR capture by span name "
        "and by propagated trace_id",
    )
    parser.add_argument(
        "trace_path", help="EDL_TRACE_DIR or a merged.trace.json"
    )
    parser.add_argument("--slowest", type=int, default=10)
    args = parser.parse_args(argv)
    print_edl_summary(
        summarize_edl_traces(args.trace_path, slowest=args.slowest)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
