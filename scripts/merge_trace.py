#!/usr/bin/env python
"""Merge per-role EDL trace files into one Perfetto-loadable timeline.

Each role (master / worker-N / ps-N / serve-N) buffers Chrome trace
events to ``$EDL_TRACE_DIR/<role>-<pid>.trace.json``
(elasticdl_tpu/observability/trace.py). Timestamps are already
wall-clock microseconds, so merging is concatenation — plus flow
events that make the cross-role hops visible arrows in Perfetto
(https://ui.perfetto.dev) or chrome://tracing.

Flows thread by the PROPAGATED trace context first (ISSUE 9): spans
carrying ``trace_id``/``span_id``/``parent_id`` args — one worker step
or one serve predict request spanning worker → PS / client → serve →
PS — are grouped exactly, parent to child, no heuristics. Spans
WITHOUT a trace context (older trace files, standalone spans) fall
back to the PR-2 ``task_id`` correlation so old captures keep merging.

Usage:
    python scripts/merge_trace.py TRACE_DIR [-o merged.trace.json]
"""

import argparse
import json
import os
import re
import sys


def _parse_events(text):
    """Events from either trace shape: the object form
    {"traceEvents": [...]} (e.g. a re-merged file) or the JSON Array
    Format the role writers append — "[" + one event per line with
    trailing commas, closing "]" optional per the trace-event spec (a
    torn final line from a crashed process is skipped)."""
    try:
        data = json.loads(text)
    except ValueError:
        data = None
    if isinstance(data, dict):
        return data.get("traceEvents", [])
    if isinstance(data, list):
        return data
    events = []
    body = text.lstrip()
    if body.startswith("["):
        body = body[1:]
    for line in body.splitlines():
        line = line.strip().rstrip(",")
        if not line or line == "]":
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            continue  # torn tail write from a crashed role
    return events


def load_role_files(trace_dir):
    """[(filename, [events])] for every *.trace.json in the dir."""
    names = sorted(
        n for n in os.listdir(trace_dir)
        if n.endswith(".trace.json") and not n.startswith("merged")
    )
    loaded = []
    for name in names:
        path = os.path.join(trace_dir, name)
        try:
            with open(path, "r", encoding="utf-8") as f:
                events = _parse_events(f.read())
        except OSError as e:
            print("skipping %s: %s" % (path, e), file=sys.stderr)
            continue
        loaded.append((name, events))
    return loaded


# shared helpers for the consumers sitting on top of a capture
# (trace_summary.py, critical_path.py) — one definition, one behavior


def load_events(path):
    """Events from a trace DIR (merged in-memory) or a merged file."""
    if os.path.isdir(path):
        merged, _names = merge(path)
        return merged["traceEvents"]
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return data.get("traceEvents", [])
    return data


def role_by_pid(events):
    """pid -> role name, from the process_name metadata events."""
    return {
        e["pid"]: (e.get("args") or {}).get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }


def normalize_role(role):
    # "worker-3" -> "worker", "ps-0" -> "ps", "serve-1" -> "serve"
    return re.sub(r"-\d+$", "", str(role))


def percentile(values, q):
    """Nearest-rank percentile; None on an empty list."""
    if not values:
        return None
    ordered = sorted(values)
    index = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[index]


def context_flow_events(events):
    """Flow (s/t/f) events threading every span of one TRACE (same
    propagated ``trace_id``) across processes, in timestamp order —
    the exact grouping the span context carried over gRPC metadata."""
    by_trace = {}
    for event in events:
        if event.get("ph") != "X":
            continue
        trace_id = (event.get("args") or {}).get("trace_id")
        if not trace_id:
            continue
        by_trace.setdefault(trace_id, []).append(event)
    flows = []
    for trace_id, spans in sorted(by_trace.items()):
        if len(spans) < 2:
            continue
        spans.sort(key=lambda e: e["ts"])
        for i, event in enumerate(spans):
            phase = "s" if i == 0 else ("f" if i == len(spans) - 1 else "t")
            flow = {
                "name": "trace",
                "cat": "trace",
                "ph": phase,
                "id": trace_id[:16],
                "ts": event["ts"],
                "pid": event["pid"],
                "tid": event["tid"],
            }
            if phase == "f":
                flow["bp"] = "e"  # bind to the enclosing slice
            flows.append(flow)
    return flows


def task_flow_events(events):
    """Flow (s/t/f) events connecting same-task_id spans across
    processes, in timestamp order. Task groups whose EVERY span also
    carries a trace context are skipped (context_flow_events already
    threads them exactly); mixed groups still thread fully — the
    master's ``dispatch`` span has a task_id but no trace context (the
    worker's get_task poll runs outside any root span), and dropping
    the worker's context-carrying train/push spans from its group
    would orphan the dispatch arrow the PR-2 timeline promises."""
    by_task = {}
    for event in events:
        if event.get("ph") != "X":
            continue
        args = event.get("args") or {}
        task_id = args.get("task_id")
        if task_id in (None, ""):
            continue
        by_task.setdefault(task_id, []).append(event)
    by_task = {
        task_id: spans
        for task_id, spans in by_task.items()
        if any(
            not (e.get("args") or {}).get("trace_id") for e in spans
        )
    }
    flows = []
    for task_id, spans in sorted(by_task.items(), key=lambda kv: str(kv[0])):
        if len(spans) < 2:
            continue
        spans.sort(key=lambda e: e["ts"])
        for i, event in enumerate(spans):
            phase = "s" if i == 0 else ("f" if i == len(spans) - 1 else "t")
            flow = {
                "name": "task",
                "cat": "task",
                "ph": phase,
                "id": str(task_id),
                "ts": event["ts"],
                "pid": event["pid"],
                "tid": event["tid"],
            }
            if phase == "f":
                flow["bp"] = "e"  # bind to the enclosing slice
            flows.append(flow)
    return flows


def merge(trace_dir):
    role_files = load_role_files(trace_dir)
    if not role_files:
        raise SystemExit("no *.trace.json files in %s" % trace_dir)
    events = []
    for _name, role_events in role_files:
        events.extend(role_events)
    events.extend(context_flow_events(events))
    events.extend(task_flow_events(events))
    # stable display: metadata first, then time order
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {"traceEvents": events}, [name for name, _ in role_files]


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("trace_dir", help="EDL_TRACE_DIR of the run")
    parser.add_argument(
        "-o", "--output", default="",
        help="output path (default: TRACE_DIR/merged.trace.json)",
    )
    args = parser.parse_args(argv)
    merged, names = merge(args.trace_dir)
    out = args.output or os.path.join(args.trace_dir, "merged.trace.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    print(
        "merged %d role file(s) (%s) -> %s [%d events]"
        % (len(names), ", ".join(names), out, len(merged["traceEvents"]))
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
