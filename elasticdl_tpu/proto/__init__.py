from elasticdl_tpu.proto import elasticdl_tpu_pb2  # noqa: F401
