"""gRPC service bindings for the Master and Pserver services.

The reference generates these with the protoc gRPC plugin
(elasticdl/proto/elasticdl.proto:108-157); this environment has no
`grpc_tools`, so the stubs/servicers are written by hand against grpc's
generic-handler API. The wire format is identical to what generated code
would produce (unary-unary methods, protobuf (de)serializers), so clients
and servers here interoperate with any standard gRPC toolchain.
"""

import grpc

from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

_MASTER_SERVICE = "elasticdl_tpu.Master"
_PSERVER_SERVICE = "elasticdl_tpu.Pserver"
_SERVE_SERVICE = "elasticdl_tpu.Serve"
_ROUTER_SERVICE = "elasticdl_tpu.Router"

# method name -> (request class, response class)
_MASTER_METHODS = {
    "get_task": (pb.GetTaskRequest, pb.Task),
    "report_task_result": (pb.ReportTaskResultRequest, pb.Empty),
    "report_evaluation_metrics": (pb.ReportEvaluationMetricsRequest, pb.Empty),
    "report_version": (pb.ReportVersionRequest, pb.Empty),
    "get_comm_info": (pb.GetCommInfoRequest, pb.CommInfo),
    # fresh-incarnation declaration: requeue everything still assigned
    # to this worker_id (a relaunched worker reuses its id, so stale
    # assignments from a fatally-aborted predecessor would otherwise
    # look live until the slow task timeout). Returns the
    # master-assigned relaunch epoch the worker uses as its push
    # incarnation (logical, monotonic per worker_id — wall clocks on
    # relaunch hosts are not trusted to order incarnations).
    "reset_worker": (pb.GetTaskRequest, pb.ResetWorkerResponse),
    # graceful-drain ack (ISSUE 7): a scale-down victim / preempted
    # worker that finished draining (task reported, async push joined,
    # device-tier rows flushed) deregisters so the master removes it
    # cleanly — no dead_air alert, no requeue-on-death fallback. Old
    # masters answer UNIMPLEMENTED; the worker exits anyway and the
    # liveness path covers the cleanup.
    "deregister_worker": (pb.DeregisterWorkerRequest, pb.Empty),
}

_PSERVER_METHODS = {
    "push_model": (pb.Model, pb.Empty),
    "push_embedding_table_infos": (pb.Model, pb.Empty),
    "pull_dense_parameters": (
        pb.PullDenseParametersRequest,
        pb.PullDenseParametersResponse,
    ),
    "pull_embedding_vectors": (pb.PullEmbeddingVectorsRequest, pb.TensorBlob),
    # fused multi-table pull: every table's ids for this shard ride one
    # RPC (ids-only IndexedSlicesProto in, per-table row blobs out) —
    # a step costs ps_num pull RPCs instead of tables x ps_num
    "pull_embedding_batch": (pb.BatchedSlices, pb.PullEmbeddingBatchResponse),
    "push_gradients": (pb.PushGradientsRequest, pb.PushGradientsResponse),
    # device-tier writeback (ISSUE 6): raw row VALUES overwriting the
    # store (eviction/flush of the HBM hot set), not gradients — no
    # optimizer math, no version bump. Reuses the Model message
    # (embedding_tables: IndexedSlicesProto carries values + ids).
    "push_embedding_rows": (pb.Model, pb.PushGradientsResponse),
}

# Online serving tier (ISSUE 8): a serve role loads an exported model
# and answers Predict over the same wire stack. predict rides the
# admission-controlled micro-batcher (RESOURCE_EXHAUSTED when the
# bounded queue sheds, DEADLINE_EXCEEDED when a request's budget
# expires while queued); model_info answers the loaded artifact's
# identity (the hot-swap contract's observable).
_SERVE_METHODS = {
    "predict": (pb.PredictRequest, pb.PredictResponse),
    "model_info": (pb.Empty, pb.ModelInfoResponse),
}

# Serving-fleet router (ISSUE 17): the router also serves the full
# Serve surface (clients point --serving_addr at it unchanged); this
# service is the replica-facing control plane. register announces a
# replica (addr + capacity + loaded stamp), heartbeat carries the
# replica's telemetry and returns directives (drain, target export
# version for canary/promote), deregister is the exactly-once drain
# ack reused from the ISSUE 7/8 scale-down path.
_ROUTER_METHODS = {
    "register_replica": (pb.RegisterReplicaRequest, pb.RegisterReplicaResponse),
    "heartbeat_replica": (pb.ReplicaHeartbeatRequest, pb.ReplicaHeartbeatResponse),
    "deregister_replica": (pb.DeregisterReplicaRequest, pb.Empty),
}


class _Stub:
    """Builds unary-unary callables for each method of a service."""

    def __init__(self, channel, service_name, methods):
        for name, (req_cls, resp_cls) in methods.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    "/%s/%s" % (service_name, name),
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )


class MasterStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, _MASTER_SERVICE, _MASTER_METHODS)


class PserverStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, _PSERVER_SERVICE, _PSERVER_METHODS)


class ServeStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, _SERVE_SERVICE, _SERVE_METHODS)


class RouterStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, _ROUTER_SERVICE, _ROUTER_METHODS)


def _add_service(server, servicer, service_name, methods):
    handlers = {}
    for name, (req_cls, resp_cls) in methods.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )


def add_master_servicer_to_server(servicer, server):
    _add_service(server, servicer, _MASTER_SERVICE, _MASTER_METHODS)


def add_pserver_servicer_to_server(servicer, server):
    _add_service(server, servicer, _PSERVER_SERVICE, _PSERVER_METHODS)


def add_serve_servicer_to_server(servicer, server):
    _add_service(server, servicer, _SERVE_SERVICE, _SERVE_METHODS)


def add_router_servicer_to_server(servicer, server):
    _add_service(server, servicer, _ROUTER_SERVICE, _ROUTER_METHODS)
