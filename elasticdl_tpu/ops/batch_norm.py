"""TPU-first BatchNorm.

Profiling the ResNet50 train step on a v5e chip (docs/PERF_RESNET.md)
showed the step is HBM-bandwidth-bound and that flax's ``nn.BatchNorm``
costs an extra ~8% of step time: its mean/variance are computed as two
dependent passes (``mean`` then ``mean((x - mean)**2)``), which XLA
cannot fuse into one read of the activation, and its normalize applies
``(x - mean) * inv * scale + bias`` as several elementwise ops.

``TpuBatchNorm`` keeps the exact same semantics (biased variance, f32
statistics, running-average update) but is shaped for the compiler:

- single-pass statistics: ``E[x]`` and ``E[x^2]`` reduce the input in
  one read (XLA fuses both reductions into the producing convolution's
  epilogue — the profile shows them as ``multiply_reduce_fusion``);
- the normalize folds to one fused multiply-add in the compute dtype:
  ``x * mul + add`` with ``mul = scale * rsqrt(var + eps)`` and
  ``add = bias - mean * mul`` precomputed on the tiny per-channel
  vectors in f32.

``stats_samples=k`` optionally computes the statistics over only the
first ``k`` batch rows (ghost-BN-style subsampling; all rows are still
normalized). This trades exactness of the batch statistics for one
fewer full read of the activation in the stats pass — measured ~3% of
ResNet50 step time at k=batch/8 — and is off (0 = full batch) by
default everywhere.

Reference parity: the reference normalizes with stock Keras
BatchNormalization inside its zoo models (e.g.
model_zoo/cifar10_functional_api/cifar10_functional_api.py); this is
the TPU-native equivalent layer.
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp
import flax.linen as nn


class BatchNorm(nn.Module):
    """Drop-in for the ``nn.BatchNorm`` surface used in this repo.

    Named ``BatchNorm`` so flax auto-naming keeps the same param-tree
    keys (``.../BatchNorm_0/scale``) as the stock layer — checkpoints
    taken before the swap keep restoring. Import as ``TpuBatchNorm``.

    ``dtype`` is accepted for signature compatibility; statistics are
    always computed in float32 and the output is produced in the input's
    dtype (matching ``nn.BatchNorm(dtype=None)`` with flax's
    force_float32_reductions).
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros
    stats_samples: int = 0

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        scale = self.param("scale", self.scale_init, (features,), jnp.float32)
        bias = self.param("bias", self.bias_init, (features,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((features,), jnp.float32),
        )
        ra_var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((features,), jnp.float32),
        )
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xs = x[: self.stats_samples] if self.stats_samples else x
            xf = xs.astype(jnp.float32)
            axes = tuple(range(xs.ndim - 1))
            mean = jnp.mean(xf, axis=axes)
            # Biased variance via E[x^2] - E[x]^2 (flax/Keras use the
            # biased estimator too). The subtraction can go slightly
            # negative in f32 for near-constant channels; clamp.
            var = jnp.maximum(
                jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean),
                0.0,
            )
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        inv = jax.lax.rsqrt(var + self.epsilon) * scale
        mul = inv.astype(x.dtype)
        add = (bias - mean * inv).astype(x.dtype)
        return x * mul + add


TpuBatchNorm = BatchNorm
