"""Flash attention as Pallas TPU kernels (forward + backward).

Blockwise online-softmax attention: O(S) memory instead of the O(S^2)
score matrix, scores kept in VMEM, matmuls on the MXU. This is the
single-chip building block; sequence parallelism composes it with ring /
all-to-all collectives (ops/ring_attention.py).

No reference counterpart — the reference's models are CTR/vision Keras
nets with no attention anywhere (SURVEY.md §5 "long-context: absent");
this is a new TPU-first capability.

Layouts: (batch, heads, seq, head_dim) — "BHSD", kernels flatten
batch*heads into one parallel grid axis — or "bshd"
(batch, seq, heads, head_dim), where the kernels address each head as
a lane-aligned d-wide block of the fused (heads*head_dim) minor dim so
callers skip the BHSD transposes (``flash_attention(layout=...)``;
measured net-negative for the stock TransformerLM on v5e but available
for shapes where it wins — docs/PERF_TRANSFORMER.md §6).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticdl_tpu.common import jax_compat

NEG_INF = -1e30
# Lane width of the m/l scratch rows (min f32 tile is (8, 128)).
_STATS_LANES = 128
# checkpoint_name labels on the forward kernel's outputs; remat policies
# reference these (e.g. models/transformer.py) to save o/lse instead of
# re-running the forward flash pass in backward.
FLASH_OUT_NAME = "flash_out"
FLASH_LSE_NAME = "flash_lse"


def _auto_block(seq, cap):
    """Largest power-of-two block <= cap that divides seq (>= 128 when
    possible so blocks stay MXU-tile aligned)."""
    block = cap
    while block > 128 and seq % block:
        block //= 2
    return block if seq % block == 0 else min(seq, 128)


def _causal_mask(s, q_block, k_block, block_q, block_k):
    q_pos = q_block * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 0
    )
    k_pos = k_block * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1
    )
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    acc_ref,
    m_ref,
    l_ref,
    *,
    sm_scale,
    causal,
    block_q,
    block_k,
):
    q_block = pl.program_id(1)
    k_block = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(k_block == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Causal: blocks strictly above the diagonal contribute nothing.
    diag_ok = (
        (q_block + 1) * block_q - 1 >= k_block * block_k
        if causal
        else True
    )

    @pl.when(diag_ok)
    def _compute():
        # Matmuls run on inputs in their NATIVE dtype with f32 MXU
        # accumulation: for bf16 inputs bf16xbf16->f32 is bit-identical
        # to upcasting first (bf16 products are exact in f32), while an
        # f32xf32 matmul the MXU must emulate in multiple passes runs
        # ~4-6x slower — this was 19% of transformer step time
        # (docs/PERF_TRANSFORMER.md). Softmax statistics stay in f32.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = (
            jax.lax.dot_general(
                q,
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )
        if causal:
            s = _causal_mask(s, q_block, k_block, block_q, block_k)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        correction = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(k_block == num_k - 1)
    def _finalize():
        l_final = l_ref[:, :1]
        # Fully-masked rows (can't happen causally, but keep the kernel
        # total): emit zeros, lse = -inf.
        safe_l = jnp.where(l_final > 0.0, l_final, 1.0)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = (
            m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))
        )


def _q_specs(heads):
    """(q-ish spec, k-ish spec, lse-ish spec) index maps for the two
    kernel views.

    - ``heads is None``: the merged "(bh, seq, d)" view — batch*heads
      flattened into grid axis 0, arrays carry one head each.
    - ``heads = H``: the fused-BSHD "(B, seq, H*d)" view — grid axis 0
      is still B*H, but the head selects a d-wide block of the fused
      minor dim instead of a row of a transposed array. This is what
      lets the model skip the BHSD transposes entirely: the kernel sees
      the exact (block, d) tiles either way (d is a lane multiple), so
      the bodies are shared.
    """
    if heads is None:
        q_idx = lambda b, i, j: (b, i, 0)
        k_idx = lambda b, i, j: (b, j, 0)
        stat_idx = lambda b, i, j: (b, 0, i)
    else:
        q_idx = lambda g, i, j: (g // heads, i, g % heads)
        k_idx = lambda g, i, j: (g // heads, j, g % heads)
        stat_idx = lambda g, i, j: (g, 0, i)
    return q_idx, k_idx, stat_idx


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
         heads=None):
    if heads is None:
        bh, seq_q, head_dim = q.shape
        seq_k = k.shape[1]
    else:
        batch, seq_q, fused = q.shape
        head_dim = fused // heads
        seq_k = k.shape[1]
        bh = batch * heads
    num_q = seq_q // block_q
    num_k = seq_k // block_k
    grid = (bh, num_q, num_k)

    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    q_idx, k_idx, stat_idx = _q_specs(heads)
    # lse rides in (bh, 1, seq) — the singleton axis makes the block's
    # second-minor dim equal the full array dim, satisfying the TPU
    # (8, 128) tiling rule that a 2-D (1, block_q) block violates
    out_shape = (
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((bh, 1, seq_q), jnp.float32),
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), q_idx),
            pl.BlockSpec((1, block_k, head_dim), k_idx),
            pl.BlockSpec((1, block_k, head_dim), k_idx),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, head_dim), q_idx),
            pl.BlockSpec((1, 1, block_q), stat_idx),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
        ],
        out_shape=out_shape,
        compiler_params=jax_compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dq_ref,
    dq_acc_ref,
    *,
    sm_scale,
    causal,
    block_q,
    block_k,
):
    q_block = pl.program_id(1)
    k_block = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(k_block == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    diag_ok = (
        (q_block + 1) * block_q - 1 >= k_block * block_k
        if causal
        else True
    )

    @pl.when(diag_ok)
    def _compute():
        # Native-dtype matmul inputs, f32 accumulation (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = (
            jax.lax.dot_general(
                q,
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )
        if causal:
            s = _causal_mask(s, q_block, k_block, block_q, block_k)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do,
            v,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_acc_ref[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(k_block == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dk_ref,
    dv_ref,
    dk_acc_ref,
    dv_acc_ref,
    *,
    sm_scale,
    causal,
    block_q,
    block_k,
):
    k_block = pl.program_id(1)
    q_block = pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(q_block == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    diag_ok = (
        (q_block + 1) * block_q - 1 >= k_block * block_k
        if causal
        else True
    )

    @pl.when(diag_ok)
    def _compute():
        # Native-dtype matmul inputs, f32 accumulation (see _fwd_kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = (
            jax.lax.dot_general(
                q,
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )
        if causal:
            s = _causal_mask(s, q_block, k_block, block_q, block_k)
        p = jnp.exp(s - lse)
        dv_acc_ref[:] += jax.lax.dot_general(
            p.astype(do.dtype),
            do,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do,
            v,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_acc_ref[:] += jax.lax.dot_general(
            ds,
            q,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(q_block == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _bwd(
    q, k, v, o, lse, do, sm_scale, causal, block_q, block_k, interpret,
    heads=None,
):
    if heads is None:
        bh, seq_q, head_dim = q.shape
        seq_k = k.shape[1]
        delta = jnp.sum(
            o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1
        )[:, None, :]  # (bh, 1, seq): same tiling-friendly layout as lse
    else:
        batch, seq_q, fused = q.shape
        head_dim = fused // heads
        seq_k = k.shape[1]
        bh = batch * heads
        # per-head dot(o, do): (B, S, H) -> (B*H, 1, S)
        delta = jnp.sum(
            o.astype(jnp.float32).reshape(batch, seq_q, heads, head_dim)
            * do.astype(jnp.float32).reshape(
                batch, seq_q, heads, head_dim
            ),
            axis=-1,
        ).transpose(0, 2, 1).reshape(bh, 1, seq_q)
    num_q = seq_q // block_q
    num_k = seq_k // block_k
    q_idx, k_idx, stat_idx = _q_specs(heads)

    def swapped(idx):
        # the dkv grid iterates (bh, k-block, q-block)
        return lambda b, j, i: idx(b, i, j)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            sm_scale=sm_scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
        ),
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), q_idx),
            pl.BlockSpec((1, block_k, head_dim), k_idx),
            pl.BlockSpec((1, block_k, head_dim), k_idx),
            pl.BlockSpec((1, block_q, head_dim), q_idx),
            pl.BlockSpec((1, 1, block_q), stat_idx),
            pl.BlockSpec((1, 1, block_q), stat_idx),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), q_idx),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=jax_compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            sm_scale=sm_scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
        ),
        grid=(bh, num_k, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), swapped(q_idx)),
            pl.BlockSpec((1, block_k, head_dim), swapped(k_idx)),
            pl.BlockSpec((1, block_k, head_dim), swapped(k_idx)),
            pl.BlockSpec((1, block_q, head_dim), swapped(q_idx)),
            pl.BlockSpec((1, 1, block_q), swapped(stat_idx)),
            pl.BlockSpec((1, 1, block_q), swapped(stat_idx)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, head_dim), swapped(k_idx)),
            pl.BlockSpec((1, block_k, head_dim), swapped(k_idx)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ),
        compiler_params=jax_compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------
#
# The gradient is attached by an identity-primal custom_vjp ``_attach``
# over explicit (o, lse) values rather than by wrapping the forward
# kernel itself. Rationale: if the forward pallas_call lives inside the
# custom_vjp, its lse output exists only as a hidden residual, so a
# rematerialization policy (jax.checkpoint) can never mark it saveable —
# every rematted transformer block then pays a SECOND forward flash pass
# during backward (~5% of train-step time at S=2k, docs/
# PERF_TRANSFORMER.md). Here (o, lse) are ordinary named primal values
# (checkpoint_name "flash_out"/"flash_lse"): a policy that saves them
# lets remat DCE the forward kernel in the backward re-trace, while
# ``_attach``'s own primal is a free identity.


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _attach(q, k, v, o, lse, sm_scale, causal, block_q, block_k,
            interpret, heads):
    return o


def _attach_fwd(q, k, v, o, lse, sm_scale, causal, block_q, block_k,
                interpret, heads):
    return o, (q, k, v, o, lse)


def _attach_bwd(sm_scale, causal, block_q, block_k, interpret, heads,
                res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd(
        q, k, v, o, lse, do, sm_scale, causal, block_q, block_k,
        interpret, heads,
    )
    # o/lse arrive behind stop_gradient; their cotangents are discarded.
    return dq, dk, dv, jnp.zeros_like(o), jnp.zeros_like(lse)


_attach.defvjp(_attach_fwd, _attach_bwd)


def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret,
           heads=None):
    # stop_gradient on the kernel inputs keeps AD linearization out of
    # the forward pallas_call (it has no JVP rule and needs none — all
    # gradients flow through _attach's bwd kernels).
    o, lse = _fwd(
        jax.lax.stop_gradient(q),
        jax.lax.stop_gradient(k),
        jax.lax.stop_gradient(v),
        sm_scale,
        causal,
        block_q,
        block_k,
        interpret,
        heads,
    )
    o = checkpoint_name(o, FLASH_OUT_NAME)
    lse = checkpoint_name(lse, FLASH_LSE_NAME)
    return _attach(
        q,
        k,
        v,
        o,
        lse,
        sm_scale,
        causal,
        block_q,
        block_k,
        interpret,
        heads,
    )


def flash_attention(
    q,
    k,
    v,
    causal=False,
    sm_scale=None,
    block_q=None,
    block_k=None,
    interpret=False,
    layout="bhsd",
):
    """Blockwise attention.

    layout selects the input/output convention:
    - "bhsd" (default): (batch, heads, seq, head_dim).
    - "bshd": (batch, seq, heads, head_dim) — the layout qkv
      projections naturally produce. The kernel addresses each head as
      a d-wide block of the fused trailing (heads*head_dim) dim, so NO
      transpose is ever materialized; measured ~3% of transformer step
      time on v5e was BHSD<->BSHD "data formatting"
      (docs/PERF_TRANSFORMER.md). Requires head_dim to be a multiple of
      128 lanes (the auto dispatcher checks).

    Sequence lengths must be multiples of the block sizes (the auto
    dispatcher in ops/attention.py falls back to the XLA impl when they
    are not); head_dim should be a multiple of 128 lanes for best MXU
    utilisation but any size compiles in the "bhsd" layout.

    block_q/block_k default to the largest power-of-two blocks (up to
    512/1024) dividing the sequence: measured on v5e at S=16k, (512,
    1024) runs 4.6x faster than (128, 128) — bigger k-blocks amortize
    the online-softmax rescale and keep the MXU fed.
    """
    if q.ndim != 4:
        raise ValueError("expected 4-D q/k/v")
    if layout == "bhsd":
        batch, heads, seq_q, head_dim = q.shape
        seq_k = k.shape[2]
    elif layout == "bshd":
        batch, seq_q, heads, head_dim = q.shape
        seq_k = k.shape[1]
        if head_dim % 128:
            raise ValueError(
                "layout='bshd' needs head_dim %% 128 == 0 (got %d): "
                "the head is addressed as a lane-aligned block of the "
                "fused minor dim" % head_dim
            )
    else:
        raise ValueError("layout must be 'bhsd' or 'bshd'")
    if block_q is None:
        block_q = _auto_block(seq_q, 512)
    if block_k is None:
        # Smaller causal k-blocks (512) look 30-40% faster in an
        # ISOLATED kernel fwd+bwd micro-bench (above-diagonal blocks
        # skip compute), but inside the full jitted train step the
        # effect is noise at S<=2k and a 1-2% REGRESSION at S=4-8k —
        # XLA's surrounding schedule absorbs the skip and the extra
        # k-iterations cost dq/dkv loop overhead. Defaults follow the
        # in-model measurement; pass block_k explicitly to retune.
        block_k = _auto_block(seq_k, 1024)
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k:
        raise ValueError(
            "seq lengths (%d, %d) must be multiples of the block sizes "
            "(%d, %d)" % (seq_q, seq_k, block_q, block_k)
        )
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)
    if layout == "bshd":
        fuse = lambda t: t.reshape(batch, t.shape[1], heads * head_dim)
        o = _flash(
            fuse(q),
            fuse(k),
            fuse(v),
            sm_scale,
            causal,
            block_q,
            block_k,
            interpret,
            heads,
        )
        return o.reshape(batch, seq_q, heads, head_dim)
    merge = lambda t: t.reshape(batch * heads, t.shape[2], head_dim)
    o = _flash(
        merge(q),
        merge(k),
        merge(v),
        sm_scale,
        causal,
        block_q,
        block_k,
        interpret,
    )
    return o.reshape(batch, heads, seq_q, head_dim)
