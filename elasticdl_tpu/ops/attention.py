"""Attention dispatch: Pallas flash kernel on TPU, XLA math elsewhere.

``dot_product_attention`` is the op model code calls; the implementation
is picked by backend (or forced via ``impl=``):

- ``"pallas"``  — ops/flash_attention.py blockwise kernel (TPU)
- ``"xla"``     — plain jnp softmax attention (any backend; also the
                  correctness oracle the kernel is tested against)
- ``"auto"``    — pallas on TPU when shapes allow, else xla
"""

import math

import jax
import jax.numpy as jnp

from elasticdl_tpu.ops import flash_attention as _flash


def _check_layout(layout):
    if layout not in ("bhsd", "bshd"):
        raise ValueError("layout must be 'bhsd' or 'bshd', got %r"
                         % (layout,))


def xla_attention(q, k, v, causal=False, sm_scale=None, layout="bhsd"):
    """Reference O(S^2) attention ((batch, heads, seq, dim) or, with
    layout="bshd", (batch, seq, heads, dim) — no transposes either
    way, einsum handles both)."""
    _check_layout(layout)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    qk, pv = (
        ("bhqd,bhkd->bhqk", "bhqk,bhkd->bhqd")
        if layout == "bhsd"
        else ("bqhd,bkhd->bhqk", "bhqk,bkhd->bqhd")
    )
    s = jnp.einsum(qk, q, k, preferred_element_type=jnp.float32) * sm_scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        q_pos = jnp.arange(seq_q)[:, None]
        k_pos = jnp.arange(seq_k)[None, :]
        s = jnp.where(q_pos >= k_pos, s, _flash.NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum(pv, p, v)


def _pallas_ok(q, k, block_q, block_k, layout):
    seq_axis = 2 if layout == "bhsd" else 1
    seq_q, seq_k = q.shape[seq_axis], k.shape[seq_axis]
    if layout == "bshd" and q.shape[-1] % 128:
        return False  # fused-head addressing needs lane-aligned heads
    # None = flash_attention's auto-tuner picks the block; ask it what
    # it would pick so this gate can't drift from the tuner's fallback
    if block_q is None:
        block_q = _flash._auto_block(seq_q, 512)
    if block_k is None:
        block_k = _flash._auto_block(seq_k, 1024)
    return (
        seq_q % min(block_q, seq_q) == 0
        and seq_k % min(block_k, seq_k) == 0
        and seq_q >= 8
        and seq_k >= 128  # below one lane tile the kernel buys nothing
    )


def dot_product_attention(
    q,
    k,
    v,
    causal=False,
    sm_scale=None,
    impl="auto",
    block_q=None,
    block_k=None,
    interpret=False,
    layout="bhsd",
):
    _check_layout(layout)
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        impl = (
            "pallas"
            if on_tpu and _pallas_ok(q, k, block_q, block_k, layout)
            else "xla"
        )
    if impl == "pallas":
        if layout == "bshd" and q.shape[-1] % 128:
            # fused-head addressing needs lane-aligned head_dim; honor
            # the explicit pallas request through a transpose adapter
            to_bhsd = lambda t: t.transpose(0, 2, 1, 3)
            out = _flash.flash_attention(
                to_bhsd(q),
                to_bhsd(k),
                to_bhsd(v),
                causal=causal,
                sm_scale=sm_scale,
                block_q=block_q,
                block_k=block_k,
                interpret=interpret,
            )
            return out.transpose(0, 2, 1, 3)
        return _flash.flash_attention(
            q,
            k,
            v,
            causal=causal,
            sm_scale=sm_scale,
            block_q=block_q,
            block_k=block_k,
            interpret=interpret,
            layout=layout,
        )
    if impl == "xla":
        return xla_attention(
            q, k, v, causal=causal, sm_scale=sm_scale, layout=layout
        )
    raise ValueError("unknown attention impl %r" % (impl,))
