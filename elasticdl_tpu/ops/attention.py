"""Attention dispatch: Pallas flash kernel on TPU, XLA math elsewhere.

``dot_product_attention`` is the op model code calls; the implementation
is picked by backend (or forced via ``impl=``):

- ``"pallas"``  — ops/flash_attention.py blockwise kernel (TPU)
- ``"xla"``     — plain jnp softmax attention (any backend; also the
                  correctness oracle the kernel is tested against)
- ``"auto"``    — pallas on TPU when shapes allow, else xla
"""

import math

import jax
import jax.numpy as jnp

from elasticdl_tpu.ops import flash_attention as _flash


def xla_attention(q, k, v, causal=False, sm_scale=None):
    """Reference O(S^2) attention over (batch, heads, seq, dim)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        q_pos = jnp.arange(seq_q)[:, None]
        k_pos = jnp.arange(seq_k)[None, :]
        s = jnp.where(q_pos >= k_pos, s, _flash.NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _pallas_ok(q, k, block_q, block_k):
    seq_q, seq_k = q.shape[2], k.shape[2]
    # None = flash_attention's auto-tuner picks the block; ask it what
    # it would pick so this gate can't drift from the tuner's fallback
    if block_q is None:
        block_q = _flash._auto_block(seq_q, 512)
    if block_k is None:
        block_k = _flash._auto_block(seq_k, 1024)
    return (
        seq_q % min(block_q, seq_q) == 0
        and seq_k % min(block_k, seq_k) == 0
        and seq_q >= 8
        and seq_k >= 128  # below one lane tile the kernel buys nothing
    )


def dot_product_attention(
    q,
    k,
    v,
    causal=False,
    sm_scale=None,
    impl="auto",
    block_q=None,
    block_k=None,
    interpret=False,
):
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        impl = (
            "pallas"
            if on_tpu and _pallas_ok(q, k, block_q, block_k)
            else "xla"
        )
    if impl == "pallas":
        return _flash.flash_attention(
            q,
            k,
            v,
            causal=causal,
            sm_scale=sm_scale,
            block_q=block_q,
            block_k=block_k,
            interpret=interpret,
        )
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    raise ValueError("unknown attention impl %r" % (impl,))
