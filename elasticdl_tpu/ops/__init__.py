"""TPU-native compute ops (Pallas kernels + SPMD attention).

The reference has no custom device kernels beyond Eigen CPU loops
(go/pkg/kernel/capi/kernel_api.cc); on TPU the hot ops are expressed as
Pallas kernels (flash attention) and shard_map collectives (ring /
all-to-all sequence parallelism).
"""

from elasticdl_tpu.ops.attention import dot_product_attention  # noqa: F401
