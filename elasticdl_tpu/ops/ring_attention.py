"""Sequence/context parallelism: ring attention and all-to-all (Ulysses).

The reference has no long-context support at all (SURVEY.md §5); the only
axis it ever shards is the embedding-id axis across PS pods. These ops
are the new TPU-first capability: attention over a sequence sharded
across the ``sp`` mesh axis, communicating over ICI.

Two schedules, both differentiable (autodiff through scan/ppermute —
``ppermute``/``all_to_all`` have transpose rules, so the backward pass is
the reverse ring):

- ``ring_attention``: KV blocks rotate around the sp ring via
  ``ppermute`` while each device folds them into a flash-style online
  softmax. Memory O(S_local), comm overlaps compute under XLA latency
  hiding. Blockwise/RingAttention schedule (Liu et al.) — re-derived,
  not ported.
- ``ulysses_attention``: ``all_to_all`` re-shards seq <-> heads so each
  device holds the full sequence for H/sp heads, runs ordinary (flash)
  attention locally, and all-to-alls back. Cheaper comm for moderate S,
  requires heads % sp == 0.

Both are called *inside* jit on global arrays; they open a shard_map
manual region over the mesh.
"""

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common import jax_compat
from elasticdl_tpu.parallel.mesh import DATA_AXES

NEG_INF = -1e30


def _default_spec():
    # (batch, heads, seq, head_dim): batch over data axes, heads over tp,
    # seq over sp.
    return P(DATA_AXES, "tp", "sp", None)


def _block_update(carry, k_blk, v_blk, q, mask):
    """Fold one KV block into the running (m, l, acc) softmax state."""
    m_prev, l_prev, acc = carry
    s = (
        jnp.einsum(
            "bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        )
    )
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l_prev * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd",
        p,
        v_blk.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _spec_axis_names(spec):
    names = []
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            names.extend(entry)
        else:
            names.append(entry)
    return tuple(names)


def _make_flash_ring(axis_name, sp_size, causal, sm_scale, spec_axes,
                     block_q, block_k, interpret):
    """Per-device ring fold whose block compute is the Pallas flash
    kernel (ops/flash_attention.py) instead of einsum math.

    Forward: each ring step runs the kernel on (q, k_blk) and merges
    the partial (o_t, lse_t) into the running output with the standard
    log-sum-exp combine. Backward (custom_vjp — the kernel's own vjp
    can't serve because the merge needs lse as a live output): re-rotate
    the KV ring, call the kernel's backward per block with the GLOBAL
    (o, lse, do) — exp(s - lse_global) IS the global softmax restricted
    to the block — accumulate dq locally, and let each block's (dk, dv)
    accumulators ride the ring home (sp hops = full circle).
    Schedule per Liu et al. RingAttention; implementation original.
    """
    from elasticdl_tpu.ops import flash_attention as F

    NEG = F.NEG_INF
    vary = lambda x: jax_compat.pvary(x, spec_axes)

    def lse_w(lse_from, lse_to):
        # (bh, 1, S) log-weights -> (bh, S, 1) multiplicative weights
        return jnp.exp(lse_from - lse_to).transpose(0, 2, 1)

    def kernel_fwd(q_m, k_blk, v_blk, src, my_idx):
        def zeros(_):
            return (
                jnp.zeros(q_m.shape, jnp.float32),
                jnp.full(
                    (q_m.shape[0], 1, q_m.shape[1]), NEG, jnp.float32
                ),
            )

        def run(_):
            def call(diag):
                def inner(_):
                    o_t, lse_t = F._fwd(
                        q_m, k_blk, v_blk, sm_scale, diag,
                        block_q, block_k, interpret,
                    )
                    return o_t.astype(jnp.float32), lse_t

                return inner

            if not causal:
                return call(False)(None)
            return jax.lax.cond(
                src == my_idx, call(True), call(False), None
            )

        if not causal:
            return run(None)
        return jax.lax.cond(src > my_idx, zeros, run, None)

    def kernel_bwd(q_m, k_blk, v_blk, o_m, lse, do_m, src, my_idx):
        def zeros(_):
            return (
                jnp.zeros(q_m.shape, jnp.float32),
                jnp.zeros(k_blk.shape, jnp.float32),
                jnp.zeros(v_blk.shape, jnp.float32),
            )

        def run(_):
            def call(diag):
                def inner(_):
                    dq, dk, dv = F._bwd(
                        q_m, k_blk, v_blk, o_m, lse, do_m, sm_scale,
                        diag, block_q, block_k, interpret,
                    )
                    return (
                        dq.astype(jnp.float32),
                        dk.astype(jnp.float32),
                        dv.astype(jnp.float32),
                    )

                return inner

            if not causal:
                return call(False)(None)
            return jax.lax.cond(
                src == my_idx, call(True), call(False), None
            )

        if not causal:
            return run(None)
        return jax.lax.cond(src > my_idx, zeros, run, None)

    perm = [(j, (j + 1) % sp_size) for j in range(sp_size)]

    @jax.custom_vjp
    def fold(q_m, k_m, v_m):
        o, _ = _fold_fwd(q_m, k_m, v_m)
        return o

    def _fold_fwd(q_m, k_m, v_m):
        # only the causal mask needs the device index; the non-causal
        # fold ignores src/my_idx entirely, and leaving a dead
        # axis_index in the program lowers to a PartitionId op the CPU
        # SPMD partitioner rejects
        my_idx = (
            jax.lax.axis_index(axis_name) if causal else jnp.uint32(0)
        )
        bh, seq, _ = q_m.shape

        def step(carry, t):
            o, lse, k_blk, v_blk = carry
            src = (my_idx - t) % sp_size
            o_t, lse_t = kernel_fwd(q_m, k_blk, v_blk, src, my_idx)
            lse_new = jnp.logaddexp(lse, lse_t)
            o = o * lse_w(lse, lse_new) + o_t * lse_w(lse_t, lse_new)
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            return (o, lse_new, k_blk, v_blk), None

        init = (
            vary(jnp.zeros(q_m.shape, jnp.float32)),
            vary(jnp.full((bh, 1, seq), NEG, jnp.float32)),
            k_m,
            v_m,
        )
        (o, lse, _, _), _ = jax.lax.scan(
            step, init, jnp.arange(sp_size)
        )
        o = o.astype(q_m.dtype)
        return o, (q_m, k_m, v_m, o, lse)

    def _fold_bwd(res, do_m):
        q_m, k_m, v_m, o_m, lse = res
        my_idx = (
            jax.lax.axis_index(axis_name) if causal else jnp.uint32(0)
        )

        def step(carry, t):
            dq, k_blk, v_blk, dk_acc, dv_acc = carry
            src = (my_idx - t) % sp_size
            dq_t, dk_t, dv_t = kernel_bwd(
                q_m, k_blk, v_blk, o_m, lse, do_m, src, my_idx
            )
            dq = dq + dq_t
            dk_acc = dk_acc + dk_t
            dv_acc = dv_acc + dv_t
            # the (dk, dv) accumulators ride with their blocks: after
            # sp hops both are back on the block's owner
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
            return (dq, k_blk, v_blk, dk_acc, dv_acc), None

        init = (
            vary(jnp.zeros(q_m.shape, jnp.float32)),
            k_m,
            v_m,
            vary(jnp.zeros(k_m.shape, jnp.float32)),
            vary(jnp.zeros(v_m.shape, jnp.float32)),
        )
        (dq, _, _, dk, dv), _ = jax.lax.scan(
            step, init, jnp.arange(sp_size)
        )
        return (
            dq.astype(q_m.dtype),
            dk.astype(k_m.dtype),
            dv.astype(v_m.dtype),
        )

    fold.defvjp(_fold_fwd, _fold_bwd)
    return fold


def ring_attention(
    q,
    k,
    v,
    mesh,
    axis_name="sp",
    causal=False,
    sm_scale=None,
    spec=None,
    remat=True,
    block_impl="auto",
    block_q=None,
    block_k=None,
    interpret=False,
):
    """Attention with q/k/v sequence-sharded over ``axis_name``.

    Shapes are the global (batch, heads, seq, head_dim); sharding of the
    operands must match ``spec`` (default: batch over dp/fsdp, heads over
    tp, seq over sp).

    ``block_impl`` picks the per-block compute inside the ring fold:
    "einsum" (XLA math, any backend), "flash" (the Pallas kernel —
    per-device work becomes true flash attention), or "auto" (flash on
    TPU when the local sequence fits the kernel's block constraints).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    spec = spec if spec is not None else _default_spec()
    sp_size = mesh.shape[axis_name]
    if sp_size == 1:
        from elasticdl_tpu.ops.attention import dot_product_attention

        # honor block_impl even in the ring-of-one degenerate case: a
        # user who pinned "einsum" (e.g. around a kernel bug) must not
        # silently get the Pallas path back via impl="auto"
        impl = {"flash": "pallas", "einsum": "xla"}.get(
            block_impl, "auto"
        )
        return dot_product_attention(
            q, k, v, causal=causal, sm_scale=sm_scale, impl=impl,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )

    spec_axes = _spec_axis_names(spec)
    seq_loc_global = q.shape[2] // sp_size
    resolved = block_impl
    if resolved == "auto":
        from elasticdl_tpu.ops import flash_attention as _F

        blk = _F._auto_block(seq_loc_global, 512)
        ok = (
            jax.default_backend() == "tpu"
            and seq_loc_global >= 128
            and seq_loc_global % min(blk, seq_loc_global) == 0
        )
        resolved = "flash" if ok else "einsum"
    if resolved not in ("flash", "einsum"):
        raise ValueError("unknown ring block_impl %r" % (block_impl,))
    if resolved == "flash":
        from elasticdl_tpu.ops import flash_attention as _F

        blk_q = min(
            block_q or _F._auto_block(seq_loc_global, 512),
            seq_loc_global,
        )
        blk_k = min(
            block_k or _F._auto_block(seq_loc_global, 1024),
            seq_loc_global,
        )
        if seq_loc_global % blk_q or seq_loc_global % blk_k:
            # the kernel grid would silently skip the tail rows
            raise ValueError(
                "flash ring fold needs the local sequence (%d = global "
                "%d / sp %d) divisible by the blocks (%d, %d)"
                % (seq_loc_global, q.shape[2], sp_size, blk_q, blk_k)
            )
        fold = _make_flash_ring(
            axis_name, sp_size, causal, sm_scale, spec_axes,
            blk_q, blk_k, interpret,
        )

        def flash_local_fn(q_loc, k_loc, v_loc):
            b, h, s, d = q_loc.shape
            merge = lambda t: t.reshape(b * h, s, d)
            o = fold(merge(q_loc), merge(k_loc), merge(v_loc))
            return o.reshape(b, h, s, d)

        # check_vma=False: pallas_call's out ShapeDtypeStructs carry no
        # vma annotation, which the VMA checker rejects inside a
        # checked manual region; the specs here mirror the (long
        # VMA-checked) einsum path below
        return jax_compat.shard_map(
            flash_local_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)

    def local_fn(q_loc, k_loc, v_loc):
        my_idx = jax.lax.axis_index(axis_name)
        seq_loc = q_loc.shape[2]
        q32 = q_loc.astype(jnp.float32) * sm_scale

        def step(carry, t):
            m, l, acc, k_blk, v_blk = carry
            # After t hops the block on this device originated at shard
            # (my_idx - t) mod sp.
            src = (my_idx - t) % sp_size

            def masked_update(operands):
                m, l, acc, k_blk, v_blk = operands
                if causal:
                    q_pos = my_idx * seq_loc + jnp.arange(seq_loc)
                    k_pos = src * seq_loc + jnp.arange(seq_loc)
                    mask = q_pos[:, None] >= k_pos[None, :]
                    mask = mask[None, None]
                else:
                    mask = None
                return _block_update((m, l, acc), k_blk, v_blk, q32, mask)

            if causal:
                # Blocks strictly in the future contribute nothing: skip
                # the matmuls entirely (branch selected at runtime).
                m, l, acc = jax.lax.cond(
                    src > my_idx,
                    lambda operands: operands[:3],
                    masked_update,
                    (m, l, acc, k_blk, v_blk),
                )
            else:
                m, l, acc = masked_update((m, l, acc, k_blk, v_blk))
            # Rotate KV one hop around the ring (device j -> j+1).
            perm = [(j, (j + 1) % sp_size) for j in range(sp_size)]
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            return (m, l, acc, k_blk, v_blk), None

        step_fn = jax.checkpoint(step) if remat else step
        batch, heads = q_loc.shape[0], q_loc.shape[1]
        # Literal-zero inits are "unvarying" in shard_map's VMA typing
        # while the scan outputs vary per device; pvary reconciles them.
        # Vary only over the axes the in/out spec mentions: axes absent
        # from the spec (e.g. pp/ep) must stay unvarying or the out-spec
        # check rejects the result.
        spec_axes = []
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                spec_axes.extend(entry)
            else:
                spec_axes.append(entry)
        vary = lambda x: jax_compat.pvary(
            x, tuple(spec_axes)
        )
        init = (
            vary(jnp.full((batch, heads, seq_loc), NEG_INF, jnp.float32)),
            vary(jnp.zeros((batch, heads, seq_loc), jnp.float32)),
            vary(
                jnp.zeros(
                    (batch, heads, seq_loc, q_loc.shape[3]), jnp.float32
                )
            ),
            k_loc,
            v_loc,
        )
        (m, l, acc, _, _), _ = jax.lax.scan(
            step_fn, init, jnp.arange(sp_size)
        )
        safe_l = jnp.where(l > 0.0, l, 1.0)
        return (acc / safe_l[..., None]).astype(q_loc.dtype)

    return jax_compat.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)


def ulysses_attention(
    q,
    k,
    v,
    mesh,
    axis_name="sp",
    causal=False,
    sm_scale=None,
    spec=None,
    attention_fn=None,
):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses schedule).

    Re-shards (heads sharded <- seq sharded), runs full-sequence local
    attention per head group, re-shards back. ``attention_fn(q, k, v,
    causal, sm_scale)`` defaults to the flash/XLA dispatcher.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    spec = spec if spec is not None else _default_spec()
    sp_size = mesh.shape[axis_name]
    if attention_fn is None:
        from elasticdl_tpu.ops.attention import dot_product_attention

        attention_fn = dot_product_attention
    if sp_size == 1:
        return attention_fn(q, k, v, causal=causal, sm_scale=sm_scale)
    # The all_to_all splits the *per-device* head count (global heads
    # already divided by whatever axes spec shards dim 1 over).
    head_axes = tuple(spec)[1] if len(tuple(spec)) > 1 else None
    if head_axes is None:
        head_shard = 1
    elif isinstance(head_axes, (tuple, list)):
        head_shard = math.prod(mesh.shape[a] for a in head_axes)
    else:
        head_shard = mesh.shape[head_axes]
    local_heads = q.shape[1] // head_shard
    if local_heads % sp_size:
        raise ValueError(
            "ulysses needs per-device heads (%d global / %d sharded = %d)"
            " divisible by sp (%d)"
            % (q.shape[1], head_shard, local_heads, sp_size)
        )

    def local_fn(q_loc, k_loc, v_loc):
        # (B, H_loc*sp, S/sp, D) -> (B, H_loc, S, D): scatter heads,
        # gather sequence.
        def seq_to_heads(x):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=1, concat_axis=2, tiled=True
            )

        def heads_to_seq(x):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True
            )

        out = attention_fn(
            seq_to_heads(q_loc),
            seq_to_heads(k_loc),
            seq_to_heads(v_loc),
            causal=causal,
            sm_scale=sm_scale,
        )
        return heads_to_seq(out)

    return jax_compat.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
