"""Sequence/context parallelism: ring attention and all-to-all (Ulysses).

The reference has no long-context support at all (SURVEY.md §5); the only
axis it ever shards is the embedding-id axis across PS pods. These ops
are the new TPU-first capability: attention over a sequence sharded
across the ``sp`` mesh axis, communicating over ICI.

Two schedules, both differentiable (autodiff through scan/ppermute —
``ppermute``/``all_to_all`` have transpose rules, so the backward pass is
the reverse ring):

- ``ring_attention``: KV blocks rotate around the sp ring via
  ``ppermute`` while each device folds them into a flash-style online
  softmax. Memory O(S_local), comm overlaps compute under XLA latency
  hiding. Blockwise/RingAttention schedule (Liu et al.) — re-derived,
  not ported.
- ``ulysses_attention``: ``all_to_all`` re-shards seq <-> heads so each
  device holds the full sequence for H/sp heads, runs ordinary (flash)
  attention locally, and all-to-alls back. Cheaper comm for moderate S,
  requires heads % sp == 0.

Both are called *inside* jit on global arrays; they open a shard_map
manual region over the mesh.
"""

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.parallel.mesh import DATA_AXES

NEG_INF = -1e30


def _default_spec():
    # (batch, heads, seq, head_dim): batch over data axes, heads over tp,
    # seq over sp.
    return P(DATA_AXES, "tp", "sp", None)


def _block_update(carry, k_blk, v_blk, q, mask):
    """Fold one KV block into the running (m, l, acc) softmax state."""
    m_prev, l_prev, acc = carry
    s = (
        jnp.einsum(
            "bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        )
    )
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l_prev * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd",
        p,
        v_blk.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def ring_attention(
    q,
    k,
    v,
    mesh,
    axis_name="sp",
    causal=False,
    sm_scale=None,
    spec=None,
    remat=True,
):
    """Attention with q/k/v sequence-sharded over ``axis_name``.

    Shapes are the global (batch, heads, seq, head_dim); sharding of the
    operands must match ``spec`` (default: batch over dp/fsdp, heads over
    tp, seq over sp).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    spec = spec if spec is not None else _default_spec()
    sp_size = mesh.shape[axis_name]
    if sp_size == 1:
        from elasticdl_tpu.ops.attention import xla_attention

        return xla_attention(q, k, v, causal=causal, sm_scale=sm_scale)

    def local_fn(q_loc, k_loc, v_loc):
        my_idx = jax.lax.axis_index(axis_name)
        seq_loc = q_loc.shape[2]
        q32 = q_loc.astype(jnp.float32) * sm_scale

        def step(carry, t):
            m, l, acc, k_blk, v_blk = carry
            # After t hops the block on this device originated at shard
            # (my_idx - t) mod sp.
            src = (my_idx - t) % sp_size

            def masked_update(operands):
                m, l, acc, k_blk, v_blk = operands
                if causal:
                    q_pos = my_idx * seq_loc + jnp.arange(seq_loc)
                    k_pos = src * seq_loc + jnp.arange(seq_loc)
                    mask = q_pos[:, None] >= k_pos[None, :]
                    mask = mask[None, None]
                else:
                    mask = None
                return _block_update((m, l, acc), k_blk, v_blk, q32, mask)

            if causal:
                # Blocks strictly in the future contribute nothing: skip
                # the matmuls entirely (branch selected at runtime).
                m, l, acc = jax.lax.cond(
                    src > my_idx,
                    lambda operands: operands[:3],
                    masked_update,
                    (m, l, acc, k_blk, v_blk),
                )
            else:
                m, l, acc = masked_update((m, l, acc, k_blk, v_blk))
            # Rotate KV one hop around the ring (device j -> j+1).
            perm = [(j, (j + 1) % sp_size) for j in range(sp_size)]
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            return (m, l, acc, k_blk, v_blk), None

        step_fn = jax.checkpoint(step) if remat else step
        batch, heads = q_loc.shape[0], q_loc.shape[1]
        # Literal-zero inits are "unvarying" in shard_map's VMA typing
        # while the scan outputs vary per device; pvary reconciles them.
        # Vary only over the axes the in/out spec mentions: axes absent
        # from the spec (e.g. pp/ep) must stay unvarying or the out-spec
        # check rejects the result.
        spec_axes = []
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                spec_axes.extend(entry)
            else:
                spec_axes.append(entry)
        vary = lambda x: jax.lax.pcast(
            x, tuple(spec_axes), to="varying"
        )
        init = (
            vary(jnp.full((batch, heads, seq_loc), NEG_INF, jnp.float32)),
            vary(jnp.zeros((batch, heads, seq_loc), jnp.float32)),
            vary(
                jnp.zeros(
                    (batch, heads, seq_loc, q_loc.shape[3]), jnp.float32
                )
            ),
            k_loc,
            v_loc,
        )
        (m, l, acc, _, _), _ = jax.lax.scan(
            step_fn, init, jnp.arange(sp_size)
        )
        safe_l = jnp.where(l > 0.0, l, 1.0)
        return (acc / safe_l[..., None]).astype(q_loc.dtype)

    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)


def ulysses_attention(
    q,
    k,
    v,
    mesh,
    axis_name="sp",
    causal=False,
    sm_scale=None,
    spec=None,
    attention_fn=None,
):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses schedule).

    Re-shards (heads sharded <- seq sharded), runs full-sequence local
    attention per head group, re-shards back. ``attention_fn(q, k, v,
    causal, sm_scale)`` defaults to the flash/XLA dispatcher.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    spec = spec if spec is not None else _default_spec()
    sp_size = mesh.shape[axis_name]
    if attention_fn is None:
        from elasticdl_tpu.ops.attention import dot_product_attention

        attention_fn = dot_product_attention
    if sp_size == 1:
        return attention_fn(q, k, v, causal=causal, sm_scale=sm_scale)
    # The all_to_all splits the *per-device* head count (global heads
    # already divided by whatever axes spec shards dim 1 over).
    head_axes = tuple(spec)[1] if len(tuple(spec)) > 1 else None
    if head_axes is None:
        head_shard = 1
    elif isinstance(head_axes, (tuple, list)):
        head_shard = math.prod(mesh.shape[a] for a in head_axes)
    else:
        head_shard = mesh.shape[head_axes]
    local_heads = q.shape[1] // head_shard
    if local_heads % sp_size:
        raise ValueError(
            "ulysses needs per-device heads (%d global / %d sharded = %d)"
            " divisible by sp (%d)"
            % (q.shape[1], head_shard, local_heads, sp_size)
        )

    def local_fn(q_loc, k_loc, v_loc):
        # (B, H_loc*sp, S/sp, D) -> (B, H_loc, S, D): scatter heads,
        # gather sequence.
        def seq_to_heads(x):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=1, concat_axis=2, tiled=True
            )

        def heads_to_seq(x):
            return jax.lax.all_to_all(
                x, axis_name, split_axis=2, concat_axis=1, tiled=True
            )

        out = attention_fn(
            seq_to_heads(q_loc),
            seq_to_heads(k_loc),
            seq_to_heads(v_loc),
            causal=causal,
            sm_scale=sm_scale,
        )
        return heads_to_seq(out)

    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
