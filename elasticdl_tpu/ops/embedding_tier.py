"""Fused device-tier embedding kernels: gather-merge and scatter-apply.

The device tier (train/device_tier.py) keeps the Zipfian hot set of each
host-PS embedding table resident in accelerator memory as a
fixed-capacity slot table ``[capacity + pad, dim]`` (the padding's first
row is a scratch slot that absorbs writes addressed "nowhere"). Three
fused ops make the tier free of host round trips on the hit path:

- ``fused_insert_gather`` — one dispatch per table per step: write this
  step's staged promotions into their slots (resetting their optimizer
  slot state), read the eviction victims' current values out (the host
  writes them back to the PS), and materialize the step's full row
  buffer by merging device-resident hits with the PS-pulled miss rows.
- ``fused_scatter_apply`` — the sparse optimizer step applied directly
  to the resident slots from the step's row gradients: no gradient for
  a hit row ever crosses back to host RAM. Mirrors the PS store's
  update math (ps/embedding_store.py) for sgd / momentum / nesterov /
  adagrad / adam so a row trains the same whichever tier holds it.
- ``gather_rows`` — plain slot gather (flush/writeback reads).

Two implementations share every call site: a Pallas TPU kernel pair
(one grid step per row, slot indices scalar-prefetched so the block
index map does the gather/scatter addressing) and a pure-jnp fallback
built on XLA gather/scatter (``.at[].set``), which is what CPU CI runs
— both paths produce identical results, asserted by
tests/test_device_tier.py. Kernel choice: ``EDL_TIER_KERNEL`` =
``jnp`` (default everywhere but TPU) | ``pallas`` | ``auto`` (pallas on
a TPU backend, jnp elsewhere).

Uniqueness contract: ``slots`` entries are unique per call except the
scratch sentinel, which may repeat — every op writes the scratch row
with set-semantics only, so duplicate scratch writes race benignly into
a row nothing ever reads.
"""

import functools

import jax
import jax.numpy as jnp

from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.ops.embedding_tier")

KERNEL_ENV = "EDL_TIER_KERNEL"

# tests flip this to run the Pallas kernels in interpreter mode on CPU
# (same code path as TPU minus the Mosaic lowering)
INTERPRET = False

# optimizer -> number of [rows, dim] slot-state buffers (mirror of
# ps/embedding_store.OPT_SLOT_COUNTS for the tier-supported subset)
TIER_OPT_SLOTS = {
    "sgd": 0, "momentum": 1, "nesterov": 1, "adagrad": 1, "adam": 2,
}


def resolve_kernel(kind=None):
    """-> "pallas" | "jnp". ``auto`` picks pallas only on a TPU
    backend; CPU CI exercises the jnp path (same call sites)."""
    kind = (kind or env_str(KERNEL_ENV, "auto")).strip().lower()
    if kind not in ("auto", "pallas", "jnp"):
        raise ValueError(
            "%s must be auto|pallas|jnp (got %r)" % (KERNEL_ENV, kind)
        )
    if kind == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "jnp"
    return kind


def init_table_state(capacity, dim, opt_type, dtype=jnp.float32):
    """Fresh tier state for one table: weights + optimizer slot state +
    per-slot step counts (adam bias correction), all zeros. ``capacity``
    INCLUDES the scratch padding row(s)."""
    if opt_type not in TIER_OPT_SLOTS:
        raise ValueError(
            "device tier supports %s sparse optimizers (got %r)"
            % (sorted(TIER_OPT_SLOTS), opt_type)
        )
    state = {"rows": jnp.zeros((capacity, dim), dtype)}
    for k in range(TIER_OPT_SLOTS[opt_type]):
        state["slot%d" % k] = jnp.zeros((capacity, dim), dtype)
    state["steps"] = jnp.zeros((capacity,), jnp.int32)
    return state


# ---------------------------------------------------------------------
# pure-jnp implementations (XLA gather/scatter; the CPU-CI path)


def _jnp_insert_gather(state, ins_slots, ins_rows, evict_slots, slots,
                       miss_rows):
    """-> (new_state, combined_rows, evicted_rows).

    Order matters: victims are read BEFORE staged inserts land (an
    insert may reuse a victim's slot this very step), and the combined
    buffer is gathered AFTER (a promotion is a hit from its first
    step). Padding convention: ``ins_slots``/``evict_slots`` pad with
    the scratch slot, ``slots`` pads misses with -1."""
    evicted = jnp.take(state["rows"], evict_slots, axis=0)
    new_state = dict(state)
    new_state["rows"] = state["rows"].at[ins_slots].set(ins_rows)
    for key, value in state.items():
        if key.startswith("slot"):
            new_state[key] = value.at[ins_slots].set(0.0)
    new_state["steps"] = state["steps"].at[ins_slots].set(0)
    hit = slots >= 0
    safe = jnp.where(hit, slots, 0)
    gathered = jnp.take(new_state["rows"], safe, axis=0)
    combined = jnp.where(hit[:, None], gathered, miss_rows)
    return new_state, combined, evicted


def _jnp_scatter_apply(state, slots, grads, opt_type, lr, momentum,
                       beta1, beta2, epsilon):
    """Sparse optimizer step on the resident slots; misses (slot -1)
    are routed to the scratch row. Update math mirrors
    ps/embedding_store.NumpyEmbeddingStore (fp32 bias corrections)."""
    scratch = state["rows"].shape[0] - 1
    target = jnp.where(slots >= 0, slots, scratch).astype(jnp.int32)
    w = jnp.take(state["rows"], target, axis=0)
    step = jnp.take(state["steps"], target) + 1
    new_state = dict(state)
    if opt_type == "sgd":
        new_w = w - lr * grads
    elif opt_type in ("momentum", "nesterov"):
        m = jnp.take(state["slot0"], target, axis=0)
        m = momentum * m + grads
        if opt_type == "nesterov":
            new_w = w - lr * (grads + momentum * m)
        else:
            new_w = w - lr * m
        new_state["slot0"] = state["slot0"].at[target].set(m)
    elif opt_type == "adagrad":
        s = jnp.take(state["slot0"], target, axis=0)
        s = s + grads * grads
        new_w = w - lr * grads / (jnp.sqrt(s) + epsilon)
        new_state["slot0"] = state["slot0"].at[target].set(s)
    elif opt_type == "adam":
        m = jnp.take(state["slot0"], target, axis=0)
        v = jnp.take(state["slot1"], target, axis=0)
        m = beta1 * m + (1.0 - beta1) * grads
        v = beta2 * v + (1.0 - beta2) * grads * grads
        stepf = step.astype(jnp.float32)[:, None]
        mhat = m / (1.0 - jnp.power(beta1, stepf))
        vhat = v / (1.0 - jnp.power(beta2, stepf))
        new_w = w - lr * mhat / (jnp.sqrt(vhat) + epsilon)
        new_state["slot0"] = state["slot0"].at[target].set(m)
        new_state["slot1"] = state["slot1"].at[target].set(v)
    else:
        raise ValueError("unsupported tier optimizer %r" % opt_type)
    new_state["rows"] = state["rows"].at[target].set(new_w)
    new_state["steps"] = state["steps"].at[target].set(step)
    return new_state


# ---------------------------------------------------------------------
# Pallas TPU kernels: one grid step per row, slot addressing done by
# the BlockSpec index maps over scalar-prefetched slot arrays.


def _pallas_gather(table, slots, miss_rows):
    """combined[i] = slots[i] >= 0 ? table[slots[i]] : miss_rows[i]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, dim = miss_rows.shape

    def kernel(slots_ref, table_blk, miss_blk, out_ref):
        i = pl.program_id(0)
        hit = slots_ref[i] >= 0
        out_ref[:] = jnp.where(hit, table_blk[:], miss_blk[:])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            # the gather: block row = the slot (clamped to 0 on miss;
            # the select above discards the garbage row)
            pl.BlockSpec(
                (1, dim),
                lambda i, slots: (jnp.maximum(slots[i], 0), 0),
            ),
            pl.BlockSpec((1, dim), lambda i, slots: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, dim), lambda i, slots: (i, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, dim), table.dtype),
        interpret=INTERPRET,
    )(slots, table, miss_rows)


def _pallas_set_rows(table, slots, rows):
    """table.at[slots].set(rows) (staged promotion insert); ``slots``
    pad with the scratch row, whose garbage nothing reads."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, dim = rows.shape

    def kernel(slots_ref, table_blk, rows_blk, out_blk):
        del slots_ref, table_blk
        out_blk[:] = rows_blk[:]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            # the aliased table rides along so unvisited rows keep
            # their values (in-place update via the alias below)
            pl.BlockSpec((1, dim), lambda i, slots: (slots[i], 0)),
            pl.BlockSpec((1, dim), lambda i, slots: (i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, dim), lambda i, slots: (slots[i], 0)
        ),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={1: 0},
        interpret=INTERPRET,
    )(slots, table, rows)


def _pallas_insert_gather(state, ins_slots, ins_rows, evict_slots, slots,
                          miss_rows):
    evicted = _pallas_gather(
        state["rows"], evict_slots,
        jnp.zeros((evict_slots.shape[0],) + state["rows"].shape[1:],
                  state["rows"].dtype),
    )
    new_state = dict(state)
    new_state["rows"] = _pallas_set_rows(
        state["rows"], ins_slots, ins_rows
    )
    zeros = jnp.zeros_like(ins_rows)
    for key, value in state.items():
        if key.startswith("slot"):
            new_state[key] = _pallas_set_rows(value, ins_slots, zeros)
    # steps is a 1-d int32 vector; the scalar reset stays on XLA scatter
    # (a [n] set is not worth a kernel launch)
    new_state["steps"] = state["steps"].at[ins_slots].set(0)
    combined = _pallas_gather(new_state["rows"], slots, miss_rows)
    return new_state, combined, evicted


def _pallas_scatter_apply(state, slots, grads, opt_type, lr, momentum,
                          beta1, beta2, epsilon):
    """One grid step per gradient row: the BlockSpec index maps route
    each row's read-modify-write straight at its resident slot (misses
    at the scratch row). Aliased in/out so the update is in place."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, dim = grads.shape
    scratch = state["rows"].shape[0] - 1
    target = jnp.where(slots >= 0, slots, scratch).astype(jnp.int32)
    step = state["steps"].at[target].add(1)
    stepf = jnp.take(step, target).astype(jnp.float32)
    n_slots = sum(1 for k in state if k.startswith("slot"))

    def row_spec():
        return pl.BlockSpec((1, dim), lambda i, tgt: (i, 0))

    def slot_spec():
        return pl.BlockSpec((1, dim), lambda i, tgt: (tgt[i], 0))

    def kernel(tgt_ref, *refs):
        i = pl.program_id(0)
        grad_blk = refs[0]
        step_blk = refs[1]
        in_w = refs[2]
        in_slots = refs[3:3 + n_slots]
        out_w = refs[3 + n_slots]
        out_slots = refs[4 + n_slots:4 + 2 * n_slots]
        del tgt_ref, i
        g = grad_blk[:]
        w = in_w[:]
        if opt_type == "sgd":
            out_w[:] = w - lr * g
        elif opt_type in ("momentum", "nesterov"):
            m = momentum * in_slots[0][:] + g
            if opt_type == "nesterov":
                out_w[:] = w - lr * (g + momentum * m)
            else:
                out_w[:] = w - lr * m
            out_slots[0][:] = m
        elif opt_type == "adagrad":
            s = in_slots[0][:] + g * g
            out_w[:] = w - lr * g / (jnp.sqrt(s) + epsilon)
            out_slots[0][:] = s
        else:  # adam
            t = step_blk[0, 0]
            m = beta1 * in_slots[0][:] + (1.0 - beta1) * g
            v = beta2 * in_slots[1][:] + (1.0 - beta2) * g * g
            mhat = m / (1.0 - jnp.power(beta1, t))
            vhat = v / (1.0 - jnp.power(beta2, t))
            out_w[:] = w - lr * mhat / (jnp.sqrt(vhat) + epsilon)
            out_slots[0][:] = m
            out_slots[1][:] = v

    slot_keys = sorted(k for k in state if k.startswith("slot"))
    inputs = [grads, stepf[:, None], state["rows"]]
    inputs += [state[k] for k in slot_keys]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            row_spec(),                       # grads
            pl.BlockSpec((1, 1), lambda i, tgt: (i, 0)),  # step counts
            slot_spec(),                      # weights (read)
        ] + [slot_spec() for _ in slot_keys],
        out_specs=[slot_spec()] + [slot_spec() for _ in slot_keys],
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(state["rows"].shape, state["rows"].dtype)
        ] + [
            jax.ShapeDtypeStruct(state[k].shape, state[k].dtype)
            for k in slot_keys
        ],
        # weights/slot buffers update in place (alias input -> output);
        # input index offsets: [slots(prefetch), grads, step, rows, ...]
        input_output_aliases=dict(
            [(3, 0)] + [(4 + j, 1 + j) for j in range(n_slots)]
        ),
        interpret=INTERPRET,
    )(target, *inputs)
    outs = [outs] if not isinstance(outs, (list, tuple)) else list(outs)
    new_state = dict(state)
    new_state["rows"] = outs[0]
    for j, key in enumerate(slot_keys):
        new_state[key] = outs[1 + j]
    new_state["steps"] = step
    return new_state


# ---------------------------------------------------------------------
# public fused ops


def fused_insert_gather(state, ins_slots, ins_rows, evict_slots, slots,
                        miss_rows, kernel="jnp"):
    """Stage promotions in, read eviction victims out, and materialize
    the step's combined row buffer — one fused op (see module
    docstring for padding conventions)."""
    impl = (
        _pallas_insert_gather if kernel == "pallas"
        else _jnp_insert_gather
    )
    return impl(state, ins_slots, ins_rows, evict_slots, slots, miss_rows)


def fused_scatter_apply(state, slots, grads, opt_type="sgd", lr=0.01,
                        momentum=0.9, beta1=0.9, beta2=0.999,
                        epsilon=1e-8, kernel="jnp"):
    """Apply one step's row gradients to the resident slots in device
    memory (misses fall into the scratch row)."""
    impl = (
        _pallas_scatter_apply if kernel == "pallas"
        else _jnp_scatter_apply
    )
    return impl(
        state, slots, grads, opt_type, lr, momentum, beta1, beta2,
        epsilon,
    )


def gather_rows(state, slots, kernel="jnp"):
    """Read resident rows at ``slots`` (flush / eviction writeback)."""
    if kernel == "pallas":
        return _pallas_gather(
            state["rows"], slots,
            jnp.zeros(
                (slots.shape[0],) + state["rows"].shape[1:],
                state["rows"].dtype,
            ),
        )
    return jnp.take(state["rows"], jnp.maximum(slots, 0), axis=0)


@functools.lru_cache(maxsize=None)
def _warn_fallback_once(reason):
    logger.warning(
        "Pallas TPU kernels unavailable (%s); device tier falling "
        "back to the jnp gather/scatter path", reason,
    )


def checked_kernel(kind):
    """Resolve the configured kernel, degrading pallas->jnp (with one
    warning) when the Pallas TPU stack is unimportable — the tier must
    train on any backend the rest of the framework supports."""
    kind = resolve_kernel(kind)
    if kind != "pallas":
        return kind
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    # logged (once) by _warn_fallback_once before degrading
    except Exception as e:  # edlint: disable=ft-swallowed-except
        _warn_fallback_once(repr(e))
        return "jnp"
    return kind
