"""Mixture-of-experts routing: top-k router with static capacity.

No reference counterpart (SURVEY.md §2.12: expert parallelism is absent
from the reference); this is a new TPU-first capability. The design is
the GShard/Switch dispatch formulation expressed entirely as static-shape
einsums so XLA can lay expert compute out over an ``ep`` mesh axis and
insert the all-to-alls itself:

- every token picks its top-k experts from router logits;
- each expert has a fixed per-group capacity C (static shape!), tokens
  beyond capacity are dropped (their combine weight is zero, the residual
  stream carries them through);
- dispatch/combine are (G, S, E, C) tensors contracted against the token
  stream, so "send token to expert" is an einsum — exactly the shape
  GSPMD turns into an all-to-all when tokens are dp-sharded and experts
  ep-sharded.

Everything is shape-static and jit-friendly: k is a Python int (unrolled
loop), capacity is computed from static dims.
"""

import jax
import jax.numpy as jnp


def expert_capacity(seq_len, num_experts, k=1, capacity_factor=1.25):
    """Static per-group expert capacity: ceil(S*k/E) * factor."""
    per_expert = (seq_len * k + num_experts - 1) // num_experts
    return max(1, int(per_expert * capacity_factor))


def top_k_routing(router_logits, k, capacity):
    """Compute dispatch/combine tensors for top-k token→expert routing.

    Args:
      router_logits: (G, S, E) — G token groups (batch rows), S tokens
        per group, E experts.
      k: experts per token (static Python int).
      capacity: per-(group, expert) token budget C (static Python int).

    Returns:
      combine: (G, S, E, C) float — weights for re-combining expert
        outputs back into the token stream (zero for dropped tokens).
      dispatch: (G, S, E, C) bool — one-hot token→(expert, slot)
        assignment.
      aux_loss: scalar — Switch-style load-balance loss, E * Σ_e f_e·p_e
        where f_e is the fraction of tokens whose FIRST choice is e and
        p_e the mean router probability of e.
    """
    num_experts = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gates, indices = jax.lax.top_k(probs, k)  # (G, S, k)
    # Renormalize the kept gates so combine weights sum to 1 per token.
    gates = gates / (gates.sum(axis=-1, keepdims=True) + 1e-9)

    # Load-balance aux loss over first choices (Switch Transformer eq. 4).
    first_choice = jax.nn.one_hot(indices[..., 0], num_experts)
    tokens_per_expert = first_choice.mean(axis=(0, 1))  # f_e
    prob_per_expert = probs.mean(axis=(0, 1))  # p_e
    aux_loss = num_experts * jnp.sum(tokens_per_expert * prob_per_expert)

    # Assign capacity slots choice-rank-major: all rank-0 choices get
    # priority over rank-1 choices, and within a rank, earlier tokens win
    # (cumsum order). `counts` carries per-expert occupancy across ranks.
    combine = jnp.zeros(
        router_logits.shape + (capacity,), dtype=jnp.float32
    )
    dispatch = jnp.zeros(
        router_logits.shape + (capacity,), dtype=jnp.bool_
    )
    counts = jnp.zeros(
        router_logits.shape[:1] + (num_experts,), dtype=jnp.int32
    )  # (G, E)
    for rank in range(k):
        choice = jax.nn.one_hot(
            indices[..., rank], num_experts, dtype=jnp.int32
        )  # (G, S, E)
        # Position of each token inside its chosen expert's buffer.
        position = (
            jnp.cumsum(choice, axis=1) - choice + counts[:, None, :]
        )  # (G, S, E)
        within = (position < capacity) & (choice > 0)
        slot = jax.nn.one_hot(position, capacity, dtype=jnp.float32)
        dispatch_r = within[..., None] & (slot > 0)  # (G, S, E, C)
        combine = combine + gates[..., rank, None, None] * dispatch_r
        dispatch = dispatch | dispatch_r
        counts = counts + (choice * within).sum(axis=1)
    return combine, dispatch, aux_loss


def moe_dispatch(x, dispatch):
    """Token stream → per-expert buffers.

    x: (G, S, M); dispatch: (G, S, E, C) → (E, G, C, M).
    Under GSPMD (tokens g→dp-sharded, output e→ep-sharded) this einsum
    IS the all-to-all.
    """
    return jnp.einsum(
        "gsec,gsm->egcm", dispatch.astype(x.dtype), x
    )


def moe_combine(expert_out, combine):
    """Per-expert buffers → token stream (weighted by gate values).

    expert_out: (E, G, C, M); combine: (G, S, E, C) → (G, S, M).
    """
    return jnp.einsum(
        "gsec,egcm->gsm", combine.astype(expert_out.dtype), expert_out
    )
