"""Mixture-of-experts routing: top-k router with static capacity.

No reference counterpart (SURVEY.md §2.12: expert parallelism is absent
from the reference); this is a new TPU-first capability. The design is
the GShard/Switch dispatch formulation expressed entirely as static-shape
einsums so XLA can lay expert compute out over an ``ep`` mesh axis and
insert the all-to-alls itself:

- every token picks its top-k experts from router logits;
- each expert has a fixed per-group capacity C (static shape!), tokens
  beyond capacity are dropped (their combine weight is zero, the residual
  stream carries them through);
- dispatch/combine are (G, S, E, C) tensors contracted against the token
  stream, so "send token to expert" is an einsum — exactly the shape
  GSPMD turns into an all-to-all when tokens are dp-sharded and experts
  ep-sharded.

Everything is shape-static and jit-friendly: k is a Python int (unrolled
loop), capacity is computed from static dims.
"""

import jax
import jax.numpy as jnp


def expert_capacity(seq_len, num_experts, k=1, capacity_factor=1.25):
    """Static per-group expert capacity: ceil(S*k/E) * factor."""
    per_expert = (seq_len * k + num_experts - 1) // num_experts
    return max(1, int(per_expert * capacity_factor))


def top_k_routing(router_logits, k, capacity):
    """Compute dispatch/combine tensors for top-k token→expert routing.

    Args:
      router_logits: (G, S, E) — G token groups (batch rows), S tokens
        per group, E experts.
      k: experts per token (static Python int).
      capacity: per-(group, expert) token budget C (static Python int).

    Returns:
      combine: (G, S, E, C) float — weights for re-combining expert
        outputs back into the token stream (zero for dropped tokens).
      dispatch: (G, S, E, C) bool — one-hot token→(expert, slot)
        assignment.
      aux_loss: scalar — Switch-style load-balance loss, E * Σ_e f_e·p_e
        where f_e is the fraction of tokens whose FIRST choice is e and
        p_e the mean router probability of e.
    """
    num_experts = router_logits.shape[-1]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gates, indices = jax.lax.top_k(probs, k)  # (G, S, k)
    # Renormalize the kept gates so combine weights sum to 1 per token.
    gates = gates / (gates.sum(axis=-1, keepdims=True) + 1e-9)

    # Load-balance aux loss over first choices (Switch Transformer eq. 4).
    first_choice = jax.nn.one_hot(indices[..., 0], num_experts)
    tokens_per_expert = first_choice.mean(axis=(0, 1))  # f_e
    prob_per_expert = probs.mean(axis=(0, 1))  # p_e
    aux_loss = num_experts * jnp.sum(tokens_per_expert * prob_per_expert)

    # Assign capacity slots choice-rank-major: all rank-0 choices get
    # priority over rank-1 choices, and within a rank, earlier tokens win
    # (cumsum order). `counts` carries per-expert occupancy across ranks.
    combine = jnp.zeros(
        router_logits.shape + (capacity,), dtype=jnp.float32
    )
    dispatch = jnp.zeros(
        router_logits.shape + (capacity,), dtype=jnp.bool_
    )
    counts = jnp.zeros(
        router_logits.shape[:1] + (num_experts,), dtype=jnp.int32
    )  # (G, E)
    for rank in range(k):
        choice = jax.nn.one_hot(
            indices[..., rank], num_experts, dtype=jnp.int32
        )  # (G, S, E)
        # Position of each token inside its chosen expert's buffer.
        position = (
            jnp.cumsum(choice, axis=1) - choice + counts[:, None, :]
        )  # (G, S, E)
        within = (position < capacity) & (choice > 0)
        slot = jax.nn.one_hot(position, capacity, dtype=jnp.float32)
        dispatch_r = within[..., None] & (slot > 0)  # (G, S, E, C)
        combine = combine + gates[..., rank, None, None] * dispatch_r
        dispatch = dispatch | dispatch_r
        counts = counts + (choice * within).sum(axis=1)
    return combine, dispatch, aux_loss


def top_k_routing_compact(router_logits, k, capacity):
    """Slot-index routing: the same assignment policy as
    ``top_k_routing`` (choice-rank-major priority, cumsum order within
    a rank, capacity overflow dropped) but WITHOUT materializing the
    (G, S, E, C) one-hot tensors — it returns flat slot ids instead.

    The on-chip trace of the einsum formulation
    (docs/traces/moe_v5e_summary.txt) showed the one-hot dispatch/
    combine einsums and their (G, S, E, C) operands dragging the
    matmul-fusion bandwidth to 404 GB/s; this form replaces them with
    O(S·k) index arithmetic so dispatch/combine become gathers.

    Returns:
      gates: (G, k, S) float32, rank-major combine weights (zero is
        NOT forced for dropped tokens — the combine gather reads a
        zero row for them instead).
      slot: (G, k*S) int32 — flat ``expert * capacity + position``
        slot id per (rank, token), rank-major; dropped tokens get the
        out-of-range id ``E * capacity`` (the zero-pad row).
      aux_loss: identical to ``top_k_routing``.
    """
    num_groups, seq, num_experts = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gates, indices = jax.lax.top_k(probs, k)  # (G, S, k)
    gates = gates / (gates.sum(axis=-1, keepdims=True) + 1e-9)

    first_choice = jax.nn.one_hot(indices[..., 0], num_experts)
    tokens_per_expert = first_choice.mean(axis=(0, 1))
    prob_per_expert = probs.mean(axis=(0, 1))
    aux_loss = num_experts * jnp.sum(tokens_per_expert * prob_per_expert)

    # Rank-major flat order (rank 0 of every token precedes rank 1, and
    # within a rank earlier tokens win) — the priority top_k_routing's
    # per-rank cumsum loop implements. Position = number of prior
    # assignments to the same expert in this order; counting dropped
    # priors too is equivalent (a prior overflow forces >= C either
    # way), so no per-rank clamped-occupancy carry is needed.
    e_flat = indices.transpose(0, 2, 1).reshape(
        num_groups, k * seq
    )  # (G, kS)
    onehot = jax.nn.one_hot(e_flat, num_experts, dtype=jnp.int32)
    prior = jnp.cumsum(onehot, axis=1) - onehot  # (G, kS, E)
    position = jnp.take_along_axis(
        prior, e_flat[:, :, None], axis=2
    )[..., 0]  # (G, kS)
    slot = jnp.where(
        position < capacity,
        e_flat * capacity + position,
        num_experts * capacity,
    ).astype(jnp.int32)
    return gates.transpose(0, 2, 1), slot, aux_loss


def invert_slots(slot, n_slots):
    """(G, kS) slot ids → (G, n_slots) flat FILLER index per slot
    (sentinel kS for empty slots). Valid slot ids are unique by
    construction; only the dummy slot n_slots collides, and that
    column is sliced off. This tiny int32 scatter is the ONLY scatter
    in the compact formulation — because the slot mapping is
    invertible, every M-wide data movement (including both autodiff
    backwards, see the custom VJPs below) is a gather, which the TPU
    streams at memory bandwidth where XLA's scatter-add lowering was
    measured at 93 GB/s (docs/PERF_MOE.md trace)."""
    num_groups, flat = slot.shape
    j_ids = jnp.broadcast_to(
        jnp.arange(flat, dtype=jnp.int32), (num_groups, flat)
    )
    j_for_slot = jnp.full(
        (num_groups, n_slots + 1), flat, dtype=jnp.int32
    )
    return j_for_slot.at[
        jnp.arange(num_groups)[:, None], slot
    ].set(j_ids)[:, :n_slots]


@jax.custom_vjp
def _dispatch_gather(x, slot, j_for_slot):
    num_groups, seq, dim = x.shape
    flat = slot.shape[1]
    token = jnp.where(j_for_slot == flat, seq, j_for_slot % seq)
    x_pad = jnp.concatenate(
        [x, jnp.zeros((num_groups, 1, dim), x.dtype)], axis=1
    )
    return jnp.take_along_axis(
        x_pad, token[:, :, None], axis=1
    )  # (G, E*C, M)


def _dispatch_gather_fwd(x, slot, j_for_slot):
    return _dispatch_gather(x, slot, j_for_slot), (slot, x.shape)


def _dispatch_gather_bwd(res, d_out):
    """dx[g,s] = Σ_r d_out[g, slot[g, r·S+s]] — a GATHER through the
    forward index (dropped ranks hit the zero pad row), where plain
    autodiff of take_along_axis would emit a scatter-add."""
    slot, (num_groups, seq, dim) = res
    k = slot.shape[1] // seq
    d_out_pad = jnp.concatenate(
        [d_out, jnp.zeros((num_groups, 1, dim), d_out.dtype)], axis=1
    )
    rows = jnp.take_along_axis(d_out_pad, slot[:, :, None], axis=1)
    dx = rows.reshape(num_groups, k, seq, dim).sum(axis=1)
    return (dx, None, None)


_dispatch_gather.defvjp(_dispatch_gather_fwd, _dispatch_gather_bwd)


def moe_dispatch_compact(x, slot, num_experts, capacity,
                         j_for_slot=None):
    """Token stream → per-expert buffers via an inverse-permutation
    gather (no (G, S, E, C) one-hot, no dispatch matmul FLOPs).

    x: (G, S, M); slot: (G, k*S) from ``top_k_routing_compact``
    → (E, G, C, M). Same semantics as ``moe_dispatch(x, dispatch)``:
    a slot holds its token's embedding, empty slots are zero.
    ``j_for_slot``: pass ``invert_slots(slot, E*C)`` when the caller
    also combines (MoeMlp does) so the inversion scatter runs once.
    """
    num_groups, _, dim = x.shape
    if j_for_slot is None:
        j_for_slot = invert_slots(slot, num_experts * capacity)
    out = _dispatch_gather(x, slot, j_for_slot)
    return out.reshape(
        num_groups, num_experts, capacity, dim
    ).transpose(1, 0, 2, 3)


@jax.custom_vjp
def _combine_gather(eo_flat, gates, slot, j_for_slot):
    """eo_flat: (G, E*C, M); gates: (G, k, S) → y (G, S, M)."""
    num_groups, _, dim = eo_flat.shape
    k = gates.shape[1]
    seq = slot.shape[1] // k
    eo_pad = jnp.concatenate(
        [eo_flat, jnp.zeros((num_groups, 1, dim), eo_flat.dtype)],
        axis=1,
    )
    rows = jnp.take_along_axis(eo_pad, slot[:, :, None], axis=1)
    rows = rows.reshape(num_groups, k, seq, dim)
    return (rows * gates[..., None].astype(rows.dtype)).sum(axis=1)


def _combine_gather_fwd(eo_flat, gates, slot, j_for_slot):
    return (
        _combine_gather(eo_flat, gates, slot, j_for_slot),
        (eo_flat, gates, slot, j_for_slot),
    )


def _combine_gather_bwd(res, dy):
    """Both cotangents are gathers:
    d_eo[g,n] = gate_of_filler(n) · dy[g, token_of_filler(n)] (each
    slot has at most ONE filler — the inverse index j_for_slot), and
    d_gates[g,r,s] = <dy[g,s], eo[g, slot[g,r·S+s]]> (re-gather of the
    forward rows). Plain autodiff would scatter-add gate-weighted dy
    rows into the expert buffers instead."""
    eo_flat, gates, slot, j_for_slot = res
    num_groups, _, dim = eo_flat.shape
    k = gates.shape[1]
    flat = slot.shape[1]
    seq = flat // k

    # d_gates: recompute the forward row gather (cheap; saves keeping
    # the (G, kS, M) rows tensor alive as a residual)
    eo_pad = jnp.concatenate(
        [eo_flat, jnp.zeros((num_groups, 1, dim), eo_flat.dtype)],
        axis=1,
    )
    rows = jnp.take_along_axis(eo_pad, slot[:, :, None], axis=1)
    rows = rows.reshape(num_groups, k, seq, dim)
    d_gates = (
        rows.astype(jnp.float32) * dy[:, None].astype(jnp.float32)
    ).sum(axis=-1).astype(gates.dtype)

    # d_eo: gather dy by each slot's filler token, weighted by the
    # filler's gate (empty slots: sentinel j = kS hits the zero pads)
    token = jnp.where(j_for_slot == flat, seq, j_for_slot % seq)
    dy_pad = jnp.concatenate(
        [dy, jnp.zeros((num_groups, 1, dim), dy.dtype)], axis=1
    )
    gate_flat_pad = jnp.concatenate(
        [
            gates.reshape(num_groups, flat),
            jnp.zeros((num_groups, 1), gates.dtype),
        ],
        axis=1,
    )
    d_rows = jnp.take_along_axis(dy_pad, token[:, :, None], axis=1)
    gate_for_slot = jnp.take_along_axis(
        gate_flat_pad, j_for_slot, axis=1
    )
    d_eo = (
        d_rows * gate_for_slot[:, :, None].astype(d_rows.dtype)
    ).astype(eo_flat.dtype)
    return (d_eo, d_gates, None, None)


_combine_gather.defvjp(_combine_gather_fwd, _combine_gather_bwd)


def moe_combine_compact(expert_out, slot, gates, j_for_slot=None):
    """Per-expert buffers → token stream: gather each (rank, token)'s
    slot row back and sum over ranks weighted by the gates.

    expert_out: (E, G, C, M); slot: (G, k*S); gates: (G, k, S)
    → (G, S, M). Dropped tokens point at the zero pad row, so their
    contribution is zero — identical to ``moe_combine``'s zero combine
    weights (including the zero gate-gradient for dropped tokens:
    d(gate) = <dy, zero row> = 0 on both paths). ``j_for_slot`` as in
    ``moe_dispatch_compact``.
    """
    num_experts, num_groups, capacity, dim = expert_out.shape
    eo_flat = expert_out.transpose(1, 0, 2, 3).reshape(
        num_groups, num_experts * capacity, dim
    )
    if j_for_slot is None:
        j_for_slot = invert_slots(slot, num_experts * capacity)
    return _combine_gather(eo_flat, gates, slot, j_for_slot)


def moe_dispatch(x, dispatch):
    """Token stream → per-expert buffers.

    x: (G, S, M); dispatch: (G, S, E, C) → (E, G, C, M).
    Under GSPMD (tokens g→dp-sharded, output e→ep-sharded) this einsum
    IS the all-to-all.
    """
    return jnp.einsum(
        "gsec,gsm->egcm", dispatch.astype(x.dtype), x
    )


def moe_combine(expert_out, combine):
    """Per-expert buffers → token stream (weighted by gate values).

    expert_out: (E, G, C, M); combine: (G, S, E, C) → (G, S, M).
    """
    return jnp.einsum(
        "gsec,egcm->gsm", combine.astype(expert_out.dtype), expert_out
    )
