"""Decoder-only transformer LM — the long-context model family.

No reference counterpart: the reference zoo is CTR/vision Keras models
(SURVEY.md §2.11) with no attention; this family exists to exercise the
TPU-first capabilities the rebuild adds — flash attention (Pallas),
tensor parallelism (GSPMD rules below), and sequence/context parallelism
(ring / all-to-all schedules over the ``sp`` mesh axis).

Design notes (TPU-first):
- pre-LayerNorm blocks, GELU MLP, rotary position embeddings — all
  position-wise ops GSPMD shards trivially over dp/sp.
- attention dispatches by config: single-device flash/XLA, or ring /
  ulysses shard_map schedules when the mesh has sp > 1.
- tensor parallelism is pure annotation: qkv/mlp-up kernels split their
  output dim over ``tp``, out-proj/mlp-down split their input dim, so
  XLA inserts one psum per block (Megatron layout, expressed as GSPMD
  rules instead of hand-written collectives).
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.ops.attention import dot_product_attention
from elasticdl_tpu.ops.ring_attention import (
    ring_attention,
    ulysses_attention,
)
from elasticdl_tpu.parallel.mesh import DATA_AXES
from elasticdl_tpu.parallel.sharding import ShardingRules
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer


def rotary_embedding(x, base=10000.0, seq_axis=2):
    """Apply RoPE; seq_axis=2 for (B, H, S, d), 1 for (B, S, H, d)."""
    seq, dim = x.shape[seq_axis], x.shape[-1]
    half = dim // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(seq, dtype=jnp.float32)[:, None] * freqs[None, :]
    shape = [1] * x.ndim
    shape[seq_axis], shape[-1] = seq, half
    cos = jnp.cos(angles).reshape(shape)
    sin = jnp.sin(angles).reshape(shape)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


class Attention(nn.Module):
    num_heads: int
    attention_impl: str = "auto"  # auto | xla | pallas | ring | ulysses
    mesh: Optional[Any] = None
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, training=False):
        dim = x.shape[-1]
        head_dim = dim // self.num_heads
        dense = lambda name: nn.DenseGeneral(
            (self.num_heads, head_dim),
            axis=-1,
            use_bias=False,
            name=name,
        )
        # (B, S, H, d) -> (B, H, S, d). A transpose-free path exists
        # (dot_product_attention(layout="bshd") — the flash kernel can
        # address heads as lane-aligned blocks of the fused minor dim)
        # but measured net-NEGATIVE on v5e (+1.4% device time at the
        # best-MFU config): XLA's transposes already run near the HBM
        # roofline, and removing them shifts cost into strided kernel
        # DMA and worse qkv-matmul layouts. docs/PERF_TRANSFORMER.md.
        to_bhsd = lambda t: t.transpose(0, 2, 1, 3)
        q = to_bhsd(dense("query")(x))
        k = to_bhsd(dense("key")(x))
        v = to_bhsd(dense("value")(x))
        q = rotary_embedding(q)
        k = rotary_embedding(k)

        if self.attention_impl == "ring":
            out = ring_attention(q, k, v, self.mesh, causal=True)
        elif self.attention_impl == "ulysses":
            out = ulysses_attention(q, k, v, self.mesh, causal=True)
        else:
            out = dot_product_attention(
                q, k, v, causal=True, impl=self.attention_impl
            )
        out = out.transpose(0, 2, 1, 3)  # back to (B, S, H, d)
        out = nn.DenseGeneral(
            dim, axis=(-2, -1), use_bias=False, name="out_proj"
        )(out)
        if self.dropout:
            out = nn.Dropout(
                self.dropout, deterministic=not training
            )(out)
        return out


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    attention_impl: str = "auto"
    mesh: Optional[Any] = None
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, training=False):
        dim = x.shape[-1]
        h = nn.LayerNorm(name="ln_attn")(x)
        x = x + Attention(
            self.num_heads,
            attention_impl=self.attention_impl,
            mesh=self.mesh,
            dropout=self.dropout,
            name="attn",
        )(h, training)
        h = nn.LayerNorm(name="ln_mlp")(x)
        h = nn.Dense(dim * self.mlp_ratio, use_bias=False, name="mlp_up")(h)
        h = nn.gelu(h)
        h = nn.Dense(dim, use_bias=False, name="mlp_down")(h)
        if self.dropout:
            h = nn.Dropout(self.dropout, deterministic=not training)(h)
        return x + h


class TransformerLM(nn.Module):
    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    embed_dim: int = 512
    mlp_ratio: int = 4
    dropout: float = 0.0
    attention_impl: str = "auto"
    mesh: Optional[Any] = None
    # per-block rematerialization: activations recomputed in the
    # backward pass instead of stored — the standard HBM-for-FLOPs trade
    # that makes long-sequence / deep configs fit (jax.checkpoint)
    remat: bool = False
    # remat policy: "full" recomputes everything (min memory, ~1/3 extra
    # FLOPs); "dots" saves matmul outputs and recomputes only elementwise
    # ops (LayerNorm/GELU/residual) — near-zero extra MXU work, which is
    # what keeps MFU high on memory-tight configs; "flash" saves only the
    # attention kernel's (o, lse) outputs — between the two: projections
    # recompute, the O(S^2) attention forward does not, for lengths where
    # "dots" exceeds HBM (docs/PERF_TRANSFORMER.md)
    remat_policy: str = "full"

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        x = nn.Embed(
            self.vocab_size, self.embed_dim, name="wte"
        )(tokens.astype(jnp.int32))
        if self.remat:
            import jax

            from elasticdl_tpu.ops.flash_attention import (
                FLASH_LSE_NAME,
                FLASH_OUT_NAME,
            )

            if self.remat_policy not in ("full", "dots", "flash"):
                raise ValueError(
                    "remat_policy must be 'full', 'dots' or 'flash', "
                    "got %r" % (self.remat_policy,)
                )
            # "dots" also saves the flash kernel's (o, lse) named
            # outputs: without them remat re-runs the forward flash
            # pass inside every block's backward (flash_attention.py
            # "custom_vjp wrapper" note). "flash" saves ONLY those
            # named outputs — the projections/mlp recompute like
            # "full", but the O(S^2) attention forward never re-runs —
            # the middle ground for lengths where "dots" exceeds HBM
            # (docs/PERF_TRANSFORMER.md, S=16k).
            if self.remat_policy == "dots":
                policy = jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names(
                        FLASH_OUT_NAME, FLASH_LSE_NAME
                    ),
                )
            elif self.remat_policy == "flash":
                # only the pallas flash kernel tags its outputs with
                # these checkpoint_names (flash_attention.py:522-523);
                # under any other attention impl the policy would match
                # nothing and silently degrade to "full" — reject the
                # contradiction instead. "auto" stays allowed: it
                # resolves to pallas on TPU (the regime this policy
                # exists for) and its CPU fallback to xla is the
                # documented degradation for tests.
                if self.attention_impl not in ("auto", "pallas"):
                    raise ValueError(
                        'remat_policy="flash" saves the pallas flash '
                        "kernel's named outputs; attention_impl=%r "
                        "never produces them (the policy would match "
                        "nothing and degrade to \"full\")"
                        % (self.attention_impl,)
                    )
                policy = jax.checkpoint_policies.save_only_these_names(
                    FLASH_OUT_NAME, FLASH_LSE_NAME
                )
            else:
                policy = None
            block_cls = nn.remat(Block, static_argnums=(2,), policy=policy)
        else:
            block_cls = Block
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads,
                mlp_ratio=self.mlp_ratio,
                attention_impl=self.attention_impl,
                mesh=self.mesh,
                dropout=self.dropout,
                name="block_%d" % i,
            )(x, training)
        x = nn.LayerNorm(name="ln_f")(x)
        return nn.Dense(self.vocab_size, use_bias=False, name="lm_head")(x)


# ---------------------------------------------------------------------------
# Sharding rules (tensor parallelism as pure annotation)
# ---------------------------------------------------------------------------


def transformer_sharding_rules():
    """Megatron-style TP layout + fsdp on everything big.

    qkv and mlp-up split output features over tp (their matmuls become
    local); out-proj and mlp-down split input features, after which XLA
    inserts a single psum per block. Embedding and lm_head split vocab.
    """
    return ShardingRules(
        rules=[
            (r"(query|key|value)/kernel$", P("fsdp", "tp", None)),
            (r"out_proj/kernel$", P("tp", None, "fsdp")),
            (r"mlp_up/kernel$", P("fsdp", "tp")),
            (r"mlp_down/kernel$", P("tp", "fsdp")),
            (r"wte/embedding$", P("tp", "fsdp")),
            (r"lm_head/kernel$", P("fsdp", "tp")),
            (r".*", P()),
        ],
        default_spec=P(),
    )


def batch_spec():
    """Tokens/labels (B, S): batch over data axes, sequence over sp."""
    return P(DATA_AXES, "sp")


# ---------------------------------------------------------------------------
# Model-zoo contract
# ---------------------------------------------------------------------------


def custom_model(mesh=None):
    return TransformerLM(
        vocab_size=32000,
        num_layers=12,
        num_heads=12,
        embed_dim=768,
        mesh=mesh,
    )


def loss(labels, predictions):
    # Next-token prediction: logits at t predict token at t+1. Returns a
    # per-sample vector (contract: trainer applies the batch mask).
    logits = predictions[:, :-1]
    targets = labels[:, 1:]
    per_token = sparse_softmax_cross_entropy(targets, logits)
    return per_token.mean(axis=-1)


def optimizer():
    return create_optimizer(
        "AdamW", learning_rate=3e-4, weight_decay=0.01
    )


def sharding_rules():
    return transformer_sharding_rules()


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        tokens = example["tokens"].astype(np.int32)
        # LM: the sequence is both input and label (shift happens in loss)
        return tokens, tokens

    return dataset.map(parse)


def eval_metrics_fn():
    return {"accuracy": metrics.Accuracy()}
