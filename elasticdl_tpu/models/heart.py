"""Cleveland heart-disease classifier over the feature-column stack.

Reference parity: model_zoo/heart_functional_api/heart_functional_api.py
— numeric columns, a bucketized age, a hashed+embedded ``thal``, a
DenseFeatures layer feeding a 16-16-1 sigmoid tower (:19-57), trained
with binary cross entropy.

TPU redesign follows census_wide_deep.py: categorical resolution
(hashing) runs per record in dataset_fn on the host; the flax model
sees numeric arrays + identity categorical ids, so the forward is one
jit-fused program. The final sigmoid moves into the loss (logits out,
numerically stabler; metrics take from_logits=True).
"""

import flax.linen as nn
import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.data.gen.converters import (
    HEART_CATEGORICAL,
    HEART_NUMERIC,
)
from elasticdl_tpu.preprocessing import Hashing
from elasticdl_tpu.preprocessing import feature_column as fc
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sigmoid_binary_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer

# reference heart_functional_api.py:28-30
AGE_BOUNDARIES = [18.0, 25.0, 30.0, 35.0, 40.0, 45.0, 50.0, 55.0, 60.0,
                  65.0]
THAL_BUCKETS = 100  # :34-36 hash_bucket_size=100
THAL_EMBED_DIM = 8

_thal_hash = Hashing(THAL_BUCKETS)


def build_columns():
    numeric = [
        fc.numeric_column(key)
        for key in ("trestbps", "chol", "thalach", "oldpeak", "slope",
                    "ca")
    ]
    age_buckets = fc.bucketized_column(
        fc.numeric_column("age"), AGE_BOUNDARIES
    )
    thal = fc.embedding_column(
        fc.categorical_column_with_identity("thal_id", THAL_BUCKETS),
        dimension=THAL_EMBED_DIM,
    )
    return tuple(numeric) + (fc.indicator_column(age_buckets), thal)


class HeartNet(nn.Module):
    hidden: tuple = (16, 16)  # reference :50-52

    def setup(self):
        self.features = fc.DenseFeatures(columns=build_columns())
        self.layers = [nn.Dense(w) for w in self.hidden]
        self.logit = nn.Dense(1)

    def __call__(self, features, training: bool = False):
        x = self.features(features)
        for layer in self.layers:
            x = nn.relu(layer(x))
        return self.logit(x).squeeze(-1)


def custom_model():
    return HeartNet()


def loss(labels, predictions):
    return sigmoid_binary_cross_entropy(labels, predictions)


def optimizer():
    # the reference ships SGD(1e-6) — far too cold to learn anything in
    # CI-sized runs over raw-scale clinical features; Adam at 1e-3
    return create_optimizer("Adam", learning_rate=0.001)


# raw clinical value ranges (UCI Cleveland); inputs are standardized to
# ~[-0.5, 0.5] in dataset_fn — raw chol runs to 564 and swamps a relu
# tower that also eats 0/1 indicator columns
_RANGES = {
    "age": (29.0, 77.0), "trestbps": (94.0, 200.0),
    "chol": (126.0, 564.0), "thalach": (71.0, 202.0),
    "oldpeak": (0.0, 6.2),
}


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        features = {}
        for key in HEART_NUMERIC:
            value = np.float32(example[key])
            if key in _RANGES and key != "age":
                lo, hi = _RANGES[key]
                value = np.float32((value - (lo + hi) / 2) / (hi - lo))
            features[key] = value.reshape(())
        for key in ("slope", "ca"):
            features[key] = np.float32(example[key]).reshape(())
        features["thal_id"] = _thal_hash(
            np.array([str(example["thal"])])
        ).reshape((1,))
        return features, np.float32(example["label"]).reshape(())

    return dataset.map(parse)


def eval_metrics_fn():
    return {
        "auc": metrics.AUC(from_logits=True),
        "accuracy": metrics.BinaryAccuracy(from_logits=True),
    }
