"""Census-income Wide&Deep over the feature-column stack.

Reference parity: model_zoo/census_wide_deep_model/
wide_deep_functional_api.py + feature_config.py (vocab lookups for
work-class/marital-status, hash buckets for education/occupation,
age/hours bucketization, one concatenated id group feeding a wide
indicator + deep embedding, staged LR schedule :75-84).

TPU redesign: string->id resolution (IndexLookup/Hashing — host-only
ops, XLA has no strings) happens per record in dataset_fn; the flax
model sees only numeric arrays and identity categorical columns, so the
whole forward is one jit-fused program. The LR schedule runs through
LearningRateScheduler over an inject_hyperparams optimizer — host-set
like the reference, no recompile.
"""

import flax.linen as nn
import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.preprocessing import Hashing, IndexLookup
from elasticdl_tpu.preprocessing import feature_column as fc
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.callbacks import LearningRateScheduler
from elasticdl_tpu.train.losses import sigmoid_binary_cross_entropy
from elasticdl_tpu.train.optimizers import (
    create_host_schedulable_optimizer,
)

from elasticdl_tpu.data.census_schema import (  # noqa: F401 (re-export)
    MARITAL_STATUS_VOCABULARY,
    WORK_CLASS_VOCABULARY,
)

AGE_BOUNDARIES = [18.0, 25.0, 30.0, 35.0, 40.0, 45.0, 50.0, 60.0, 70.0]
HOURS_BOUNDARIES = [20.0, 35.0, 40.0, 45.0, 55.0]
EDUCATION_BUCKETS = 30
OCCUPATION_BUCKETS = 50

_work_lookup = IndexLookup(WORK_CLASS_VOCABULARY, num_oov_tokens=1)
_marital_lookup = IndexLookup(MARITAL_STATUS_VOCABULARY, num_oov_tokens=1)
_education_hash = Hashing(EDUCATION_BUCKETS)
_occupation_hash = Hashing(OCCUPATION_BUCKETS)


def build_columns():
    age = fc.numeric_column("age")
    hours = fc.numeric_column("hours_per_week")
    age_buckets = fc.bucketized_column(age, AGE_BOUNDARIES)
    hours_buckets = fc.bucketized_column(hours, HOURS_BOUNDARIES)
    # ids were resolved in dataset_fn; identity columns bound them
    work_class = fc.categorical_column_with_identity(
        "work_class_id", _work_lookup.vocab_size()
    )
    marital = fc.categorical_column_with_identity(
        "marital_status_id", _marital_lookup.vocab_size()
    )
    education = fc.categorical_column_with_identity(
        "education_id", EDUCATION_BUCKETS
    )
    occupation = fc.categorical_column_with_identity(
        "occupation_id", OCCUPATION_BUCKETS
    )
    group = fc.concatenated_categorical_column(
        [
            age_buckets,
            hours_buckets,
            work_class,
            marital,
            education,
            occupation,
        ]
    )
    wide_columns = (fc.indicator_column(group),)
    deep_columns = (
        age,
        hours,
        fc.embedding_column(group, dimension=8, combiner="sum"),
    )
    return wide_columns, deep_columns


class CensusWideDeep(nn.Module):
    hidden: tuple = (64, 32)

    def setup(self):
        wide_cols, deep_cols = build_columns()
        self.wide_features = fc.DenseFeatures(columns=wide_cols)
        self.deep_features = fc.DenseFeatures(columns=deep_cols)
        self.deep_layers = [nn.Dense(w) for w in self.hidden]
        self.wide_logit = nn.Dense(1)
        self.deep_logit = nn.Dense(1)

    def __call__(self, features, training: bool = False):
        wide = self.wide_features(features)
        deep = self.deep_features(features)
        for layer in self.deep_layers:
            deep = nn.relu(layer(deep))
        logit = self.wide_logit(wide) + self.deep_logit(deep)
        return logit.squeeze(-1)


def custom_model():
    return CensusWideDeep()


def loss(labels, predictions):
    return sigmoid_binary_cross_entropy(labels, predictions)


def optimizer():
    return create_host_schedulable_optimizer("Adam", learning_rate=0.0003)


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)

        def s(key):
            value = example[key]
            return value if isinstance(value, str) else str(value)

        features = {
            "age": np.float32(example["age"]).reshape(()),
            "hours_per_week": np.float32(
                example["hours_per_week"]
            ).reshape(()),
            "work_class_id": _work_lookup(
                np.array([s("work_class")])
            ).reshape((1,)),
            "marital_status_id": _marital_lookup(
                np.array([s("marital_status")])
            ).reshape((1,)),
            "education_id": _education_hash(
                np.array([s("education")])
            ).reshape((1,)),
            "occupation_id": _occupation_hash(
                np.array([s("occupation")])
            ).reshape((1,)),
        }
        return features, np.float32(example["label"]).reshape(())

    return dataset.map(parse)


def eval_metrics_fn():
    return {
        "auc": metrics.AUC(from_logits=True),
        "accuracy": metrics.BinaryAccuracy(from_logits=True),
    }


def callbacks():
    # wide_deep_functional_api.py:75-84 staged LR schedule, applied
    # host-side between steps (no recompile).
    def _schedule(model_version):
        if model_version < 5000:
            return 0.0003
        elif model_version < 12000:
            return 0.0002
        return 0.0001

    return [LearningRateScheduler(_schedule)]
