"""DCN CTR model-zoo module (model_zoo/dac_ctr/dcn_model.py parity).

Thin wrapper over models/ctr.py pinning the variant; see that module for
the architecture and citations.
"""

from elasticdl_tpu.models.ctr import (  # noqa: F401
    dataset_fn,
    eval_metrics_fn,
    loss,
    optimizer,
)
from elasticdl_tpu.models import ctr as _ctr


def custom_model():
    return _ctr._VARIANTS["dcn"]()
