"""MobileNetV2 in flax (inverted residual bottlenecks).

Reference parity: model_zoo/cifar10/cifar10_mobilenetv2.py and the
ImageNet MobileNetV2 benchmarks (docs/benchmark/ftlib_benchmark.md:79-86,
139-156 — the reference's second headline model). Fresh TPU-first
implementation: NHWC, depthwise convs via feature_group_count (XLA's
native depthwise form), ReLU6, width multiples of 8, TpuBatchNorm
(f32 stats, compute-dtype stream — ops/batch_norm.py).

``small_inputs=True`` keeps the CIFAR stem at stride 1 (32x32 inputs
would otherwise collapse before the deep stages).
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.ops.batch_norm import TpuBatchNorm
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class InvertedResidual(nn.Module):
    filters: int
    strides: int = 1
    expand_ratio: int = 6

    @nn.compact
    def __call__(self, x, training: bool = False):
        norm = lambda: TpuBatchNorm(  # noqa: E731
            use_running_average=not training,
            momentum=0.9,
        )
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand_ratio
        residual = x
        if self.expand_ratio != 1:
            x = nn.Conv(hidden, (1, 1), use_bias=False)(x)
            x = nn.relu6(norm()(x))
        # depthwise: one group per channel — XLA lowers this to the
        # native depthwise conv on TPU
        x = nn.Conv(
            hidden,
            (3, 3),
            strides=(self.strides, self.strides),
            padding="SAME",
            feature_group_count=hidden,
            use_bias=False,
        )(x)
        x = nn.relu6(norm()(x))
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        x = norm()(x)
        if self.strides == 1 and in_ch == self.filters:
            x = x + residual
        return x


# (expand_ratio, filters, repeats, first_stride)
_V2_CONFIG = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


class MobileNetV2(nn.Module):
    num_classes: int = 1000
    width_multiplier: float = 1.0
    small_inputs: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        norm = lambda: TpuBatchNorm(  # noqa: E731
            use_running_average=not training,
            momentum=0.9,
        )
        stem = _make_divisible(32 * self.width_multiplier)
        stem_strides = (1, 1) if self.small_inputs else (2, 2)
        x = nn.Conv(
            stem, (3, 3), strides=stem_strides, padding="SAME",
            use_bias=False,
        )(x)
        x = nn.relu6(norm()(x))
        for i, (expand, filters, repeats, stride) in enumerate(_V2_CONFIG):
            filters = _make_divisible(filters * self.width_multiplier)
            for r in range(repeats):
                if self.small_inputs and i == 1 and r == 0:
                    stride_r = 1  # keep 32x32 resolution one stage longer
                else:
                    stride_r = stride if r == 0 else 1
                x = InvertedResidual(
                    filters, strides=stride_r, expand_ratio=expand
                )(x, training=training)
        head = _make_divisible(max(1280 * self.width_multiplier, 1280))
        x = nn.Conv(head, (1, 1), use_bias=False)(x)
        x = nn.relu6(norm()(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def mobilenetv2(num_classes=1000, **kwargs):
    return MobileNetV2(num_classes=num_classes, **kwargs)


def custom_model():
    return MobileNetV2(num_classes=10, small_inputs=True)


def loss(labels, predictions):
    return sparse_softmax_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer(
        "Momentum", learning_rate=0.02, momentum=0.9, nesterov=True
    )


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        image = example["image"].astype(np.float32) / 255.0
        if image.ndim == 2:
            image = np.stack([image] * 3, axis=-1)
        return image, example["label"].astype(np.int32).reshape(())

    return dataset.map(parse)


def eval_metrics_fn():
    return {"accuracy": metrics.Accuracy()}
