"""CIFAR-10 CNN (the reference's "functional API" baseline).

Reference parity: model_zoo/cifar10/cifar10_functional_api.py (VGG-style
conv stack with BN + dropout over 32x32x3) and cifar10/data_parser.py
(uint8 image / int label records). The resnet/mobilenet CIFAR variants
live in models/resnet.py (small_inputs=True) and models/mobilenet.py.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer


class Cifar10CNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, training: bool = False):
        norm = lambda: nn.BatchNorm(  # noqa: E731
            use_running_average=not training,
            momentum=0.9,
            dtype=jnp.float32,
        )
        for filters in (32, 64, 128):
            x = nn.Conv(filters, (3, 3), padding="SAME", use_bias=False)(x)
            x = nn.relu(norm()(x))
            x = nn.Conv(filters, (3, 3), padding="SAME", use_bias=False)(x)
            x = nn.relu(norm()(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = nn.Dropout(0.25, deterministic=not training)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512)(x))
        x = nn.Dropout(0.5, deterministic=not training)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def custom_model():
    return Cifar10CNN()


def loss(labels, predictions):
    return sparse_softmax_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer("Adam", learning_rate=0.001)


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        image = example["image"].astype(np.float32) / 255.0
        if image.ndim == 2:  # grayscale fixtures -> 3 channels
            image = np.stack([image] * 3, axis=-1)
        return image, example["label"].astype(np.int32).reshape(())

    return dataset.map(parse)


def eval_metrics_fn():
    return {"accuracy": metrics.Accuracy()}
