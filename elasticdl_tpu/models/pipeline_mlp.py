"""Pipeline-parallel MLP with tensor parallelism inside each stage.

No reference counterpart (SURVEY.md §2.12 lists pp as absent from the
reference); this is the minimal model exercising the pp x tp
composition: stages are Megatron-style column+row parallel MLP blocks —
W1 sharded on its output dim over ``tp``, W2 on its input dim, one
manual ``psum`` per block rejoining the activation — scheduled through
:func:`elasticdl_tpu.parallel.pipeline.pipeline_apply` (1f1b schedule,
optional interleaved chunks).

Model contract: plain class with ``init``/``apply`` (the stage loop
lives in a shard_map; see pipeline_transformer.py for the idiom).
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.parallel.collectives import mesh_psum
from elasticdl_tpu.parallel.pipeline import pipeline_apply
from elasticdl_tpu.parallel.sharding import ShardingRules
from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer


def _make_stage_fn(use_tp):
    """Column-parallel W1, row-parallel W2, one psum over tp."""

    def layer_fn(p, x):
        h = jnp.maximum(x @ p["W1"], 0.0)
        out = h @ p["W2"]
        if use_tp:
            # mesh_psum, not lax.psum: the 1f1b schedule differentiates
            # this stage fn INSIDE the shard_map body, where the pinned
            # jax's psum transpose doubles tp-sharded grads
            out = mesh_psum(out, "tp")
        return jnp.tanh(out + p["b"]) + x  # residual keeps depth trainable

    return layer_fn


class PipelinedMlpNet:
    """Residual MLP classifier, layers split into pipeline stages."""

    def __init__(self, num_classes=16, dim=32, hidden=64, num_layers=4,
                 num_stages=1, num_chunks=1, num_microbatches=2,
                 mesh=None):
        chunks = num_stages * num_chunks
        if num_layers % chunks != 0:
            raise ValueError(
                "num_layers=%d not divisible by stages*chunks=%d"
                % (num_layers, chunks)
            )
        self.num_classes = num_classes
        self.dim = dim
        self.hidden = hidden
        self.num_layers = num_layers
        self.num_stages = num_stages
        self.num_chunks = num_chunks
        self.num_microbatches = num_microbatches
        self.mesh = mesh

    def init(self, rng, features, training=False, rngs=None):
        del training, rngs
        keys = jax.random.split(rng, 3)
        scale_in = 1.0 / jnp.sqrt(self.dim)
        blocks = {
            "W1": jax.random.normal(
                keys[0], (self.num_layers, self.dim, self.hidden)
            ) * scale_in,
            "W2": jax.random.normal(
                keys[1], (self.num_layers, self.hidden, self.dim)
            ) / jnp.sqrt(self.hidden),
            "b": jnp.zeros((self.num_layers, self.dim)),
        }
        head = jax.random.normal(
            keys[2], (self.dim, self.num_classes)
        ) * scale_in
        return {"params": {"blocks": blocks, "head": head}}

    def apply(self, variables, features, training=False, rngs=None):
        del training, rngs
        params = variables["params"]
        x = jnp.asarray(features, jnp.float32)
        if x.shape[-1] != self.dim:
            raise ValueError(
                "features last dim %d != model dim %d"
                % (x.shape[-1], self.dim)
            )
        blocks = params["blocks"]
        if self.mesh is None:
            layer_fn = _make_stage_fn(use_tp=False)

            def layer(carry, p):
                return layer_fn(p, carry), None

            x, _ = jax.lax.scan(layer, x, blocks)
        else:
            chunks = self.num_stages * self.num_chunks
            per_chunk = self.num_layers // chunks
            staged = jax.tree_util.tree_map(
                lambda leaf: leaf.reshape(
                    (chunks, per_chunk) + leaf.shape[1:]
                ),
                blocks,
            )
            tp = self.mesh.shape.get("tp", 1)
            layer_fn = _make_stage_fn(use_tp=tp > 1)
            param_specs = {
                "W1": P("pp", None, None, "tp") if tp > 1 else P("pp"),
                "W2": P("pp", None, "tp", None) if tp > 1 else P("pp"),
                "b": P("pp"),
            }

            def stage(p, h):
                def layer(carry, lp):
                    return layer_fn(lp, carry), None

                h, _ = jax.lax.scan(layer, h, p)
                return h

            x = pipeline_apply(
                stage,
                staged,
                x,
                num_microbatches=self.num_microbatches,
                mesh=self.mesh,
                num_chunks=self.num_chunks,
                param_specs=param_specs,
            )
        return x @ params["head"]


def pipeline_mlp_sharding_rules():
    """State layout for the FLAT [num_layers, ...] block stack (the
    chunked rank-4 view exists only inside ``apply``)."""
    return ShardingRules(
        rules=[
            (r"blocks/W1$", P("pp", None, "tp")),
            (r"blocks/W2$", P("pp", "tp", None)),
            (r"blocks/b$", P("pp")),
            (r".*", P()),
        ],
        default_spec=P(),
    )


# -- model-zoo contract -----------------------------------------------------

def mesh_config(num_devices):
    from elasticdl_tpu.parallel.mesh import MeshConfig

    if num_devices % 4 == 0:
        return MeshConfig(dp=num_devices // 4, pp=2, tp=2)
    if num_devices % 2 == 0:
        return MeshConfig(dp=num_devices // 2, pp=2)
    return MeshConfig(dp=num_devices)


def custom_model(mesh=None):
    num_stages = max(mesh.shape.get("pp", 1), 1) if mesh is not None else 1
    return PipelinedMlpNet(num_stages=num_stages, mesh=mesh)


def loss(labels, logits):
    return sparse_softmax_cross_entropy(labels, logits)


def optimizer():
    return create_optimizer("Adam", learning_rate=0.01)


def sharding_rules():
    return pipeline_mlp_sharding_rules()


def eval_metrics_fn():
    from elasticdl_tpu.train import metrics

    return {"accuracy": metrics.Accuracy()}
