"""ResNet v1.5 family (ResNet18/34/50/101/152) in flax.

Reference parity: model_zoo/imagenet_resnet50/, model_zoo/cifar10/ and
model_zoo/resnet50_subclass/ (Keras applications-based). Fresh TPU-first
implementation: NHWC layout (TPU conv-native), TpuBatchNorm
(ops/batch_norm.py: f32 single-pass statistics, residual stream stays
in the compute dtype — a BN that forced f32 outputs would promote every
downstream conv to f32 and halve the MXU rate, measured 1.8x step-time
cost on v5e; the single-pass stats + fused-multiply-add normalize are
worth another ~8% of step time over flax's nn.BatchNorm, see
docs/PERF_RESNET.md);
zero-init on the last BN scale of each block (standard trick: the
residual branch starts as identity, which stabilizes large-batch
training), and channel counts that are multiples of 128 in the deep
stages so the MXU tiles cleanly.
"""

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.ops.batch_norm import TpuBatchNorm
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, training: bool = False):
        norm = partial(
            TpuBatchNorm,
            use_running_average=not training,
            momentum=0.9,
            epsilon=1e-5,
        )
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=[(1, 1), (1, 1)], use_bias=False,
        )(y)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape[-1] != self.filters * 4 or self.strides != 1:
            residual = nn.Conv(
                self.filters * 4,
                (1, 1),
                strides=(self.strides, self.strides),
                use_bias=False,
            )(x)
            residual = norm()(residual)
        return nn.relu(y + residual)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, training: bool = False):
        norm = partial(
            TpuBatchNorm,
            use_running_average=not training,
            momentum=0.9,
            epsilon=1e-5,
        )
        residual = x
        y = nn.Conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding=[(1, 1), (1, 1)], use_bias=False,
        )(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)], use_bias=False)(y)
        y = norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape[-1] != self.filters or self.strides != 1:
            residual = nn.Conv(
                self.filters,
                (1, 1),
                strides=(self.strides, self.strides),
                use_bias=False,
            )(x)
            residual = norm()(residual)
        return nn.relu(y + residual)


def space_to_depth(x, block=2):
    """[B, H, W, C] -> [B, H/b, W/b, C*b*b]: each output pixel packs a
    b x b spatial block into channels. Pure reshape/transpose — free on
    TPU relative to an HBM-bound stem conv."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, c * block * block)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: type = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    small_inputs: bool = False  # cifar-style stem (3x3, no maxpool)
    # "conv7": the classic 7x7/2 stem. "space_to_depth": MLPerf-style
    # conv0 — input packed 2x2 into channels, then a 4x4/1 conv on the
    # half-res grid; same receptive-field class (7x7 zero-padded to 8x8
    # factorizes exactly over 2x2 blocks), far better MXU utilization
    # than a stride-2 conv over 3 channels.
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x, training: bool = False):
        if self.stem not in ("conv7", "space_to_depth"):
            raise ValueError(
                "unknown stem %r (conv7 | space_to_depth)" % self.stem
            )
        if self.small_inputs and self.stem != "conv7":
            raise ValueError(
                "small_inputs uses the cifar 3x3 stem; stem=%r conflicts"
                % self.stem
            )
        if x.ndim == 3:
            x = x[..., None]
        if self.small_inputs:
            x = nn.Conv(
                self.num_filters, (3, 3), padding=[(1, 1), (1, 1)],
                use_bias=False,
            )(x)
        elif self.stem == "space_to_depth":
            x = space_to_depth(x, 2)
            x = nn.Conv(
                self.num_filters, (4, 4), padding="SAME", use_bias=False
            )(x)
        else:
            x = nn.Conv(
                self.num_filters, (7, 7), strides=(2, 2),
                padding=[(3, 3), (3, 3)], use_bias=False,
            )(x)
        x = TpuBatchNorm(
            use_running_average=not training,
            momentum=0.9,
            epsilon=1e-5,
        )(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(
                x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)]
            )
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**stage, strides=strides
                )(x, training=training)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def resnet18(num_classes=1000, **kwargs):
    return ResNet([2, 2, 2, 2], BasicBlock, num_classes, **kwargs)


def resnet34(num_classes=1000, **kwargs):
    return ResNet([3, 4, 6, 3], BasicBlock, num_classes, **kwargs)


def resnet50(num_classes=1000, **kwargs):
    return ResNet([3, 4, 6, 3], BottleneckBlock, num_classes, **kwargs)


def resnet101(num_classes=1000, **kwargs):
    return ResNet([3, 4, 23, 3], BottleneckBlock, num_classes, **kwargs)


def resnet152(num_classes=1000, **kwargs):
    return ResNet([3, 8, 36, 3], BottleneckBlock, num_classes, **kwargs)


# ---------------------------------------------------------------------
# model-zoo contract (imagenet_resnet50 equivalent)

NUM_CLASSES = 1000


def custom_model():
    return resnet50(num_classes=NUM_CLASSES)


def loss(labels, predictions):
    return sparse_softmax_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer(
        "Momentum", learning_rate=0.1, momentum=0.9, nesterov=True
    )


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        image = example["image"].astype(np.float32) / 255.0
        label = example["label"].astype(np.int32).reshape(())
        return image, label

    return dataset.map(parse)


def eval_metrics_fn():
    return {"accuracy": metrics.Accuracy()}
