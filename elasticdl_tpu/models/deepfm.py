"""DeepFM over host-PS embedding tables (CTR family).

Reference parity: model_zoo/deepfm_edl_embedding/deepfm_edl_embedding.py
(uses elasticdl.layers.Embedding against the PS) and the dac_ctr deepfm
variant. TPU redesign: ids are swapped for (rows, indices) before the
step (train/sparse.py), so the device-side model is pure dense math —
gather, FM interaction, MLP — all fusable by XLA.

Expected raw features: {"ids": int64 [B, F]} and labels {0,1}.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sigmoid_binary_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer
from elasticdl_tpu.train.sparse import SparseEmbeddingSpec, embedding_lookup

_logger = _logger_factory("elasticdl_tpu.models.deepfm")

EMBEDDING_DIM = 8


class DeepFM(nn.Module):
    embedding_dim: int = EMBEDDING_DIM
    hidden: tuple = (64, 32)

    @nn.compact
    def __call__(self, features, training: bool = False):
        # [B, F, d] second-order embeddings + [B, F->sum, 1] first-order
        emb = embedding_lookup(features, "deepfm_emb", combiner=None)
        linear = embedding_lookup(features, "deepfm_linear", combiner="sum")
        # FM second-order: 0.5 * ((sum v)^2 - sum v^2)
        summed = emb.sum(axis=1)
        fm = 0.5 * (jnp.square(summed) - jnp.square(emb).sum(axis=1))
        fm_term = fm.sum(axis=-1, keepdims=True)
        # deep tower over flattened field embeddings
        deep = emb.reshape((emb.shape[0], -1))
        for width in self.hidden:
            deep = nn.relu(nn.Dense(width)(deep))
        deep_term = nn.Dense(1)(deep)
        logit = linear.reshape((-1, 1)) + fm_term + deep_term
        return logit.squeeze(-1)


def custom_model():
    return DeepFM()


def loss(labels, predictions):
    return sigmoid_binary_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer("Adam", learning_rate=0.001)


# Deployable default shape: criteo-dac (reference model_zoo/dac_ctr/
# feature_config.py groups 39 raw columns). The models are field-count
# agnostic at apply time; this default sizes the id buffers.
NUM_FIELDS = 39
# Measured ceiling on the padded unique-id buffer for ZIPFIAN id
# streams (docs/PERF_SPARSE.md round-2 addendum): a CTR batch carries
# far fewer unique ids than batch*fields, and right-sizing the buffer
# was +22% steps/s on chip. This is an opt-in deployment tuning (the
# bench config uses it); the library default below stays the always-
# safe worst case so near-uniform id streams never hit the capacity
# ValueError out of the box.
MAX_ID_CAPACITY = 8192

# capacity-warning dedup (ISSUE 6 satellite): specs are constructed
# once per trainer, and a bench/worker process builds several trainers
# over its life — BENCH_r05's tail carried the identical line 3x. One
# line per distinct (capacity, batch, fields) shape per process says
# everything the repeat said.
_warned_capacities = set()


def sparse_embedding_specs(num_features=NUM_FIELDS, batch_size=64,
                           capacity=None):
    """Host-PS tables this model trains against (TPU-contract addition:
    the reference discovers elasticdl.layers.Embedding instances via
    model introspection, model_handler.py:98-102; here the module
    declares them). The capacity default is the always-safe worst case
    ``batch_size * num_features`` — any id stream fits. Zipfian CTR
    streams should opt into the measured perf cap (+22% steps/s on
    chip) via ``capacity=min(batch*fields, MAX_ID_CAPACITY)`` or
    EDL_SPARSE_ID_CAPACITY, as the bench config does; overflow raises
    a clear ValueError naming the knob (train/sparse.py)."""
    from elasticdl_tpu.common.env_utils import env_int

    if capacity is None:
        capacity = env_int(
            "EDL_SPARSE_ID_CAPACITY", batch_size * num_features
        )
    shape_key = (capacity, batch_size, num_features)
    if (
        capacity < batch_size * num_features
        and shape_key not in _warned_capacities
    ):
        _warned_capacities.add(shape_key)
        _logger.info(
            "deepfm id-buffer capacity %d < worst case %d (batch %d x "
            "%d fields): fine for Zipfian id streams; a near-uniform "
            "stream will raise a capacity ValueError naming this knob",
            capacity, batch_size * num_features, batch_size, num_features,
        )
    return [
        # Small second-order init: an id the optimizer barely touched
        # contributes ~nothing through the FM/deep towers instead of
        # init-scale noise. On held-out CTR data most ids are rare, so
        # eval AUC is dominated by exactly those rows — init 0.05 cost
        # ~0.08 AUC on the planted-signal eval vs 0.001 (measured via
        # the local-executor lane).
        SparseEmbeddingSpec(
            "deepfm_emb",
            EMBEDDING_DIM,
            feature_key="ids",
            capacity=capacity,
            init_scale=0.001,
        ),
        # Wide term starts at exactly no-op (standard wide&deep
        # practice): a zero row is the correct prior for an unseen id,
        # and the first gradient step writes the signal, not a
        # correction of random noise.
        SparseEmbeddingSpec(
            "deepfm_linear", 1, feature_key="ids", capacity=capacity,
            initializer="zeros",
        ),
    ]


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        return (
            {"ids": example["ids"].astype(np.int64)},
            example["label"].astype(np.float32).reshape(()),
        )

    return dataset.map(parse)


def eval_metrics_fn():
    return {
        "auc": metrics.AUC(from_logits=True),
        "accuracy": metrics.BinaryAccuracy(from_logits=True),
    }
