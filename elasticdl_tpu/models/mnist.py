"""MNIST CNN — the minimum end-to-end model (reference PR1 scope).

Reference parity: model_zoo/mnist/mnist_functional_api.py:21-103
(custom_model/loss/optimizer/dataset_fn/eval_metrics_fn contract). The
network here is a fresh flax design, not a translation: NHWC convs with
feature counts padded to MXU-friendly multiples, relu fused by XLA.
"""

import flax.linen as nn
import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer


class MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, training: bool = False):
        if x.ndim == 3:
            x = x[..., None]  # NHW -> NHWC
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.25, deterministic=not training)(x)
        return nn.Dense(self.num_classes)(x)


def custom_model():
    return MnistCNN()


def loss(labels, predictions):
    return sparse_softmax_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer("Adam", learning_rate=0.002)


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        image = example["image"].astype(np.float32) / 255.0
        label = example["label"].astype(np.int32).reshape(())
        return image, label

    return dataset.map(parse)


def eval_metrics_fn():
    return {"accuracy": metrics.Accuracy()}
