"""Iris DNN over CSV records.

Reference parity: model_zoo/odps_iris_dnn_model/odps_iris_dnn_model.py
(4-feature DNN, the canonical table-reader example). The reader side is
CSVDataReader (data/readers.py) standing in for the ODPS table reader;
records arrive as delimited text rows.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer


class IrisDNN(nn.Module):
    num_classes: int = 3

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.relu(nn.Dense(16)(x))
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def custom_model():
    return IrisDNN()


def loss(labels, predictions):
    return sparse_softmax_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer("Adam", learning_rate=0.01)


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(record):
        if isinstance(record, (bytes, bytearray, memoryview)):
            # record readers yield bytes-like objects (the mmap reader
            # yields zero-copy memoryviews)
            record = bytes(record).decode("utf-8")
        if isinstance(record, str):
            parts = record.strip().split(",")
        else:  # already a sequence of fields
            parts = list(record)
        features = np.array([float(v) for v in parts[:4]], np.float32)
        label = np.int32(float(parts[4])) if len(parts) > 4 else np.int32(0)
        return features, label.reshape(())

    return dataset.map(parse)


def eval_metrics_fn():
    return {"accuracy": metrics.Accuracy()}
