"""Criteo-style CTR family: Wide&Deep, DCN, xDeepFM.

Reference parity: model_zoo/dac_ctr/{wide_deep_model,dcn_model,
xdeepfm_model}.py — shared embedding backbone (utils.py
lookup_embedding_func sums per-field embeddings) with per-model
interaction heads: CrossNet for DCN (dcn_model.py:80-87), CIN for
xDeepFM (xdeepfm_model.py:92), linear+deep for Wide&Deep. The TPU
redesign keeps these tables device-resident (they're modest:
vocab x dim), expresses every interaction as batched matmuls for the
MXU, and leaves nothing to per-row dynamic ops.

Expected raw features: {"ids": int64 [B, F]} (one id per field, as the
tests' ctr fixture fabricates) and binary labels. Select the variant via
EDL_CTR_VARIANT or the per-variant model_zoo modules (wide_deep / dcn /
xdeepfm submodule attributes at the bottom).
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sigmoid_binary_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer

# Deployable defaults are criteo-dac scale (reference model_zoo/dac_ctr/
# feature_config.py: 39 raw columns hashed into a shared id space): the
# zoo module an operator launches is the shape the bench tunes. The
# models are field-count agnostic at apply time; vocab sizes the tables
# ([1M, 8] f32 = 32 MB, comfortably device-resident). Override per-job
# via custom_model(vocab=..., embed_dim=...) or EDL_CTR_VOCAB /
# EDL_CTR_EMBED_DIM.
VOCAB = 1_000_000
NUM_FIELDS = 39
EMBED_DIM = 8


class FieldEmbeddings(nn.Module):
    """[B, F] ids -> [B, F, d] one table per model (fields share the id
    space, as dac_ctr's concatenated group embeddings do)."""

    vocab: int = VOCAB
    dim: int = EMBED_DIM

    @nn.compact
    def __call__(self, ids):
        # small-normal init: logits start near 0 (BCE ~ln2), the
        # standard CTR-embedding scale (dim can be 1, where fan-based
        # scaling explodes)
        table = self.param(
            "embeddings",
            nn.initializers.truncated_normal(0.01),
            (self.vocab, self.dim),
        )
        return jnp.take(table, ids.astype(jnp.int32), axis=0)


class CrossNet(nn.Module):
    """DCN cross layers: x_{l+1} = x0 * (w_l . x_l) + b_l + x_l.

    Reference: deepctr CrossNet used at dcn_model.py:80; implemented
    natively — the per-layer op is a rank-1 update, one dot + one outer
    product, which XLA fuses into two MXU-friendly matmuls."""

    num_layers: int = 2

    @nn.compact
    def __call__(self, x0):
        x = x0
        for i in range(self.num_layers):
            w = self.param(
                "w%d" % i,
                nn.initializers.truncated_normal(0.02),
                (x0.shape[-1],),
            )
            b = self.param(
                "b%d" % i, nn.initializers.zeros, (x0.shape[-1],)
            )
            xw = jnp.einsum("bd,d->b", x, w)[:, None]  # [B,1]
            x = x0 * xw + b + x
        return x


class CIN(nn.Module):
    """Compressed Interaction Network (xDeepFM).

    Reference: deepctr CIN used at xdeepfm_model.py:92. Layer k:
    z^k = outer(x^k, x^0) along the embedding axis, compressed by a
    learned [Hk*F0 -> Hk+1] projection; sum-pool each layer's features.
    Expressed as einsums so the whole stack is batched matmuls."""

    layer_sizes: tuple = (16, 16)

    @nn.compact
    def __call__(self, x0):
        # x0: [B, F, D]
        batch, f0, dim = x0.shape
        x = x0
        pooled = []
        for k, size in enumerate(self.layer_sizes):
            # outer product over field axes, per embedding dim:
            # [B, Hk, F0, D]
            z = jnp.einsum("bhd,bfd->bhfd", x, x0)
            z = z.reshape(batch, x.shape[1] * f0, dim)
            w = self.param(
                "cin%d" % k,
                nn.initializers.truncated_normal(0.02),
                (x.shape[1] * f0, size),
            )
            x = nn.relu(jnp.einsum("bzd,zh->bhd", z, w))
            pooled.append(x.sum(axis=-1))  # [B, Hk]
        return jnp.concatenate(pooled, axis=-1)


class DNN(nn.Module):
    """model_zoo/dac_ctr/utils.py:44-67 DNN tower."""

    hidden: tuple = (64, 32)

    @nn.compact
    def __call__(self, x):
        for width in self.hidden:
            x = nn.relu(nn.Dense(width)(x))
        return x


class WideDeep(nn.Module):
    """wide = linear over per-field 1-d embeddings; deep = DNN over
    concatenated field embeddings (wide_deep_model.py)."""

    vocab: int = VOCAB
    embed_dim: int = EMBED_DIM

    @nn.compact
    def __call__(self, features, training: bool = False):
        ids = features["ids"]
        wide = FieldEmbeddings(
            vocab=self.vocab, dim=1, name="wide"
        )(ids)  # [B,F,1]
        deep_emb = FieldEmbeddings(
            vocab=self.vocab, dim=self.embed_dim, name="deep"
        )(ids)  # [B,F,D]
        deep = DNN()(deep_emb.reshape((ids.shape[0], -1)))
        logit = wide.sum(axis=(1, 2), keepdims=False)[:, None]
        logit = logit + nn.Dense(1)(deep)
        return logit.squeeze(-1)


class DCN(nn.Module):
    """CrossNet + DNN over the flattened embeddings, concat -> logit
    (dcn_model.py:53-88)."""

    vocab: int = VOCAB
    embed_dim: int = EMBED_DIM

    @nn.compact
    def __call__(self, features, training: bool = False):
        ids = features["ids"]
        emb = FieldEmbeddings(vocab=self.vocab, dim=self.embed_dim)(ids)
        flat = emb.reshape((ids.shape[0], -1))
        cross = CrossNet(num_layers=2)(flat)
        deep = DNN()(flat)
        both = jnp.concatenate([deep, cross], axis=1)
        return nn.Dense(1)(both).squeeze(-1)


class XDeepFM(nn.Module):
    """linear + CIN + DNN (xdeepfm_model.py:55-101)."""

    vocab: int = VOCAB
    embed_dim: int = EMBED_DIM

    @nn.compact
    def __call__(self, features, training: bool = False):
        ids = features["ids"]
        linear = FieldEmbeddings(
            vocab=self.vocab, dim=1, name="linear"
        )(ids)
        emb = FieldEmbeddings(
            vocab=self.vocab, dim=self.embed_dim, name="deep"
        )(ids)
        cin_out = CIN()(emb)
        deep = DNN()(emb.reshape((ids.shape[0], -1)))
        logit = (
            linear.sum(axis=(1, 2))[:, None]
            + nn.Dense(1)(cin_out)
            + nn.Dense(1)(deep)
        )
        return logit.squeeze(-1)


_VARIANTS = {"wide_deep": WideDeep, "dcn": DCN, "xdeepfm": XDeepFM}


def custom_model(variant="dcn", vocab=None, embed_dim=None):
    from elasticdl_tpu.common.env_utils import env_int, env_str

    variant = env_str("EDL_CTR_VARIANT", variant)
    vocab = env_int("EDL_CTR_VOCAB", vocab or VOCAB)
    embed_dim = env_int("EDL_CTR_EMBED_DIM", embed_dim or EMBED_DIM)
    return _VARIANTS[variant](vocab=vocab, embed_dim=embed_dim)


def loss(labels, predictions):
    return sigmoid_binary_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer("Adam", learning_rate=0.01)


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        return (
            {"ids": example["ids"].astype(np.int64)},
            example["label"].astype(np.float32).reshape(()),
        )

    return dataset.map(parse)


def eval_metrics_fn():
    return {
        "auc": metrics.AUC(from_logits=True),
        "accuracy": metrics.BinaryAccuracy(from_logits=True),
    }
