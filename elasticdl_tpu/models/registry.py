"""Model-zoo module contract loader.

Reference parity: elasticdl/python/common/model_utils.py:139-198 — a
model-zoo module exports up to 8 names; the loader resolves them with
defaults. The TPU contract keeps the same names with JAX-shaped types:

- ``custom_model()`` -> a flax Module whose ``__call__(features,
  training)`` maps a batch to outputs (the reference returns a Keras
  model)
- ``loss(labels, predictions)`` -> per-sample loss vector (jnp)
- ``optimizer()`` -> optax GradientTransformation
- ``dataset_fn(dataset, mode, metadata)`` -> maps a pipeline.Dataset of
  raw records to a Dataset of (features, label) examples
- ``eval_metrics_fn()`` -> {name: train.metrics.Metric}
- ``callbacks()`` -> list of callbacks (optional)
- ``PredictionOutputsProcessor`` -> class with process(outputs, worker_id)
  (optional)
- ``sharding_rules()`` -> parallel/ partition rules (optional; TPU-only
  addition, no reference counterpart)
"""

import importlib
import importlib.util
import os
import sys


class ModelSpec:
    def __init__(
        self,
        custom_model,
        loss,
        optimizer,
        dataset_fn,
        eval_metrics_fn=None,
        callbacks=None,
        prediction_outputs_processor=None,
        sharding_rules=None,
        sparse_embedding_specs=None,
        batch_spec=None,
        mesh_config=None,
        ps_optimizer=None,
        module=None,
    ):
        self.custom_model = custom_model
        self.loss = loss
        self.optimizer = optimizer
        self.dataset_fn = dataset_fn
        self.eval_metrics_fn = eval_metrics_fn or (lambda: {})
        self.callbacks = callbacks or (lambda: [])
        self.prediction_outputs_processor = prediction_outputs_processor
        self.sharding_rules = sharding_rules
        # () -> [SparseEmbeddingSpec]: host-PS tables the model trains
        # against (TPU contract addition; the reference discovers these by
        # introspecting for elasticdl.layers.Embedding instances)
        self.sparse_embedding_specs = sparse_embedding_specs
        # () -> PartitionSpec for batch leaves (TPU addition: models with
        # sequence parallelism shard dim 1 over sp)
        self.batch_spec = batch_spec
        # (num_devices) -> MeshConfig: the model's preferred mesh
        # topology (TPU addition: a tp/sp model picks its axis split)
        self.mesh_config = mesh_config
        # () -> (opt_type, "k=v;k=v") for the sparse host-PS optimizer
        # (the reference introspects the Keras optimizer instead,
        # common/model_utils.py:234-261 get_optimizer_info)
        self.ps_optimizer = ps_optimizer
        self.module = module


def load_module(module_path_or_name):
    """Import a model-zoo module by file path or dotted module name."""
    if os.path.exists(module_path_or_name):
        name = os.path.splitext(os.path.basename(module_path_or_name))[0]
        spec = importlib.util.spec_from_file_location(
            name, module_path_or_name
        )
        module = importlib.util.module_from_spec(spec)
        sys.modules[name] = module
        spec.loader.exec_module(module)
        return module
    return importlib.import_module(module_path_or_name)


def _resolve(module, name, default_name=None, required=True):
    target = getattr(module, name, None)
    if target is None and default_name:
        target = getattr(module, default_name, None)
    if target is None and required:
        raise ValueError(
            "Model module %s does not define required %r"
            % (module.__name__, name)
        )
    return target


def get_model_spec(
    module_path_or_name,
    model_def="",
    model_params="",
    symbol_overrides=None,
) -> ModelSpec:
    """Resolve the model-zoo contract.

    ``model_def`` (reference --model_def, model_utils.py:139-198 via
    get_module_file_path): when ``module_path_or_name`` is a DIRECTORY,
    a dotted path inside it selecting the module file — optionally with
    a trailing segment naming the model factory, e.g.
    ``mnist.mnist_functional_api`` or
    ``mnist.mnist_functional_api.custom_model``.

    ``model_params`` (reference --model_params, model_utils.py:79-94):
    a ``k=v;k=v`` string of kwargs bound onto ``custom_model`` — the
    reference calls ``custom_model(**model_params)``; here the binding
    is a functools.partial so every call site (worker, executor,
    handler) inherits it.

    ``symbol_overrides`` (reference --loss/--optimizer/--dataset_fn/
    --eval_metrics_fn/--callbacks/--prediction_outputs_processor,
    model_utils.py:139-150): {contract key: module attribute name} for
    modules whose exports use non-default names. An overridden name
    that the module does not define is an error even for otherwise
    optional contract parts — the user asked for it by name.
    """
    import functools

    factory_name = None
    target = module_path_or_name
    if model_def:
        if not os.path.isdir(module_path_or_name):
            raise ValueError(
                "--model_def requires --model_zoo to be a directory, "
                "got %r" % (module_path_or_name,)
            )
        parts = model_def.split(".")
        candidate = os.path.join(module_path_or_name, *parts) + ".py"
        if os.path.exists(candidate):
            target = candidate
        elif len(parts) >= 2:
            # last segment names the model factory inside the module
            target = (
                os.path.join(module_path_or_name, *parts[:-1]) + ".py"
            )
            if not os.path.exists(target):
                raise ValueError(
                    "--model_def %r resolves to neither %s nor %s under "
                    "%s" % (
                        model_def, candidate, target, module_path_or_name,
                    )
                )
            factory_name = parts[-1]
        else:
            # a single segment has no module to fall back to — joining
            # parts[:-1] (empty) would probe '<zoo>.py' OUTSIDE the zoo
            raise ValueError(
                "--model_def %r resolves to no module file (%s) under %s"
                % (model_def, candidate, module_path_or_name)
            )
    module = load_module(target)
    custom_model = _resolve(
        module, factory_name or "custom_model",
        None if factory_name else "model",
    )
    if model_params:
        from elasticdl_tpu.common.args import parse_params_string

        custom_model = functools.partial(
            custom_model, **parse_params_string(model_params)
        )
    overrides = symbol_overrides or {}

    def _contract(key, default_name, required=True):
        name = overrides.get(key) or default_name
        return _resolve(
            module, name, required=required or key in overrides
        )

    return ModelSpec(
        custom_model=custom_model,
        loss=_contract("loss", "loss"),
        optimizer=_contract("optimizer", "optimizer"),
        dataset_fn=_contract("dataset_fn", "dataset_fn"),
        eval_metrics_fn=_contract(
            "eval_metrics_fn", "eval_metrics_fn", required=False
        ),
        callbacks=_contract("callbacks", "callbacks", required=False),
        prediction_outputs_processor=_contract(
            "prediction_outputs_processor",
            "PredictionOutputsProcessor",
            required=False,
        ),
        sharding_rules=_resolve(module, "sharding_rules", required=False),
        sparse_embedding_specs=_resolve(
            module, "sparse_embedding_specs", required=False
        ),
        batch_spec=_resolve(module, "batch_spec", required=False),
        mesh_config=_resolve(module, "mesh_config", required=False),
        ps_optimizer=_resolve(module, "ps_optimizer", required=False),
        module=module,
    )
