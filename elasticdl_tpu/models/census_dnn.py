"""Census-income DNN over embedded categorical features.

Reference parity: model_zoo/census_dnn_model/ (census_feature_columns.py
+ census_functional_api.py / census_sequential.py / census_subclass.py
— all three build the same network: 4 numeric columns, 8 categorical
columns hashed into 64 buckets and embedded at dim 16, DenseFeatures
into a 16-16-1 sigmoid tower).

TPU redesign: hashing runs per record in dataset_fn (host-only string
op); the flax model consumes numeric arrays + identity categorical ids
so the forward is one jit-fused program. Logits out; sigmoid lives in
the loss.
"""

import flax.linen as nn
import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.preprocessing import Hashing
from elasticdl_tpu.preprocessing import feature_column as fc
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sigmoid_binary_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer

# reference census_feature_columns.py:18-33 (our census RecordIO schema
# uses underscores in place of the dashes of the raw CSV headers)
CATEGORICAL_KEYS = [
    "work_class",
    "education",
    "marital_status",
    "occupation",
    "relationship",
    "race",
    "sex",
    "native_country",
]
NUMERIC_KEYS = ["age", "capital_gain", "capital_loss", "hours_per_week"]
HASH_BUCKETS = 64  # :47
EMBED_DIM = 16  # :49

_hashers = {key: Hashing(HASH_BUCKETS) for key in CATEGORICAL_KEYS}


def build_columns():
    columns = [fc.numeric_column(key) for key in NUMERIC_KEYS]
    for key in CATEGORICAL_KEYS:
        columns.append(
            fc.embedding_column(
                fc.categorical_column_with_identity(
                    key + "_id", HASH_BUCKETS
                ),
                dimension=EMBED_DIM,
            )
        )
    return tuple(columns)


class CensusDnn(nn.Module):
    hidden: tuple = (16, 16)  # census_functional_api.py:26-27

    def setup(self):
        self.features = fc.DenseFeatures(columns=build_columns())
        self.layers = [nn.Dense(w) for w in self.hidden]
        self.logit = nn.Dense(1)

    def __call__(self, features, training: bool = False):
        x = self.features(features)
        for layer in self.layers:
            x = nn.relu(layer(x))
        return self.logit(x).squeeze(-1)


def custom_model():
    return CensusDnn()


def loss(labels, predictions):
    return sigmoid_binary_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer("Adam", learning_rate=0.001)


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        features = {
            key: np.float32(example[key]).reshape(())
            for key in NUMERIC_KEYS
        }
        for key in CATEGORICAL_KEYS:
            value = example.get(key, "")
            features[key + "_id"] = _hashers[key](
                np.array([str(value)])
            ).reshape((1,))
        return features, np.float32(example["label"]).reshape(())

    return dataset.map(parse)


def eval_metrics_fn():
    return {
        "auc": metrics.AUC(from_logits=True),
        "accuracy": metrics.BinaryAccuracy(from_logits=True),
    }
