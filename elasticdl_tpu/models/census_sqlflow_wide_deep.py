"""Census Wide&Deep generated from a declarative transform spec.

Reference parity: model_zoo/census_model_sqlflow/wide_and_deep/ — the
SQLFlow ``COLUMN`` clause compiles into a transform graph
(feature_configs.py: Vocabularize/Hash/Bucketize ops, three Concat id
groups with cumulative id offsets, wide dim-1 + deep dim-8 embeddings
per group) that the model interprets (transform_ops.py,
wide_deep_functional_keras.py).

TPU redesign keeps the declarative shape — ``TRANSFORMS`` below is the
data a SQLFlow codegen would emit — and interprets it in two stages:
string ops (vocab/hash) per record in dataset_fn on the host, numeric
ops (bucketize, group concat via id offsets, embeddings) as feature
columns inside the jitted forward. Group extents and embedding dims
match feature_configs.py:76-205 exactly.
"""

import flax.linen as nn
import numpy as np

from elasticdl_tpu.data.census_schema import (
    MARITAL_STATUS_VOCABULARY,
    WORK_CLASS_VOCABULARY,
)
from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.preprocessing import Hashing, IndexLookup
from elasticdl_tpu.preprocessing import feature_column as fc
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sigmoid_binary_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer

RELATIONSHIP_VOCABULARY = [
    "Wife", "Own-child", "Husband", "Not-in-family", "Other-relative",
    "Unmarried",
]
RACE_VOCABULARY = [
    "White", "Asian-Pac-Islander", "Amer-Indian-Eskimo", "Other", "Black",
]
SEX_VOCABULARY = ["Female", "Male"]
AGE_BOUNDARIES = [0.0, 20.0, 40.0, 60.0, 80.0]
CAPITAL_GAIN_BOUNDARIES = [6000.0, 6500.0, 7000.0, 7500.0, 8000.0]
CAPITAL_LOSS_BOUNDARIES = [2000.0, 2500.0, 3000.0, 3500.0, 4000.0]
HOURS_BOUNDARIES = [10.0, 20.0, 30.0, 40.0, 50.0, 60.0]

# The SQLFlow COLUMN clause, compiled: (output, op, input, param).
# vocab/hash rows run on the host per record; bucketize rows become
# feature columns. Cardinalities feed the Concat id offsets below.
TRANSFORMS = [
    ("workclass_lookup", "vocab", "work_class", WORK_CLASS_VOCABULARY),
    ("marital_status_lookup", "vocab", "marital_status",
     MARITAL_STATUS_VOCABULARY),
    ("relationship_lookup", "vocab", "relationship",
     RELATIONSHIP_VOCABULARY),
    ("race_lookup", "vocab", "race", RACE_VOCABULARY),
    ("sex_lookup", "vocab", "sex", SEX_VOCABULARY),
    ("education_hash", "hash", "education", 30),
    ("occupation_hash", "hash", "occupation", 30),
    ("native_country_hash", "hash", "native_country", 100),
    ("age_bucketize", "bucketize", "age", AGE_BOUNDARIES),
    ("capital_gain_bucketize", "bucketize", "capital_gain",
     CAPITAL_GAIN_BOUNDARIES),
    ("capital_loss_bucketize", "bucketize", "capital_loss",
     CAPITAL_LOSS_BOUNDARIES),
    ("hours_per_week_bucketize", "bucketize", "hours_per_week",
     HOURS_BOUNDARIES),
]

# feature_configs.py:141-168: three Concat groups over transform outputs
GROUPS = {
    "group1": ["workclass_lookup", "hours_per_week_bucketize",
               "capital_gain_bucketize", "capital_loss_bucketize"],
    "group2": ["education_hash", "marital_status_lookup",
               "relationship_lookup", "occupation_hash"],
    "group3": ["age_bucketize", "sex_lookup", "race_lookup",
               "native_country_hash"],
}
WIDE_GROUPS = ["group1", "group2"]  # dim-1 embeddings (:170-183)
DEEP_GROUPS = ["group1", "group2", "group3"]  # dim-8 (:185-205)
DEEP_DIM = 8


def _cardinality(name):
    for out, op, _, param in TRANSFORMS:
        if out != name:
            continue
        if op == "vocab":
            return len(param) + 1  # +1 OOV slot (IndexLookup)
        if op == "hash":
            return param
        if op == "bucketize":
            return len(param) + 1
    raise KeyError(name)


_host_ops = {}
for _out, _op, _src, _param in TRANSFORMS:
    if _op == "vocab":
        _host_ops[_out] = (_src, IndexLookup(_param, num_oov_tokens=1))
    elif _op == "hash":
        _host_ops[_out] = (_src, Hashing(_param))


def build_columns():
    wide_cols, deep_cols = [], []
    for group_name in sorted(GROUPS):
        parts = []
        for member in GROUPS[group_name]:
            op = next(t[1] for t in TRANSFORMS if t[0] == member)
            if op == "bucketize":
                src = next(t[2] for t in TRANSFORMS if t[0] == member)
                bounds = next(t[3] for t in TRANSFORMS if t[0] == member)
                parts.append(fc.bucketized_column(
                    fc.numeric_column(src), list(bounds)
                ))
            else:
                parts.append(fc.categorical_column_with_identity(
                    member, _cardinality(member)
                ))
        group = fc.concatenated_categorical_column(parts)
        if group_name in WIDE_GROUPS:
            wide_cols.append(
                fc.embedding_column(group, dimension=1, combiner="sum")
            )
        if group_name in DEEP_GROUPS:
            deep_cols.append(
                fc.embedding_column(
                    group, dimension=DEEP_DIM, combiner="sum"
                )
            )
    return tuple(wide_cols), tuple(deep_cols)


class SqlflowWideDeep(nn.Module):
    hidden: tuple = (16, 8)  # wide_deep_functional_keras.py:60-80

    def setup(self):
        wide_cols, deep_cols = build_columns()
        self.wide_features = fc.DenseFeatures(columns=wide_cols)
        self.deep_features = fc.DenseFeatures(columns=deep_cols)
        self.deep_layers = [nn.Dense(w) for w in self.hidden]
        self.logit = nn.Dense(1)

    def __call__(self, features, training: bool = False):
        wide = self.wide_features(features)
        deep = self.deep_features(features)
        for layer in self.deep_layers:
            deep = nn.relu(layer(deep))
        logit = jnp_sum_keepdim(wide) + self.logit(deep)
        return logit.squeeze(-1)


def jnp_sum_keepdim(x):
    return x.sum(axis=-1, keepdims=True)


def custom_model():
    return SqlflowWideDeep()


def loss(labels, predictions):
    return sigmoid_binary_cross_entropy(labels, predictions)


def optimizer():
    return create_optimizer("Adam", learning_rate=0.001)


def dataset_fn(dataset, mode=None, metadata=None):
    numeric = [
        t[2] for t in TRANSFORMS if t[1] == "bucketize"
    ]

    def parse(payload):
        example = decode_example(payload)
        features = {
            key: np.float32(example.get(key, 0.0)).reshape(())
            for key in numeric
        }
        for out, (src, op) in _host_ops.items():
            value = str(example.get(src, ""))
            features[out] = op(np.array([value])).reshape((1,))
        return features, np.float32(example["label"]).reshape(())

    return dataset.map(parse)


def eval_metrics_fn():
    return {
        "auc": metrics.AUC(from_logits=True),
        "accuracy": metrics.BinaryAccuracy(from_logits=True),
    }
