"""Mixture-of-experts transformer LM — the expert-parallel model family.

No reference counterpart (SURVEY.md §2.12: EP absent from the reference);
this family exercises the ``ep`` mesh axis. Every other block swaps the
dense MLP for a top-k-routed expert MLP (ops/moe.py): expert weight
tensors carry a leading expert dim sharded over ``ep``, the dispatch/
combine einsums become all-to-alls under GSPMD, and within each expert
the FFN is still tensor-parallel over ``tp``.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.models.transformer import Attention, Block
from elasticdl_tpu.ops.moe import (
    expert_capacity,
    invert_slots,
    moe_combine,
    moe_combine_compact,
    moe_dispatch,
    moe_dispatch_compact,
    top_k_routing,
    top_k_routing_compact,
)
from elasticdl_tpu.parallel.mesh import DATA_AXES
from elasticdl_tpu.parallel.sharding import ShardingRules
from elasticdl_tpu.train import metrics
from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
from elasticdl_tpu.train.optimizers import create_optimizer


def _constrain(x, mesh, spec):
    """Sharding hint, skipped when no mesh is in play (single device)."""
    if mesh is None or mesh.empty:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec)
    )


class MoeMlp(nn.Module):
    """Top-k routed expert FFN (GShard dispatch, Switch aux loss).

    Two dispatch implementations with identical semantics
    (``tests/test_moe.py::test_compact_dispatch_matches_onehot``):

    - ``"onehot"`` (= ``"auto"``, the measured default) — GShard
      dispatch/combine einsums. The one-hot contraction is MXU work,
      so it scales with batch (59.8% MFU at the docs/PERF_MOE.md
      B=16 config), and under GSPMD with tokens dp-sharded and
      experts ep-sharded these einsums ARE the dp→ep all-to-alls.
    - ``"compact"`` — slot-index gathers with gather-only custom
      backwards (ops/moe.py). No (G, S, E, C) one-hots and ~10% fewer
      executed FLOPs, but XLA lowers TPU row-gathers at ~200 GB/s, so
      it measured SLOWER end-to-end than the einsums at every batch
      tried — kept as an explicit option and a measured negative
      (docs/PERF_MOE.md round 5); a Pallas gather kernel is the known
      path to make it win.
    """

    num_experts: int
    mlp_ratio: int = 4
    top_k: int = 2
    capacity_factor: float = 1.25
    dispatch_impl: str = "auto"
    mesh: Optional[Any] = None

    def _use_compact(self):
        return self.dispatch_impl == "compact"

    @nn.compact
    def __call__(self, x):
        groups, seq, dim = x.shape
        ff = dim * self.mlp_ratio
        capacity = expert_capacity(
            seq, self.num_experts, self.top_k, self.capacity_factor
        )
        router_logits = nn.Dense(
            self.num_experts, use_bias=False, name="router"
        )(x)
        compact = self._use_compact()
        if compact:
            gates, slot, aux_loss = top_k_routing_compact(
                router_logits, self.top_k, capacity
            )
            # one inversion scatter shared by dispatch AND combine
            j_for_slot = invert_slots(
                slot, self.num_experts * capacity
            )
            expert_in = moe_dispatch_compact(
                x, slot, self.num_experts, capacity,
                j_for_slot=j_for_slot,
            )
        else:
            combine, dispatch, aux_loss = top_k_routing(
                router_logits, self.top_k, capacity
            )
            # (E, G, C, M): the dispatch einsum is the dp→ep all-to-all.
            expert_in = moe_dispatch(x, dispatch)
        expert_in = _constrain(
            expert_in, self.mesh, P("ep", DATA_AXES, None, None)
        )
        w_up = self.param(
            "w_up",
            nn.initializers.lecun_normal(),
            (self.num_experts, dim, ff),
        )
        w_down = self.param(
            "w_down",
            nn.initializers.lecun_normal(),
            (self.num_experts, ff, dim),
        )
        h = jnp.einsum("egcm,emf->egcf", expert_in, w_up.astype(x.dtype))
        h = nn.gelu(h)
        out = jnp.einsum("egcf,efm->egcm", h, w_down.astype(x.dtype))
        out = _constrain(
            out, self.mesh, P("ep", DATA_AXES, None, None)
        )
        if compact:
            y = moe_combine_compact(
                out, slot, gates, j_for_slot=j_for_slot
            )
        else:
            y = moe_combine(out, combine)  # ep→dp all-to-all back
        return y, aux_loss


class MoeBlock(nn.Module):
    num_heads: int
    num_experts: int
    mlp_ratio: int = 4
    top_k: int = 2
    capacity_factor: float = 1.25
    attention_impl: str = "auto"
    dispatch_impl: str = "auto"
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x, training=False):
        h = nn.LayerNorm(name="ln_attn")(x)
        x = x + Attention(
            self.num_heads,
            attention_impl=self.attention_impl,
            mesh=self.mesh,
            name="attn",
        )(h, training)
        h = nn.LayerNorm(name="ln_mlp")(x)
        y, aux_loss = MoeMlp(
            self.num_experts,
            mlp_ratio=self.mlp_ratio,
            top_k=self.top_k,
            capacity_factor=self.capacity_factor,
            dispatch_impl=self.dispatch_impl,
            mesh=self.mesh,
            name="moe_mlp",
        )(h)
        return x + y, aux_loss


class MoeTransformerLM(nn.Module):
    """Decoder-only LM with MoE FFNs in every other block.

    Training call returns ``{"logits", "aux_loss"}`` (the router
    load-balance penalty must reach the loss); eval returns bare logits
    so metrics and export see the same surface as the dense LM.
    """

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    embed_dim: int = 512
    mlp_ratio: int = 4
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    attention_impl: str = "auto"
    dispatch_impl: str = "auto"
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        x = nn.Embed(
            self.vocab_size, self.embed_dim, name="wte"
        )(tokens.astype(jnp.int32))
        aux_total = jnp.float32(0.0)
        for i in range(self.num_layers):
            if i % 2 == 1:
                x, aux = MoeBlock(
                    self.num_heads,
                    self.num_experts,
                    mlp_ratio=self.mlp_ratio,
                    top_k=self.top_k,
                    capacity_factor=self.capacity_factor,
                    attention_impl=self.attention_impl,
                    dispatch_impl=self.dispatch_impl,
                    mesh=self.mesh,
                    name="block_%d" % i,
                )(x, training)
                aux_total = aux_total + aux
            else:
                x = Block(
                    self.num_heads,
                    mlp_ratio=self.mlp_ratio,
                    attention_impl=self.attention_impl,
                    mesh=self.mesh,
                    name="block_%d" % i,
                )(x, training)
        x = nn.LayerNorm(name="ln_f")(x)
        logits = nn.Dense(
            self.vocab_size, use_bias=False, name="lm_head"
        )(x)
        if training:
            return {
                "logits": logits,
                "aux_loss": self.aux_loss_weight * aux_total,
            }
        return logits


# ---------------------------------------------------------------------------
# Sharding rules: transformer TP rules + expert-dim ep sharding
# ---------------------------------------------------------------------------


def moe_sharding_rules():
    """Dense-block rules plus expert weights over (ep, fsdp/tp).

    w_up (E, M, F): experts over ep, FFN dim over tp (Megatron within
    the expert); w_down (E, F, M) transposed to match. The router stays
    replicated — it is tiny and on the critical path of every token.
    """
    return ShardingRules(
        rules=[
            (r"router/kernel$", P()),
            (r"w_up$", P("ep", "fsdp", "tp")),
            (r"w_down$", P("ep", "tp", "fsdp")),
            (r"(query|key|value)/kernel$", P("fsdp", "tp", None)),
            (r"out_proj/kernel$", P("tp", None, "fsdp")),
            (r"mlp_up/kernel$", P("fsdp", "tp")),
            (r"mlp_down/kernel$", P("tp", "fsdp")),
            (r"wte/embedding$", P("tp", "fsdp")),
            (r"lm_head/kernel$", P("fsdp", "tp")),
            (r".*", P()),
        ],
        default_spec=P(),
    )


def batch_spec():
    return P(DATA_AXES, "sp")


# ---------------------------------------------------------------------------
# Model-zoo contract
# ---------------------------------------------------------------------------


def custom_model(mesh=None):
    return MoeTransformerLM(
        vocab_size=32000,
        num_layers=12,
        num_heads=12,
        embed_dim=768,
        num_experts=8,
        mesh=mesh,
    )


def loss(labels, predictions):
    if isinstance(predictions, dict):
        logits = predictions["logits"]
        aux = predictions["aux_loss"]
    else:
        logits, aux = predictions, 0.0
    per_token = sparse_softmax_cross_entropy(
        labels[:, 1:], logits[:, :-1]
    )
    # aux is a scalar: adding it to every per-sample loss leaves the
    # masked mean shifted by exactly aux.
    return per_token.mean(axis=-1) + aux


def optimizer():
    return create_optimizer("AdamW", learning_rate=3e-4, weight_decay=0.01)


def sharding_rules():
    return moe_sharding_rules()


def dataset_fn(dataset, mode=None, metadata=None):
    def parse(payload):
        example = decode_example(payload)
        tokens = example["tokens"].astype(np.int32)
        return tokens, tokens

    return dataset.map(parse)


def eval_metrics_fn():
    return {"accuracy": metrics.Accuracy()}
