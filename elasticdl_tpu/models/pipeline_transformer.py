"""Pipeline-parallel transformer LM.

No reference counterpart (the reference has no layer pipelining —
SURVEY.md §2.12 lists PP as absent); this family exercises the ``pp``
mesh axis: transformer blocks are pipeline *stages* whose stacked
parameters shard ``P("pp")`` over the mesh, and the forward runs the
GPipe microbatch schedule in :mod:`elasticdl_tpu.parallel.pipeline`.

Parameter layout is topology-independent by default: blocks are stored
as one flat ``(num_layers, ...)`` stack regardless of the mesh, and
``apply`` reshapes to ``(num_stages, layers_per_stage, ...)`` inside
the jitted step. A checkpoint written on a pp=4 mesh therefore restores
bit-for-bit onto pp=2 or a single chip (the elastic-resume contract the
dense checkpoint path promises). EXCEPTION: ``device_major_params=True``
(the interleaved-schedule perf opt-in, docs/PERF_PIPELINE.md) stores
the stack in device-placement order pinned to the current
``(num_stages, num_chunks)``; such state lives under the pytree key
``blocks_device_major`` instead of ``blocks``, so restoring it into a
job with the other layout setting fails LOUDLY on pytree structure
instead of silently scrambling layers — convert with
``blocks_to_portable``/``blocks_from_portable`` when moving topology.

The model is a plain (non-flax) class implementing the framework's model
contract — ``init(rng, features) -> variables`` / ``apply(variables,
features, training=, rngs=)`` — because the stage loop lives in a
``shard_map`` that flax's module system has no idiom for; the embed /
final-norm / head pieces and the per-stage Block remain ordinary flax
modules so their params initialize identically to TransformerLM's.

Dropout is intentionally unsupported here (stage rng plumbing through the
pipeline schedule isn't worth the complexity; the reference's models
don't regularize via dropout either).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.models import transformer
from elasticdl_tpu.parallel.pipeline import (
    device_major_order,
    pipeline_apply,
    stack_stage_params,
)
from elasticdl_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P


class PipelinedTransformerLM:
    """Decoder-only LM with blocks partitioned into pipeline stages.

    ``num_layers`` total blocks are split evenly across ``num_stages``
    pipeline stages; ``num_stages`` must equal the mesh's ``pp`` extent
    (or 1 when no mesh is given — pure sequential fallback for
    single-chip runs) and must divide ``num_layers`` exactly — the model
    never silently changes depth to fit a mesh.
    """

    def __init__(
        self,
        vocab_size=32000,
        num_layers=4,
        num_stages=4,
        num_heads=8,
        embed_dim=512,
        mlp_ratio=4,
        num_microbatches=4,
        attention_impl="auto",
        mesh=None,
        num_chunks=1,
        device_major_params=False,
    ):
        if num_layers % (num_stages * num_chunks) != 0:
            raise ValueError(
                "num_layers=%d is not divisible by num_stages*num_chunks"
                "=%d; refusing to silently change model depth"
                % (num_layers, num_stages * num_chunks)
            )
        if device_major_params and (num_chunks == 1 or mesh is None):
            raise ValueError(
                "device_major_params only applies to interleaved "
                "pipelines (num_chunks > 1 with a mesh) — at V=1 the "
                "portable layout is already device-contiguous"
            )
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_stages = num_stages
        # interleaved virtual chunks per device (Megatron interleaved
        # schedule; parallel/pipeline.py) — divides the bubble by V
        self.num_chunks = num_chunks
        # device-major at-rest layer order: removes the interleaved
        # schedule's per-step cross-shard permutation of the stage
        # stack (parallel/pipeline.py params_layout note) at the price
        # of an (S, V)-pinned checkpoint layout — convert with
        # blocks_to_portable/blocks_from_portable at topology changes
        self.device_major_params = device_major_params
        self.num_microbatches = num_microbatches
        self.mesh = mesh
        self.embed_dim = embed_dim
        self._wte = nn.Embed(vocab_size, embed_dim, name="wte")
        self._ln_f = nn.LayerNorm(name="ln_f")
        self._head = nn.Dense(vocab_size, use_bias=False, name="lm_head")
        self._block = transformer.Block(
            num_heads,
            mlp_ratio=mlp_ratio,
            attention_impl=attention_impl,
            mesh=mesh,
        )

    # -- model contract ------------------------------------------------
    def init(self, rng, tokens, training=False, rngs=None):
        del training, rngs
        keys = jax.random.split(rng, self.num_layers + 3)
        wte = self._wte.init(keys[0], jnp.asarray(tokens, jnp.int32))
        x = self._wte.apply(wte, jnp.asarray(tokens, jnp.int32))
        block_params = []
        for i in range(self.num_layers):
            variables = self._block.init(keys[1 + i], x, training=False)
            block_params.append(variables["params"])
        # Flat (num_layers, ...) stack — independent of num_stages, so
        # checkpoints restore across any pp extent. With
        # device_major_params the flat order is instead the device-
        # placement order for (num_stages, num_chunks) — see
        # _layer_order.
        order = self._layer_order()
        if order is not None:
            block_params = [block_params[i] for i in order]
        stacked = stack_stage_params(block_params)
        ln_f = self._ln_f.init(keys[-2], x)
        head = self._head.init(keys[-1], x)
        return {
            "params": {
                "wte": wte["params"],
                # layout-specific key: a device-major checkpoint can
                # never be restored into a portable-layout job (or vice
                # versa) without a loud pytree-structure mismatch
                self.blocks_key: stacked,
                "ln_f": ln_f["params"],
                "lm_head": head["params"],
            }
        }

    @property
    def blocks_key(self):
        return (
            "blocks_device_major" if self.device_major_params else "blocks"
        )

    def _layer_order(self):
        """Flat layer order at rest: None = layer order (portable);
        device_major_params = layers grouped so the contiguous P("pp")
        split hands each device its interleaved chunks with no per-step
        permutation (flat position p*per_chunk + k holds layer
        order_dm[p]*per_chunk + k)."""
        if not self.device_major_params:
            return None
        per_chunk = self.num_layers // (self.num_stages * self.num_chunks)
        order = []
        for chunk in device_major_order(self.num_stages, self.num_chunks):
            order.extend(
                range(chunk * per_chunk, (chunk + 1) * per_chunk)
            )
        return order

    def blocks_to_portable(self, blocks):
        """Reorder device-major-at-rest block leaves back to flat layer
        order (the topology-portable checkpoint layout). Host-side; use
        before handing a device-major checkpoint to a job with a
        different (num_stages, num_chunks)."""
        order = self._layer_order()
        if order is None:
            return blocks
        inverse = np.argsort(order)
        return jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, inverse, axis=0), blocks
        )

    def blocks_from_portable(self, blocks):
        """Inverse of blocks_to_portable."""
        order = self._layer_order()
        if order is None:
            return blocks
        return jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, np.asarray(order), axis=0),
            blocks,
        )

    def apply(self, variables, tokens, training=False, rngs=None):
        del rngs
        params = variables["params"]
        x = self._wte.apply(
            {"params": params["wte"]}, jnp.asarray(tokens, jnp.int32)
        )

        def stage_fn(stage_params, h):
            def layer(carry, layer_params):
                out = self._block.apply(
                    {"params": layer_params}, carry, training=training
                )
                return out, None

            h, _ = jax.lax.scan(layer, h, stage_params)
            return h

        if self.mesh is None:
            # Single-chip sequential fallback: scan over the flat stack.
            x = stage_fn(params[self.blocks_key], x)
        else:
            # Regroup (L, ...) -> (S*V, L/(S*V), ...) for the schedule.
            # The leading dim is pp-sharded, so the reshape splits along
            # shard boundaries (no resharding).
            chunks = self.num_stages * self.num_chunks
            per_chunk = self.num_layers // chunks
            staged = jax.tree_util.tree_map(
                lambda leaf: leaf.reshape(
                    (chunks, per_chunk) + leaf.shape[1:]
                ),
                params[self.blocks_key],
            )
            x = pipeline_apply(
                stage_fn,
                staged,
                x,
                num_microbatches=self.num_microbatches,
                mesh=self.mesh,
                num_chunks=self.num_chunks,
                params_layout=(
                    "device" if self.device_major_params else "chunk"
                ),
            )
        x = self._ln_f.apply({"params": params["ln_f"]}, x)
        return self._head.apply({"params": params["lm_head"]}, x)


def pipeline_sharding_rules():
    """Layer-stack axis over pp, everything else replicated.

    Blocks leaves are flat ``(num_layers, *param_shape)``; sharding dim 0
    over pp gives each stage exactly its own layers. Within-stage params
    are intentionally NOT fsdp/tp-sharded: the stage loop runs inside a
    ``shard_map`` manual region where GSPMD annotations are inert, so any
    other spec here would just make jit all-gather the params at the
    shard_map boundary every step.
    """
    return ShardingRules(
        rules=[
            (r"^blocks(_device_major)?/", P("pp")),
            (r"wte/embedding$", P(None, "fsdp")),
            (r"lm_head/kernel$", P("fsdp", None)),
            (r".*", P()),
        ],
        default_spec=P(),
    )


# -- model-zoo contract -----------------------------------------------------

def mesh_config(num_devices):
    from elasticdl_tpu.parallel.mesh import MeshConfig

    pp = 4 if num_devices % 4 == 0 else (2 if num_devices % 2 == 0 else 1)
    return MeshConfig(dp=num_devices // pp, pp=pp)


def custom_model(mesh=None):
    num_layers = 12
    num_stages = 1
    if mesh is not None:
        num_stages = max(mesh.shape.get("pp", 1), 1)
    if num_layers % num_stages != 0:
        raise ValueError(
            "pipeline_transformer has %d layers; mesh pp extent %d does "
            "not divide it — pick pp in {1,2,3,4,6,12}"
            % (num_layers, num_stages)
        )
    return PipelinedTransformerLM(
        vocab_size=32000,
        num_layers=num_layers,
        num_stages=num_stages,
        num_heads=12,
        embed_dim=768,
        mesh=mesh,
    )


loss = transformer.loss
optimizer = transformer.optimizer
dataset_fn = transformer.dataset_fn
eval_metrics_fn = transformer.eval_metrics_fn


def sharding_rules():
    return pipeline_sharding_rules()
