"""Pipeline-parallel transformer LM.

No reference counterpart (the reference has no layer pipelining —
SURVEY.md §2.12 lists PP as absent); this family exercises the ``pp``
mesh axis: transformer blocks are pipeline *stages* whose stacked
parameters shard ``P("pp")`` over the mesh, and the forward runs the
GPipe microbatch schedule in :mod:`elasticdl_tpu.parallel.pipeline`.

The model is a plain (non-flax) class implementing the framework's model
contract — ``init(rng, features) -> variables`` / ``apply(variables,
features, training=, rngs=)`` — because the stage loop lives in a
``shard_map`` that flax's module system has no idiom for; the embed /
final-norm / head pieces and the per-stage Block remain ordinary flax
modules so their params initialize identically to TransformerLM's.

Dropout is intentionally unsupported here (stage rng plumbing through the
pipeline schedule isn't worth the complexity; the reference's models
don't regularize via dropout either).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from elasticdl_tpu.models import transformer
from elasticdl_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from elasticdl_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P


class PipelinedTransformerLM:
    """Decoder-only LM with blocks partitioned into pipeline stages.

    ``layers_per_stage`` blocks run sequentially inside each stage;
    ``num_stages`` must equal the mesh's ``pp`` extent (or 1 when no mesh
    is given — pure sequential fallback for single-chip runs).
    """

    def __init__(
        self,
        vocab_size=32000,
        num_stages=4,
        layers_per_stage=1,
        num_heads=8,
        embed_dim=512,
        mlp_ratio=4,
        num_microbatches=4,
        attention_impl="auto",
        mesh=None,
    ):
        self.vocab_size = vocab_size
        self.num_stages = num_stages
        self.layers_per_stage = layers_per_stage
        self.num_microbatches = num_microbatches
        self.mesh = mesh
        self.embed_dim = embed_dim
        self._wte = nn.Embed(vocab_size, embed_dim, name="wte")
        self._ln_f = nn.LayerNorm(name="ln_f")
        self._head = nn.Dense(vocab_size, use_bias=False, name="lm_head")
        self._block = transformer.Block(
            num_heads,
            mlp_ratio=mlp_ratio,
            attention_impl=attention_impl,
            mesh=mesh,
        )

    # -- model contract ------------------------------------------------
    def init(self, rng, tokens, training=False, rngs=None):
        del training, rngs
        n_blocks = self.num_stages * self.layers_per_stage
        keys = jax.random.split(rng, n_blocks + 3)
        wte = self._wte.init(keys[0], jnp.asarray(tokens, jnp.int32))
        x = self._wte.apply(wte, jnp.asarray(tokens, jnp.int32))
        block_params = []
        for i in range(n_blocks):
            variables = self._block.init(keys[1 + i], x, training=False)
            block_params.append(variables["params"])
        # Stage axis (num_stages) outermost, per-stage layer axis second:
        # leaves are (S, L, ...).
        stages = [
            stack_stage_params(
                block_params[
                    s * self.layers_per_stage : (s + 1)
                    * self.layers_per_stage
                ]
            )
            for s in range(self.num_stages)
        ]
        stacked = stack_stage_params(stages)
        ln_f = self._ln_f.init(keys[-2], x)
        head = self._head.init(keys[-1], x)
        return {
            "params": {
                "wte": wte["params"],
                "blocks": stacked,
                "ln_f": ln_f["params"],
                "lm_head": head["params"],
            }
        }

    def apply(self, variables, tokens, training=False, rngs=None):
        del rngs
        params = variables["params"]
        x = self._wte.apply(
            {"params": params["wte"]}, jnp.asarray(tokens, jnp.int32)
        )

        def stage_fn(stage_params, h):
            def layer(carry, layer_params):
                out = self._block.apply(
                    {"params": layer_params}, carry, training=training
                )
                return out, None

            h, _ = jax.lax.scan(layer, h, stage_params)
            return h

        if self.mesh is None:
            # Single-chip sequential fallback.
            def all_stages(carry, stage_params):
                return stage_fn(stage_params, carry), None

            x, _ = jax.lax.scan(all_stages, x, params["blocks"])
        else:
            # pipeline_apply validates num_stages against the mesh's pp
            # extent and runs every stage sequentially when pp == 1.
            x = pipeline_apply(
                stage_fn,
                params["blocks"],
                x,
                num_microbatches=self.num_microbatches,
                mesh=self.mesh,
            )
        x = self._ln_f.apply({"params": params["ln_f"]}, x)
        return self._head.apply({"params": params["lm_head"]}, x)


def pipeline_sharding_rules():
    """Stage axis over pp; within-stage tensor parallelism composes by
    prepending (pp, layer) to the TransformerLM TP specs. Blocks leaves
    are (S, L, *param_shape)."""
    return ShardingRules(
        rules=[
            (
                r"blocks/.*(query|key|value)/kernel$",
                P("pp", None, "fsdp", "tp", None),
            ),
            (r"blocks/.*out_proj/kernel$", P("pp", None, "tp", None, "fsdp")),
            (r"blocks/.*mlp_up/kernel$", P("pp", None, "fsdp", "tp")),
            (r"blocks/.*mlp_down/kernel$", P("pp", None, "tp", "fsdp")),
            (r"^blocks/", P("pp")),
            (r"wte/embedding$", P(None, "fsdp")),
            (r"lm_head/kernel$", P("fsdp", None)),
            (r".*", P()),
        ],
        default_spec=P(),
    )


# -- model-zoo contract -----------------------------------------------------

def mesh_config(num_devices):
    from elasticdl_tpu.parallel.mesh import MeshConfig

    pp = 4 if num_devices % 4 == 0 else (2 if num_devices % 2 == 0 else 1)
    return MeshConfig(dp=num_devices // pp, pp=pp)


def custom_model(mesh=None):
    total_layers = 12
    num_stages = 1
    if mesh is not None:
        num_stages = mesh.shape.get("pp", 1)
    return PipelinedTransformerLM(
        vocab_size=32000,
        num_stages=max(num_stages, 1),
        layers_per_stage=max(1, total_layers // max(num_stages, 1)),
        num_heads=12,
        embed_dim=768,
        mesh=mesh,
    )


loss = transformer.loss
optimizer = transformer.optimizer
dataset_fn = transformer.dataset_fn
eval_metrics_fn = transformer.eval_metrics_fn


def sharding_rules():
    return pipeline_sharding_rules()
