// Concurrency stress test for the embedding store, built with
// -fsanitize=thread (make tsan). The reference ships no race detection
// at all (SURVEY.md §5: go test runs without -race); this closes that
// gap for the one component with real lock contention: concurrent
// lookups (lazy row creation), gradient pushes, exports, and version
// bumps across threads and tables.
//
// Exit 0 + "STRESS-OK" iff no data race was reported (TSAN aborts the
// process on findings when TSAN_OPTIONS=halt_on_error=1).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" {
void* edl_store_create(uint64_t seed);
void edl_store_destroy(void* handle);
int edl_store_set_optimizer(void* handle, const char* type, float lr,
                            float momentum, float beta1, float beta2,
                            float epsilon);
int edl_store_create_table(void* handle, const char* name, int64_t dim,
                           float init_scale);
int edl_store_lookup(void* handle, const char* name, const int64_t* ids,
                     int64_t n, float* out);
int edl_store_push_gradients(void* handle, const char* name,
                             const int64_t* ids, const float* grads,
                             int64_t n, float lr_scale);
int64_t edl_store_version(void* handle);
void edl_store_bump_version(void* handle);
int64_t edl_store_export_full(void* handle, const char* name,
                              int64_t* out_ids, float* out_values,
                              int64_t* out_steps, int64_t capacity);
int edl_store_table_slots(void* handle, const char* name);
}

namespace {
constexpr int kDim = 8;
constexpr int kThreads = 8;
constexpr int kIters = 400;
constexpr int kIdsPerOp = 16;
const char* kTables[2] = {"alpha", "beta"};

void worker(void* store, int tid) {
  int64_t ids[kIdsPerOp];
  float buffer[kIdsPerOp * kDim];
  float grads[kIdsPerOp * kDim];
  for (int i = 0; i < kIdsPerOp * kDim; ++i) grads[i] = 0.01f;
  uint64_t rng = 0x9e3779b97f4a7c15ull * (tid + 1);
  for (int iter = 0; iter < kIters; ++iter) {
    rng = rng * 6364136223846793005ull + 1442695040888963407ull;
    const char* table = kTables[(rng >> 33) & 1];
    for (int i = 0; i < kIdsPerOp; ++i) {
      ids[i] = (int64_t)((rng >> (i % 24)) % 512);
    }
    switch ((rng >> 20) % 4) {
      case 0:
      case 1:
        if (edl_store_lookup(store, table, ids, kIdsPerOp, buffer) != 0)
          std::abort();
        break;
      case 2:
        if (edl_store_push_gradients(store, table, ids, grads, kIdsPerOp,
                                     1.0f) != 0)
          std::abort();
        edl_store_bump_version(store);
        break;
      case 3: {
        int64_t count =
            edl_store_export_full(store, table, nullptr, nullptr, nullptr, 0);
        if (count < 0) std::abort();
        // row width follows the live optimizer's slot count — a
        // hardcoded width would heap-overflow if the optimizer under
        // stress ever changes
        const int slots = edl_store_table_slots(store, table);
        if (slots < 0) std::abort();
        std::vector<int64_t> out_ids(count + 64);
        std::vector<float> out_values((count + 64) * kDim * (1 + slots));
        std::vector<int64_t> out_steps(count + 64);
        if (edl_store_export_full(store, table, out_ids.data(),
                                  out_values.data(), out_steps.data(),
                                  count + 64) < 0)
          std::abort();
        break;
      }
    }
  }
}
}  // namespace

int main() {
  void* store = edl_store_create(7);
  edl_store_set_optimizer(store, "adam", 0.01f, 0.9f, 0.9f, 0.999f, 1e-8f);
  for (const char* table : kTables) {
    if (edl_store_create_table(store, table, kDim, 0.05f) != 0) return 2;
  }
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back(worker, store, t);
  }
  for (auto& t : threads) t.join();
  if (edl_store_version(store) <= 0) return 3;
  edl_store_destroy(store);
  std::printf("STRESS-OK\n");
  return 0;
}
