// Concurrency stress test for the embedding store, built with
// -fsanitize=thread (make tsan). The reference ships no race detection
// at all (SURVEY.md §5: go test runs without -race); this closes that
// gap for the one component with real lock contention: concurrent
// lookups (lazy row creation), gradient pushes, exports, and version
// bumps across threads and tables.
//
// Exit 0 + "STRESS-OK" iff no data race was reported (TSAN aborts the
// process on findings when TSAN_OPTIONS=halt_on_error=1).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" {
void* edl_store_create(uint64_t seed);
void edl_store_destroy(void* handle);
int edl_store_set_optimizer(void* handle, const char* type, double lr,
                            double momentum, double beta1, double beta2,
                            double epsilon);
int edl_store_create_table(void* handle, const char* name, int64_t dim,
                           float init_scale);
int edl_store_lookup(void* handle, const char* name, const int64_t* ids,
                     int64_t n, float* out);
int edl_store_push_gradients(void* handle, const char* name,
                             const int64_t* ids, const float* grads,
                             int64_t n, double lr_scale);
int64_t edl_store_version(void* handle);
void edl_store_bump_version(void* handle);
int64_t edl_store_export_full(void* handle, const char* name,
                              int64_t* out_ids, float* out_values,
                              int64_t* out_steps, int64_t capacity);
int edl_store_table_slots(void* handle, const char* name);
int edl_store_apply_blob(void* handle, const char* name,
                         const int64_t* ids, int64_t n, const void* grads,
                         int grad_dtype, double lr_scale, int dedup);
int edl_store_lookup_cast(void* handle, const char* name,
                          const int64_t* ids, int64_t n, void* out,
                          int out_dtype);
int edl_store_import_blob(void* handle, const char* name,
                          const int64_t* ids, int64_t n, const void* values,
                          int dtype, int shard_id, int shard_num);
int64_t edl_store_abi_version(void);
int64_t edl_store_drop_rows(void* handle, const char* name,
                            const int64_t* ids, int64_t n);
int64_t edl_store_export_dirty(void* handle, const char* name,
                               int64_t* out_ids, float* out_values,
                               int64_t* out_steps, int64_t* out_dead,
                               int64_t capacity, int64_t dead_capacity,
                               int64_t* out_dead_count, int clear);
int edl_store_clear_dirty(void* handle, const char* name);
}

namespace {
constexpr int kDim = 8;
constexpr int kThreads = 8;
constexpr int kIters = 400;
constexpr int kIdsPerOp = 16;
const char* kTables[2] = {"alpha", "beta"};

void worker(void* store, int tid) {
  int64_t ids[kIdsPerOp];
  float buffer[kIdsPerOp * kDim];
  float grads[kIdsPerOp * kDim];
  for (int i = 0; i < kIdsPerOp * kDim; ++i) grads[i] = 0.01f;
  uint64_t rng = 0x9e3779b97f4a7c15ull * (tid + 1);
  for (int iter = 0; iter < kIters; ++iter) {
    rng = rng * 6364136223846793005ull + 1442695040888963407ull;
    const char* table = kTables[(rng >> 33) & 1];
    for (int i = 0; i < kIdsPerOp; ++i) {
      ids[i] = (int64_t)((rng >> (i % 24)) % 512);
    }
    switch ((rng >> 20) % 4) {
      case 0:
      case 1:
        if (edl_store_lookup(store, table, ids, kIdsPerOp, buffer) != 0)
          std::abort();
        break;
      case 2:
        if (edl_store_push_gradients(store, table, ids, grads, kIdsPerOp,
                                     1.0f) != 0)
          std::abort();
        edl_store_bump_version(store);
        break;
      case 3: {
        int64_t count =
            edl_store_export_full(store, table, nullptr, nullptr, nullptr, 0);
        if (count < 0) std::abort();
        // row width follows the live optimizer's slot count — a
        // hardcoded width would heap-overflow if the optimizer under
        // stress ever changes
        const int slots = edl_store_table_slots(store, table);
        if (slots < 0) std::abort();
        std::vector<int64_t> out_ids(count + 64);
        std::vector<float> out_values((count + 64) * kDim * (1 + slots));
        std::vector<int64_t> out_steps(count + 64);
        if (edl_store_export_full(store, table, out_ids.data(),
                                  out_values.data(), out_steps.data(),
                                  count + 64) < 0)
          std::abort();
        break;
      }
    }
  }
}

// ISSUE 11 interleave: the wire-blob fast paths (deserialize+dedup+
// apply, cast lookups, raw imports) hammered from many threads
// concurrently with the classic worker() traffic above — the apply
// fan-out (EDL_PS_APPLY_THREADS) runs exactly this shape in the
// servicer. Duplicate-heavy id streams on purpose: the dedup path's
// sort/segment-sum scratch is per-call, so only the table state is
// shared.
void blob_worker(void* store, int tid) {
  int64_t ids[kIdsPerOp];
  uint16_t half_grads[kIdsPerOp * kDim];
  float f32_grads[kIdsPerOp * kDim];
  uint8_t cast_out[kIdsPerOp * kDim * 4];
  for (int i = 0; i < kIdsPerOp * kDim; ++i) {
    half_grads[i] = 0x3c00;  // 1.0 in f16
    f32_grads[i] = 0.01f;
  }
  uint64_t rng = 0xda942042e4dd58b5ull * (tid + 3);
  for (int iter = 0; iter < kIters; ++iter) {
    rng = rng * 6364136223846793005ull + 1442695040888963407ull;
    const char* table = kTables[(rng >> 33) & 1];
    for (int i = 0; i < kIdsPerOp; ++i) {
      // % 64: dense duplicates, so dedup's segment sums really merge
      ids[i] = (int64_t)((rng >> (i % 24)) % 64);
    }
    switch ((rng >> 20) % 4) {
      case 0:
        if (edl_store_apply_blob(store, table, ids, kIdsPerOp, f32_grads,
                                 /*kF32=*/0, 1.0, /*dedup=*/1) != 0)
          std::abort();
        break;
      case 1:
        if (edl_store_apply_blob(store, table, ids, kIdsPerOp, half_grads,
                                 /*kF16=*/2, 0.5, /*dedup=*/1) != 0)
          std::abort();
        break;
      case 2:
        if (edl_store_lookup_cast(store, table, ids, kIdsPerOp, cast_out,
                                  /*kBF16=*/1) != 0)
          std::abort();
        break;
      case 3:
        if (edl_store_import_blob(store, table, ids, kIdsPerOp, f32_grads,
                                  /*kF32=*/0, 0, 0) != 0)
          std::abort();
        break;
    }
  }
}
// ISSUE 13 interleave: the checkpoint thread's dirty snapshot-and-
// clear (plus lifecycle drops feeding the dead set) racing the push/
// import traffic above — exactly the off-RPC delta-save shape. The
// sizing probe + fill retry mirrors the Python binding's loop.
void dirty_worker(void* store, int tid) {
  int64_t ids[kIdsPerOp];
  uint64_t rng = 0xbf58476d1ce4e5b9ull * (tid + 11);
  for (int iter = 0; iter < kIters; ++iter) {
    rng = rng * 6364136223846793005ull + 1442695040888963407ull;
    const char* table = kTables[(rng >> 33) & 1];
    switch ((rng >> 20) % 3) {
      case 0: {
        for (int i = 0; i < kIdsPerOp; ++i) {
          ids[i] = (int64_t)((rng >> (i % 24)) % 512);
        }
        if (edl_store_drop_rows(store, table, ids, kIdsPerOp) < 0)
          std::abort();
        break;
      }
      case 1: {
        const int slots = edl_store_table_slots(store, table);
        if (slots < 0) std::abort();
        for (int attempt = 0; attempt < 8; ++attempt) {
          int64_t dead = 0;
          int64_t nd = edl_store_export_dirty(
              store, table, nullptr, nullptr, nullptr, nullptr, 0, 0,
              &dead, 0);
          if (nd < 0) std::abort();
          std::vector<int64_t> out_ids(nd + 64);
          std::vector<float> out_values((nd + 64) * kDim * (1 + slots));
          std::vector<int64_t> out_steps(nd + 64);
          std::vector<int64_t> out_dead(dead + 64);
          int64_t got = edl_store_export_dirty(
              store, table, out_ids.data(), out_values.data(),
              out_steps.data(), out_dead.data(), nd + 64, dead + 64,
              &dead, /*clear=*/1);
          if (got == -3) continue;  // grew past the slack; re-probe
          if (got < 0) std::abort();
          break;
        }
        break;
      }
      case 2:
        if (edl_store_clear_dirty(store, table) != 0) std::abort();
        break;
    }
  }
}
}  // namespace

int main() {
  if (edl_store_abi_version() < 2) return 4;
  void* store = edl_store_create(7);
  edl_store_set_optimizer(store, "adam", 0.01, 0.9, 0.9, 0.999, 1e-8);
  for (const char* table : kTables) {
    if (edl_store_create_table(store, table, kDim, 0.05f) != 0) return 2;
  }
  std::vector<std::thread> threads;
  // half classic push/pull/export traffic, half wire-blob traffic —
  // the mixed interleave is the state a UDS-fronted PS under
  // EDL_PS_APPLY_THREADS actually runs
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back(worker, store, t);
    threads.emplace_back(blob_worker, store, t);
    if (t < 2) threads.emplace_back(dirty_worker, store, t);
  }
  for (auto& t : threads) t.join();
  if (edl_store_version(store) <= 0) return 3;
  edl_store_destroy(store);
  std::printf("STRESS-OK\n");
  return 0;
}
