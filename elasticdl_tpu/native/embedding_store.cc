// Host-side sparse embedding store with fused optimizer kernels.
//
// TPU-native equivalent of the reference's Go parameter server runtime:
//   - lazy hash-map embedding tables (go/pkg/common/embedding_table.go)
//   - sparse SGD/Momentum/Adagrad/Adam kernels (go/pkg/kernel/capi/
//     kernel_api.cc) — here applied row-wise in-place, slots stored
//     inline with the row so one cache line serves weight+slots
//   - id-sharded binary checkpoints (go/pkg/ps/checkpoint.go)
//
// The dense path of the reference PS is intentionally absent: dense
// parameters live on device, GSPMD-sharded. Only the embedding-id axis
// — unbounded and hash-addressed — stays host-side.
//
// Exposed as a C API for ctypes (no pybind11 in this environment).
// ctypes releases the GIL for the duration of every call, so a whole
// deserialize+dedup+apply (edl_store_apply_blob) or a batched
// lookup/export runs GIL-free — that, not micro-optimization, is why
// the wire fast paths live behind single C entry points.
//
// FLOAT SEMANTICS (ISSUE 11): every kernel here is BIT-IDENTICAL to
// NumpyEmbeddingStore under numpy 2 / NEP 50. That pins three rules:
//   1. optimizer hyperparameters are carried as double (the python
//      float the twin stores) and rounded to float exactly where
//      numpy's weak-scalar promotion rounds them — e.g. Adam's
//      (1 - beta1) is float(1.0 - beta1_double), NOT 1.0f - beta1f;
//   2. elementwise math stays in float with numpy's operator order
//      (the Makefile passes -ffp-contract=off so gcc cannot fuse
//      a*b+c into fma and change the rounding);
//   3. bias corrections use libm pow on doubles, the same call
//      CPython's float.__pow__ makes.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

enum class OptType { kSGD = 0, kMomentum = 1, kAdagrad = 2, kAdam = 3 };

// Wire payload dtypes the blob entry points understand. Values match
// BLOB_DTYPE_CODES in ps/embedding_store.py.
enum WireDtype { kF32 = 0, kBF16 = 1, kF16 = 2 };

inline float bf16_to_f32(uint16_t h) {
  uint32_t u = static_cast<uint32_t>(h) << 16;
  float f;
  std::memcpy(&f, &u, 4);
  return f;
}

// Round-to-nearest-even f32 -> bf16, matching ml_dtypes/Eigen
// (numpy's astype(bfloat16)): NaN keeps sign + a set mantissa bit.
inline uint16_t f32_to_bf16(float f) {
  uint32_t u;
  std::memcpy(&u, &f, 4);
  if ((u & 0x7fffffffu) > 0x7f800000u) {
    return static_cast<uint16_t>((u >> 16) | 0x0040u);
  }
  const uint32_t bias = 0x7fffu + ((u >> 16) & 1u);
  return static_cast<uint16_t>((u + bias) >> 16);
}

inline float f16_to_f32(uint16_t h) {
  const uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1fu;
  uint32_t man = h & 0x3ffu;
  uint32_t u;
  if (exp == 0) {
    if (man == 0) {
      u = sign;  // +-0
    } else {
      // subnormal half: renormalize into the f32 exponent range
      int shift = 0;
      while (!(man & 0x400u)) {
        man <<= 1;
        ++shift;
      }
      man &= 0x3ffu;
      // man * 2^-24 normalized: 1.f * 2^(-14 - shift) -> biased 113-shift
      u = sign | (static_cast<uint32_t>(113 - shift) << 23) | (man << 13);
    }
  } else if (exp == 31) {
    u = sign | 0x7f800000u | (man << 13);  // inf / nan
  } else {
    u = sign | ((exp + 112u) << 23) | (man << 13);
  }
  float f;
  std::memcpy(&f, &u, 4);
  return f;
}

// Round-to-nearest-even f32 -> f16 (numpy npy_half semantics),
// including subnormal results and overflow-to-inf.
inline uint16_t f32_to_f16(float ff) {
  uint32_t f;
  std::memcpy(&f, &ff, 4);
  const uint32_t sign = f & 0x80000000u;
  f ^= sign;
  uint16_t out;
  if (f >= ((127u + 16u) << 23)) {  // overflow, inf, nan
    out = (f > (255u << 23)) ? 0x7e00u : 0x7c00u;
  } else if (f < (113u << 23)) {
    // subnormal f16 result: the "denorm magic" add performs the
    // shift-and-round in float hardware (Giesen's rtne construction)
    const uint32_t denorm_magic = ((127u - 15u) + (23u - 10u) + 1u) << 23;
    float tmp;
    std::memcpy(&tmp, &f, 4);
    float magic;
    std::memcpy(&magic, &denorm_magic, 4);
    tmp += magic;
    uint32_t t;
    std::memcpy(&t, &tmp, 4);
    out = static_cast<uint16_t>(t - denorm_magic);
  } else {
    const uint32_t mant_odd = (f >> 13) & 1u;
    f += (static_cast<uint32_t>(15 - 127) << 23) + 0xfffu;
    f += mant_odd;
    out = static_cast<uint16_t>(f >> 13);
  }
  return static_cast<uint16_t>(out | (sign >> 16));
}

inline int wire_itemsize(int dtype) {
  switch (dtype) {
    case kF32: return 4;
    case kBF16: return 2;
    case kF16: return 2;
  }
  return -1;
}

// Decode one wire row into fp32 (upcast is exact for bf16/f16).
inline void decode_row(const uint8_t* src, int dtype, int64_t dim,
                       float* dst) {
  switch (dtype) {
    case kF32:
      std::memcpy(dst, src, sizeof(float) * dim);
      break;
    case kBF16: {
      const uint16_t* h = reinterpret_cast<const uint16_t*>(src);
      for (int64_t d = 0; d < dim; ++d) dst[d] = bf16_to_f32(h[d]);
      break;
    }
    case kF16: {
      const uint16_t* h = reinterpret_cast<const uint16_t*>(src);
      for (int64_t d = 0; d < dim; ++d) dst[d] = f16_to_f32(h[d]);
      break;
    }
  }
}

// ---------------------------------------------------------------------
// numpy pairwise summation over rows, bit-for-bit. np.add.reduceat's
// segment reduce is NOT a sequential left fold: it seeds the output
// with row 0, then reduces rows 1..n-1 with numpy's blocked pairwise
// algorithm (loops_utils.h pairwise_sum: < 8 rows sequential from
// 0.0, <= 128 rows eight running accumulators combined as
// ((r0+r1)+(r2+r3))+((r4+r5)+(r6+r7)), larger split in half rounded
// down to a multiple of 8). The dedup fast path must reproduce that
// exact association or fp32 segment sums drift by an ulp and the
// parity suite (tests/test_native_parity.py) catches it.
void pairwise_sum_rows(const float* a, int64_t n, int64_t dim,
                       float* out) {
  if (n <= 0) {
    std::memset(out, 0, sizeof(float) * dim);
    return;
  }
  if (n < 8) {
    for (int64_t d = 0; d < dim; ++d) {
      float res = 0.0f;
      for (int64_t i = 0; i < n; ++i) res += a[i * dim + d];
      out[d] = res;
    }
    return;
  }
  if (n <= 128) {
    std::vector<float> r(8 * dim);
    std::memcpy(r.data(), a, sizeof(float) * 8 * dim);
    int64_t i = 8;
    for (; i + 8 <= n; i += 8) {
      for (int j = 0; j < 8; ++j) {
        float* rj = r.data() + j * dim;
        const float* aj = a + (i + j) * dim;
        for (int64_t d = 0; d < dim; ++d) rj[d] += aj[d];
      }
    }
    for (int64_t d = 0; d < dim; ++d) {
      out[d] = ((r[0 * dim + d] + r[1 * dim + d]) +
                (r[2 * dim + d] + r[3 * dim + d])) +
               ((r[4 * dim + d] + r[5 * dim + d]) +
                (r[6 * dim + d] + r[7 * dim + d]));
    }
    for (; i < n; ++i) {
      const float* ai = a + i * dim;
      for (int64_t d = 0; d < dim; ++d) out[d] += ai[d];
    }
    return;
  }
  int64_t h = n / 2;
  h -= h % 8;
  std::vector<float> right(dim);
  pairwise_sum_rows(a, h, dim, out);
  pairwise_sum_rows(a + h * dim, n - h, dim, right.data());
  for (int64_t d = 0; d < dim; ++d) out[d] += right[d];
}

// reduceat segment semantics: out = rows[0] + pairwise_sum(rows[1:]).
void reduceat_segment(const float* rows, int64_t n, int64_t dim,
                      float* out) {
  if (n == 1) {
    std::memcpy(out, rows, sizeof(float) * dim);
    return;
  }
  std::vector<float> rest(dim);
  pairwise_sum_rows(rows + dim, n - 1, dim, rest.data());
  for (int64_t d = 0; d < dim; ++d) out[d] = rows[d] + rest[d];
}

// Row initializers (reference go/pkg/common/initializer.go:25-155:
// Zero/Constant/Uniform/Normal/TruncatedNormal). kConstant covers Zero
// via param=0.
enum class InitKind {
  kUniform = 0,         // U(-param, param)
  kConstant = 1,        // fill(param)
  kNormal = 2,          // N(0, param^2)
  kTruncatedNormal = 3  // N(0, param^2) resampled into [-2p, 2p]
};

struct OptConfig {
  OptType type = OptType::kSGD;
  // doubles: the exact python floats NumpyEmbeddingStore holds —
  // rounded to f32 only where numpy's weak-scalar promotion rounds
  double lr = 0.01;
  double momentum = 0.9;
  double beta1 = 0.9;
  double beta2 = 0.999;
  double epsilon = 1e-8;
  // variants (reference go/pkg/ps/optimizer.go supports
  // Momentum+nesterov and Adam+amsgrad)
  bool nesterov = false;
  bool amsgrad = false;
  int slots() const {
    switch (type) {
      case OptType::kSGD: return 0;
      case OptType::kMomentum: return 1;
      case OptType::kAdagrad: return 1;
      case OptType::kAdam: return amsgrad ? 3 : 2;
    }
    return 0;
  }
};

struct Table {
  std::string name;
  int64_t dim = 0;
  float init_scale = 0.05f;
  InitKind init_kind = InitKind::kUniform;
  int slots = 0;
  // row layout: [weight(dim) | slot0(dim) | slot1(dim)]
  std::unordered_map<int64_t, std::unique_ptr<float[]>> rows;
  // Adam per-row step counts for bias correction.
  std::unordered_map<int64_t, int64_t> row_steps;
  // Incremental-checkpoint bookkeeping (ISSUE 13), guarded by mu like
  // the rows themselves: dirty_ids = resident rows mutated (or first
  // materialized) since the last dirty export; dead_ids = ids dropped
  // since then, replayed as deletes by the delta restore so an
  // evicted row cannot resurrect. Invariants: dirty_ids is a subset
  // of the resident ids, dead_ids is disjoint from them — a drop
  // moves an id dirty->dead, a re-materialization moves it back.
  std::unordered_set<int64_t> dirty_ids;
  std::unordered_set<int64_t> dead_ids;
  // Per-table RNG: only touched under this table's unique lock, so
  // concurrent lookups on different tables never race on RNG state.
  std::mt19937 rng;
  mutable std::shared_mutex mu;

  float* get_or_init(int64_t id) {
    std::mt19937* rng = &this->rng;
    auto it = rows.find(id);
    if (it != rows.end()) return it->second.get();
    // a lazy init is a state change: a full save would carry the drawn
    // row, so the delta chain must too (the restored twin's RNG stream
    // is at a different position — absence would not reproduce it)
    dirty_ids.insert(id);
    dead_ids.erase(id);
    auto row = std::make_unique<float[]>(dim * (1 + slots));
    switch (init_kind) {
      case InitKind::kUniform: {
        std::uniform_real_distribution<float> dist(-init_scale, init_scale);
        for (int64_t d = 0; d < dim; ++d) row[d] = dist(*rng);
        break;
      }
      case InitKind::kConstant: {
        for (int64_t d = 0; d < dim; ++d) row[d] = init_scale;
        break;
      }
      case InitKind::kNormal: {
        if (init_scale <= 0.0f) break;  // stddev<=0: zeros (std UB guard)
        std::normal_distribution<float> dist(0.0f, init_scale);
        for (int64_t d = 0; d < dim; ++d) row[d] = dist(*rng);
        break;
      }
      case InitKind::kTruncatedNormal: {
        if (init_scale <= 0.0f) break;
        std::normal_distribution<float> dist(0.0f, init_scale);
        const float bound = 2.0f * init_scale;
        for (int64_t d = 0; d < dim; ++d) {
          float x = dist(*rng);
          while (x < -bound || x > bound) x = dist(*rng);
          row[d] = x;
        }
        break;
      }
    }
    std::memset(row.get() + dim, 0, sizeof(float) * dim * slots);
    float* ptr = row.get();
    rows.emplace(id, std::move(row));
    return ptr;
  }
};

struct Store {
  OptConfig opt;
  std::unordered_map<std::string, std::unique_ptr<Table>> tables;
  uint64_t seed = 0;
  std::mutex tables_mu;
  std::atomic<int64_t> version{0};

  Table* find(const char* name) {
    std::lock_guard<std::mutex> lock(tables_mu);
    auto it = tables.find(name);
    return it == tables.end() ? nullptr : it->second.get();
  }
};

// ``lr`` arrives as DOUBLE (opt.lr * lr_scale computed in double by
// the caller) and rounds to f32 once here — numpy computes the same
// product in python floats and rounds it at the weak-scalar op.
void apply_row(const OptConfig& opt, float* row, const float* grad,
               int64_t dim, double lr, int64_t step) {
  float* w = row;
  const float lrf = static_cast<float>(lr);
  switch (opt.type) {
    case OptType::kSGD: {
      for (int64_t d = 0; d < dim; ++d) w[d] -= lrf * grad[d];
      break;
    }
    case OptType::kMomentum: {
      float* vel = row + dim;
      const float mu = static_cast<float>(opt.momentum);
      if (opt.nesterov) {
        // lookahead step: w -= lr * (g + mu * vel_new)
        for (int64_t d = 0; d < dim; ++d) {
          vel[d] = mu * vel[d] + grad[d];
          w[d] -= lrf * (grad[d] + mu * vel[d]);
        }
      } else {
        for (int64_t d = 0; d < dim; ++d) {
          vel[d] = mu * vel[d] + grad[d];
          w[d] -= lrf * vel[d];
        }
      }
      break;
    }
    case OptType::kAdagrad: {
      float* acc = row + dim;
      const float eps = static_cast<float>(opt.epsilon);
      for (int64_t d = 0; d < dim; ++d) {
        acc[d] += grad[d] * grad[d];
        w[d] -= lrf * grad[d] / (std::sqrt(acc[d]) + eps);
      }
      break;
    }
    case OptType::kAdam: {
      float* m = row + dim;
      float* v = row + 2 * dim;
      float* vmax = opt.amsgrad ? row + 3 * dim : nullptr;
      const float b1 = static_cast<float>(opt.beta1);
      const float b2 = static_cast<float>(opt.beta2);
      // numpy rounds (1 - beta1) from the DOUBLE, which is not
      // 1.0f - b1 (e.g. beta1=0.9: f32(0.1) != 1.0f - 0.9f)
      const float omb1 = static_cast<float>(1.0 - opt.beta1);
      const float omb2 = static_cast<float>(1.0 - opt.beta2);
      const float eps = static_cast<float>(opt.epsilon);
      // bias corrections in double (libm pow = CPython float.__pow__)
      // then rounded, the same value the numpy store's weak python
      // scalar takes inside its float32 division
      const float bc1 = static_cast<float>(
          1.0 - std::pow(opt.beta1, static_cast<double>(step)));
      const float bc2 = static_cast<float>(
          1.0 - std::pow(opt.beta2, static_cast<double>(step)));
      for (int64_t d = 0; d < dim; ++d) {
        m[d] = b1 * m[d] + omb1 * grad[d];
        v[d] = b2 * v[d] + omb2 * grad[d] * grad[d];
        const float mhat = m[d] / bc1;
        float vv = v[d];
        if (vmax) {
          // amsgrad: denominator uses the running max of v
          vmax[d] = vv > vmax[d] ? vv : vmax[d];
          vv = vmax[d];
        }
        const float vhat = vv / bc2;
        w[d] -= lrf * mhat / (std::sqrt(vhat) + eps);
      }
      break;
    }
  }
}

}  // namespace

extern "C" {

// ABI clock for the ctypes loader (ps/embedding_store.py): bumped on
// every signature/semantics change of this C surface. A loader that
// finds a different value (or no symbol at all — pre-clock builds)
// rebuilds the .so or falls back to numpy instead of calling through
// a drifted ABI. History: 1 = float hyperparameters, no blob entry
// points; 2 = double hyperparameters + apply_blob/lookup_cast/
// import_blob; 3 = drop_rows/drop_table (embedding lifecycle
// eviction, ISSUE 12); 4 = dirty-row tracking + export_dirty/
// dirty_count/clear_dirty (incremental checkpoints, ISSUE 13).
int64_t edl_store_abi_version(void) { return 4; }

void* edl_store_create(uint64_t seed) {
  auto* store = new Store();
  store->seed = seed;
  return store;
}

void edl_store_destroy(void* handle) { delete static_cast<Store*>(handle); }

int edl_store_set_optimizer(void* handle, const char* type, double lr,
                            double momentum, double beta1, double beta2,
                            double epsilon) {
  auto* store = static_cast<Store*>(handle);
  {
    // Rows size their slot memory from the optimizer at table-creation
    // time; swapping the optimizer afterwards would make apply_row write
    // past the allocation.
    std::lock_guard<std::mutex> lock(store->tables_mu);
    if (!store->tables.empty()) return -2;
  }
  OptConfig cfg;
  std::string t(type);
  if (t == "sgd") cfg.type = OptType::kSGD;
  else if (t == "momentum") cfg.type = OptType::kMomentum;
  else if (t == "nesterov") { cfg.type = OptType::kMomentum; cfg.nesterov = true; }
  else if (t == "adagrad") cfg.type = OptType::kAdagrad;
  else if (t == "adam") cfg.type = OptType::kAdam;
  else if (t == "amsgrad") { cfg.type = OptType::kAdam; cfg.amsgrad = true; }
  else return -1;
  cfg.lr = lr;
  cfg.momentum = momentum;
  cfg.beta1 = beta1;
  cfg.beta2 = beta2;
  cfg.epsilon = epsilon;
  store->opt = cfg;
  return 0;
}

// init_kind: InitKind value; init_param: scale / constant / stddev.
int edl_store_create_table_init(void* handle, const char* name, int64_t dim,
                                int init_kind, float init_param) {
  if (init_kind < 0 || init_kind > 3) return -2;
  auto* store = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(store->tables_mu);
  auto it = store->tables.find(name);
  if (it != store->tables.end()) {
    if (it->second->dim != dim) return -1;
    // Existing table: adopt the (possibly updated) initializer so a
    // restore-then-register sequence keeps the model's configured init.
    it->second->init_scale = init_param;
    it->second->init_kind = static_cast<InitKind>(init_kind);
    return 0;
  }
  auto table = std::make_unique<Table>();
  table->name = name;
  table->dim = dim;
  table->init_scale = init_param;
  table->init_kind = static_cast<InitKind>(init_kind);
  table->slots = store->opt.slots();
  table->rng.seed(store->seed * 1000003u + std::hash<std::string>{}(name));
  store->tables.emplace(name, std::move(table));
  return 0;
}

int edl_store_create_table(void* handle, const char* name, int64_t dim,
                           float init_scale) {
  return edl_store_create_table_init(
      handle, name, dim, (int)InitKind::kUniform, init_scale);
}

// Batch lookup; missing rows are lazily initialized (the reference's
// GetEmbeddingVector semantics, embedding_table.go:41-58).
int edl_store_lookup(void* handle, const char* name, const int64_t* ids,
                     int64_t n, float* out) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  for (int64_t i = 0; i < n; ++i) {
    const float* row = table->get_or_init(ids[i]);
    std::memcpy(out + i * table->dim, row, sizeof(float) * table->dim);
  }
  return 0;
}

// Sparse apply: grads is [n, dim] row-major, one row per id. lr_scale
// multiplies the configured LR (staleness modulation hook). Duplicate
// ids apply SEQUENTIALLY, one optimizer step per occurrence — the
// NumpyEmbeddingStore per-id-loop semantics; deduplicated single-apply
// semantics live in edl_store_apply_blob.
int edl_store_push_gradients(void* handle, const char* name,
                             const int64_t* ids, const float* grads,
                             int64_t n, double lr_scale) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  const double lr = store->opt.lr * lr_scale;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  for (int64_t i = 0; i < n; ++i) {
    float* row = table->get_or_init(ids[i]);
    int64_t step = ++table->row_steps[ids[i]];
    apply_row(store->opt, row, grads + i * table->dim, table->dim, lr, step);
    table->dirty_ids.insert(ids[i]);
  }
  return 0;
}

// ---------------------------------------------------------------------
// Wire-blob fast path (ISSUE 11): one C call per table covering the
// whole deserialize + dedup + apply a push used to spread across
// python. ``ids`` points straight at the request's packed ids_blob
// (int64, host-endian == little on every deployment target) and
// ``grads`` at the TensorBlob payload bytes at ``grad_dtype``
// (kF32/kBF16/kF16; reduced dtypes upcast to fp32 exactly, matching
// numpy astype). ``dedup`` != 0 merges duplicate ids with a
// stable-sort + sequential segment sum — bit-identical to
// tensor_utils.deduplicate_indexed_slices (sort + np.add.reduceat) —
// then applies ONE optimizer step per unique id in ascending-id
// order, which is exactly what the numpy pipeline
// (deduplicate_indexed_slices -> NumpyEmbeddingStore.push_gradients)
// computes. Returns 0, -1 unknown table, -2 bad dtype.
int edl_store_apply_blob(void* handle, const char* name,
                         const int64_t* ids, int64_t n,
                         const void* grads, int grad_dtype,
                         double lr_scale, int dedup) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  const int itemsize = wire_itemsize(grad_dtype);
  if (itemsize < 0) return -2;
  if (n <= 0) return 0;
  const int64_t dim = table->dim;
  const double lr = store->opt.lr * lr_scale;
  const uint8_t* bytes = static_cast<const uint8_t*>(grads);
  const int64_t row_bytes = dim * itemsize;

  if (!dedup) {
    std::vector<float> scratch(dim);
    std::unique_lock<std::shared_mutex> lock(table->mu);
    for (int64_t i = 0; i < n; ++i) {
      decode_row(bytes + i * row_bytes, grad_dtype, dim, scratch.data());
      float* row = table->get_or_init(ids[i]);
      int64_t step = ++table->row_steps[ids[i]];
      apply_row(store->opt, row, scratch.data(), dim, lr, step);
      table->dirty_ids.insert(ids[i]);
    }
    return 0;
  }

  // stable sort of input positions by id: duplicates keep input order,
  // so the segment sums below add in exactly reduceat's order
  std::vector<int64_t> order(n);
  for (int64_t i = 0; i < n; ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(),
                   [ids](int64_t a, int64_t b) { return ids[a] < ids[b]; });

  std::vector<float> seg;     // decoded duplicate group, [len, dim]
  std::vector<float> scratch(dim);
  std::unique_lock<std::shared_mutex> lock(table->mu);
  int64_t s = 0;
  while (s < n) {
    const int64_t id = ids[order[s]];
    int64_t e = s + 1;
    while (e < n && ids[order[e]] == id) ++e;
    const int64_t len = e - s;
    const float* grad_row;
    if (len == 1 && grad_dtype == kF32) {
      // singleton f32 segment: apply straight from the wire buffer
      grad_row = reinterpret_cast<const float*>(bytes +
                                                order[s] * row_bytes);
    } else {
      seg.resize(len * dim);
      for (int64_t k = 0; k < len; ++k) {
        decode_row(bytes + order[s + k] * row_bytes, grad_dtype, dim,
                   seg.data() + k * dim);
      }
      reduceat_segment(seg.data(), len, dim, scratch.data());
      grad_row = scratch.data();
    }
    float* row = table->get_or_init(id);
    int64_t step = ++table->row_steps[id];
    apply_row(store->opt, row, grad_row, dim, lr, step);
    table->dirty_ids.insert(id);
    s = e;
  }
  return 0;
}

// Batched lookup emitting rows directly at the wire dtype: the f32 ->
// bf16/f16 downcast (round-to-nearest-even, numpy-astype-exact)
// happens inside this one GIL-released call instead of a separate
// python astype pass. out must hold n * dim * wire_itemsize bytes.
int edl_store_lookup_cast(void* handle, const char* name,
                          const int64_t* ids, int64_t n, void* out,
                          int out_dtype) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  const int itemsize = wire_itemsize(out_dtype);
  if (itemsize < 0) return -2;
  const int64_t dim = table->dim;
  uint8_t* bytes = static_cast<uint8_t*>(out);
  std::unique_lock<std::shared_mutex> lock(table->mu);
  for (int64_t i = 0; i < n; ++i) {
    const float* row = table->get_or_init(ids[i]);
    uint8_t* dst = bytes + i * dim * itemsize;
    switch (out_dtype) {
      case kF32:
        std::memcpy(dst, row, sizeof(float) * dim);
        break;
      case kBF16: {
        uint16_t* h = reinterpret_cast<uint16_t*>(dst);
        for (int64_t d = 0; d < dim; ++d) h[d] = f32_to_bf16(row[d]);
        break;
      }
      case kF16: {
        uint16_t* h = reinterpret_cast<uint16_t*>(dst);
        for (int64_t d = 0; d < dim; ++d) h[d] = f32_to_f16(row[d]);
        break;
      }
    }
  }
  return 0;
}

// Raw row import straight from wire bytes (device-tier writebacks,
// push_embedding_rows): values at ``dtype`` upcast to the fp32 master
// rows, duplicate ids resolve last-write-wins in input order (the
// import_table loop's semantics). No optimizer math, no version bump.
int edl_store_import_blob(void* handle, const char* name,
                          const int64_t* ids, int64_t n,
                          const void* values, int dtype, int shard_id,
                          int shard_num) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  const int itemsize = wire_itemsize(dtype);
  if (itemsize < 0) return -2;
  const int64_t dim = table->dim;
  const uint8_t* bytes = static_cast<const uint8_t*>(values);
  std::unique_lock<std::shared_mutex> lock(table->mu);
  for (int64_t i = 0; i < n; ++i) {
    if (shard_num > 0 &&
        (ids[i] % shard_num + shard_num) % shard_num != shard_id)
      continue;
    float* row = table->get_or_init(ids[i]);
    decode_row(bytes + i * dim * itemsize, dtype, dim, row);
    table->dirty_ids.insert(ids[i]);
  }
  return 0;
}

// Embedding lifecycle eviction (ISSUE 12): delete rows outright —
// weights, optimizer slots, AND per-row step counts, so a later
// re-admission of the id starts from the initializer exactly like a
// never-seen id (a leftover Adam step count would silently skew its
// bias correction). Returns the number of rows actually dropped
// (absent ids are not an error: a sweep may race a checkpoint
// restore), or -1 for an unknown table. The table's RNG stream is
// deliberately NOT rewound: eviction must not perturb the init draws
// of unrelated future rows.
int64_t edl_store_drop_rows(void* handle, const char* name,
                            const int64_t* ids, int64_t n) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  int64_t dropped = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (table->rows.erase(ids[i])) {
      ++dropped;
      // the id leaves the dirty set and enters the dead set: the next
      // delta checkpoint must replay this drop as a delete, or a
      // restored PS resurrects the evicted row from an older shard
      table->dirty_ids.erase(ids[i]);
      table->dead_ids.insert(ids[i]);
    }
    table->row_steps.erase(ids[i]);
  }
  return dropped;
}

// Drop a whole table (rows, slots, steps, metadata). 0 on success,
// -1 unknown table. NOT safe concurrently with traffic on the same
// table: find() hands out raw Table pointers, so the caller must
// quiesce RPCs first — this is an administrative entry point
// (schema retirement, tests), not a sweep-path one; sweeps use
// edl_store_drop_rows, which takes the per-table lock.
int edl_store_drop_table(void* handle, const char* name) {
  auto* store = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(store->tables_mu);
  auto it = store->tables.find(name);
  if (it == store->tables.end()) return -1;
  {
    // drain in-flight holders that already locked the table; new
    // finders are excluded by tables_mu held above
    std::unique_lock<std::shared_mutex> table_lock(it->second->mu);
  }
  store->tables.erase(it);
  return 0;
}

int64_t edl_store_table_size(void* handle, const char* name) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::shared_lock<std::shared_mutex> lock(table->mu);
  return (int64_t)table->rows.size();
}

int64_t edl_store_version(void* handle) {
  return static_cast<Store*>(handle)->version.load();
}

void edl_store_bump_version(void* handle) {
  static_cast<Store*>(handle)->version.fetch_add(1);
}

// Re-anchor the version clock (PS checkpoint auto-restore): one store,
// not O(version) bump calls at boot.
void edl_store_set_version(void* handle, int64_t version) {
  static_cast<Store*>(handle)->version.store(version);
}

// Export all (id, weight-row) pairs of a table into caller buffers.
// Call with out_ids == nullptr to get the count. Weights-only variant,
// used for serving export and weight inspection; checkpoints use
// edl_store_export_full below so optimizer slot state survives resume.
int64_t edl_store_export(void* handle, const char* name, int64_t* out_ids,
                         float* out_values, int64_t capacity) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::shared_lock<std::shared_mutex> lock(table->mu);
  if (out_ids == nullptr) return (int64_t)table->rows.size();
  int64_t i = 0;
  for (const auto& kv : table->rows) {
    if (i >= capacity) break;
    out_ids[i] = kv.first;
    std::memcpy(out_values + i * table->dim, kv.second.get(),
                sizeof(float) * table->dim);
    ++i;
  }
  return i;
}

// Bulk import rows (checkpoint restore / re-shard). Only ids with
// id % shard_num == shard_id are kept when shard_num > 0.
int edl_store_import(void* handle, const char* name, const int64_t* ids,
                     const float* values, int64_t n, int shard_id,
                     int shard_num) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  for (int64_t i = 0; i < n; ++i) {
    if (shard_num > 0 && (ids[i] % shard_num + shard_num) % shard_num != shard_id)
      continue;
    float* row = table->get_or_init(ids[i]);
    std::memcpy(row, values + i * table->dim, sizeof(float) * table->dim);
    table->dirty_ids.insert(ids[i]);
  }
  return 0;
}

int edl_store_table_slots(void* handle, const char* name) {
  Table* table = static_cast<Store*>(handle)->find(name);
  return table == nullptr ? -1 : table->slots;
}

// Full-state export: weight+slot rows ([count, (1+slots)*dim] floats)
// plus per-row optimizer step counts. The weights-only export above
// matches the reference's checkpoint content (ps/parameters.py:194-199
// drops slots); this variant closes that gap so a resumed Adam/Adagrad
// continues from its exact slot state instead of restarting bias
// correction (SURVEY.md s7 "optimizer-state checkpointing").
int64_t edl_store_export_full(void* handle, const char* name,
                              int64_t* out_ids, float* out_values,
                              int64_t* out_steps, int64_t capacity) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::shared_lock<std::shared_mutex> lock(table->mu);
  if (out_ids == nullptr) return (int64_t)table->rows.size();
  const int64_t row_floats = table->dim * (1 + table->slots);
  int64_t i = 0;
  for (const auto& kv : table->rows) {
    if (i >= capacity) break;
    out_ids[i] = kv.first;
    std::memcpy(out_values + i * row_floats, kv.second.get(),
                sizeof(float) * row_floats);
    auto step_it = table->row_steps.find(kv.first);
    out_steps[i] = step_it == table->row_steps.end() ? 0 : step_it->second;
    ++i;
  }
  return i;
}

// Full-state import. row_floats must equal (1+slots)*dim for the
// CURRENT optimizer; on mismatch (optimizer changed between save and
// restore) only the leading weight segment is imported and steps are
// dropped — degrading to the weights-only semantics instead of failing.
int edl_store_import_full(void* handle, const char* name,
                          const int64_t* ids, const float* values,
                          const int64_t* steps, int64_t n,
                          int64_t row_floats, int shard_id, int shard_num) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  if (row_floats < table->dim) return -2;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  const int64_t full = table->dim * (1 + table->slots);
  const bool exact = row_floats == full;
  for (int64_t i = 0; i < n; ++i) {
    if (shard_num > 0 && (ids[i] % shard_num + shard_num) % shard_num != shard_id)
      continue;
    float* row = table->get_or_init(ids[i]);
    std::memcpy(row, values + i * row_floats,
                sizeof(float) * (exact ? full : table->dim));
    if (exact && steps != nullptr) table->row_steps[ids[i]] = steps[i];
    table->dirty_ids.insert(ids[i]);
  }
  return 0;
}

// ---------------------------------------------------------------------
// Incremental checkpoints (ISSUE 13): dirty-row delta export.

// Number of rows a dirty export would currently carry (the
// edl_ps_ckpt_dirty_rows gauge / buffer sizing). -1 unknown table.
int64_t edl_store_dirty_count(void* handle, const char* name) {
  Table* table = static_cast<Store*>(handle)->find(name);
  if (table == nullptr) return -1;
  std::shared_lock<std::shared_mutex> lock(table->mu);
  return (int64_t)table->dirty_ids.size();
}

int64_t edl_store_dead_count(void* handle, const char* name) {
  Table* table = static_cast<Store*>(handle)->find(name);
  if (table == nullptr) return -1;
  std::shared_lock<std::shared_mutex> lock(table->mu);
  return (int64_t)table->dead_ids.size();
}

// Snapshot-and-clear dirty export, the delta-checkpoint primitive:
// under ONE hold of the per-table unique lock, export every dirty
// row's full train state (ids ascending: checkpoint files must be
// deterministic — hash-set order is not) plus the dead-id tombstones,
// then clear both sets. Atomicity is the point: a row mutated after
// this call re-enters the dirty set and rides the NEXT delta; nothing
// can fall between an export and a separate clear.
//
// Sizing protocol: out_ids == nullptr is a count-only probe — returns
// the dirty count and writes the dead count through out_dead_count,
// clearing nothing. A fill call whose capacities are too small
// returns -3 having written and cleared nothing (the caller re-probes
// and retries). Returns the dirty-row count written, or -1 for an
// unknown table. ``clear`` == 0 keeps both sets (inspection).
int64_t edl_store_export_dirty(void* handle, const char* name,
                               int64_t* out_ids, float* out_values,
                               int64_t* out_steps, int64_t* out_dead,
                               int64_t capacity, int64_t dead_capacity,
                               int64_t* out_dead_count, int clear) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  const int64_t nd = (int64_t)table->dirty_ids.size();
  const int64_t ndead = (int64_t)table->dead_ids.size();
  if (out_ids == nullptr) {
    if (out_dead_count != nullptr) *out_dead_count = ndead;
    return nd;
  }
  if (nd > capacity || ndead > dead_capacity) return -3;
  std::vector<int64_t> ids(table->dirty_ids.begin(),
                           table->dirty_ids.end());
  std::sort(ids.begin(), ids.end());
  const int64_t row_floats = table->dim * (1 + table->slots);
  for (int64_t i = 0; i < nd; ++i) {
    out_ids[i] = ids[i];
    // invariant: every dirty id is resident (drops move ids to dead);
    // belt-and-braces zero fill rather than UB if it ever breaks
    auto it = table->rows.find(ids[i]);
    if (it == table->rows.end()) {
      std::memset(out_values + i * row_floats, 0,
                  sizeof(float) * row_floats);
      out_steps[i] = 0;
      continue;
    }
    std::memcpy(out_values + i * row_floats, it->second.get(),
                sizeof(float) * row_floats);
    auto step_it = table->row_steps.find(ids[i]);
    out_steps[i] =
        step_it == table->row_steps.end() ? 0 : step_it->second;
  }
  std::vector<int64_t> dead(table->dead_ids.begin(),
                            table->dead_ids.end());
  std::sort(dead.begin(), dead.end());
  for (int64_t i = 0; i < ndead; ++i) out_dead[i] = dead[i];
  if (out_dead_count != nullptr) *out_dead_count = ndead;
  if (clear) {
    table->dirty_ids.clear();
    table->dead_ids.clear();
  }
  return nd;
}

// Drop all dirty/dead bookkeeping for a table (taken before a FULL
// base export: the base carries complete state, so pre-base dirt is
// redundant — rows mutated between this clear and the export are
// re-marked and simply ride the next delta too). 0 ok, -1 unknown.
int edl_store_clear_dirty(void* handle, const char* name) {
  Table* table = static_cast<Store*>(handle)->find(name);
  if (table == nullptr) return -1;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  table->dirty_ids.clear();
  table->dead_ids.clear();
  return 0;
}

}  // extern "C"
