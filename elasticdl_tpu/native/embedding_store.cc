// Host-side sparse embedding store with fused optimizer kernels.
//
// TPU-native equivalent of the reference's Go parameter server runtime:
//   - lazy hash-map embedding tables (go/pkg/common/embedding_table.go)
//   - sparse SGD/Momentum/Adagrad/Adam kernels (go/pkg/kernel/capi/
//     kernel_api.cc) — here applied row-wise in-place, slots stored
//     inline with the row so one cache line serves weight+slots
//   - id-sharded binary checkpoints (go/pkg/ps/checkpoint.go)
//
// The dense path of the reference PS is intentionally absent: dense
// parameters live on device, GSPMD-sharded. Only the embedding-id axis
// — unbounded and hash-addressed — stays host-side.
//
// Exposed as a C API for ctypes (no pybind11 in this environment).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

enum class OptType { kSGD = 0, kMomentum = 1, kAdagrad = 2, kAdam = 3 };

// Row initializers (reference go/pkg/common/initializer.go:25-155:
// Zero/Constant/Uniform/Normal/TruncatedNormal). kConstant covers Zero
// via param=0.
enum class InitKind {
  kUniform = 0,         // U(-param, param)
  kConstant = 1,        // fill(param)
  kNormal = 2,          // N(0, param^2)
  kTruncatedNormal = 3  // N(0, param^2) resampled into [-2p, 2p]
};

struct OptConfig {
  OptType type = OptType::kSGD;
  float lr = 0.01f;
  float momentum = 0.9f;
  float beta1 = 0.9f;
  float beta2 = 0.999f;
  float epsilon = 1e-8f;
  // variants (reference go/pkg/ps/optimizer.go supports
  // Momentum+nesterov and Adam+amsgrad)
  bool nesterov = false;
  bool amsgrad = false;
  int slots() const {
    switch (type) {
      case OptType::kSGD: return 0;
      case OptType::kMomentum: return 1;
      case OptType::kAdagrad: return 1;
      case OptType::kAdam: return amsgrad ? 3 : 2;
    }
    return 0;
  }
};

struct Table {
  std::string name;
  int64_t dim = 0;
  float init_scale = 0.05f;
  InitKind init_kind = InitKind::kUniform;
  int slots = 0;
  // row layout: [weight(dim) | slot0(dim) | slot1(dim)]
  std::unordered_map<int64_t, std::unique_ptr<float[]>> rows;
  // Adam per-row step counts for bias correction.
  std::unordered_map<int64_t, int64_t> row_steps;
  // Per-table RNG: only touched under this table's unique lock, so
  // concurrent lookups on different tables never race on RNG state.
  std::mt19937 rng;
  mutable std::shared_mutex mu;

  float* get_or_init(int64_t id) {
    std::mt19937* rng = &this->rng;
    auto it = rows.find(id);
    if (it != rows.end()) return it->second.get();
    auto row = std::make_unique<float[]>(dim * (1 + slots));
    switch (init_kind) {
      case InitKind::kUniform: {
        std::uniform_real_distribution<float> dist(-init_scale, init_scale);
        for (int64_t d = 0; d < dim; ++d) row[d] = dist(*rng);
        break;
      }
      case InitKind::kConstant: {
        for (int64_t d = 0; d < dim; ++d) row[d] = init_scale;
        break;
      }
      case InitKind::kNormal: {
        if (init_scale <= 0.0f) break;  // stddev<=0: zeros (std UB guard)
        std::normal_distribution<float> dist(0.0f, init_scale);
        for (int64_t d = 0; d < dim; ++d) row[d] = dist(*rng);
        break;
      }
      case InitKind::kTruncatedNormal: {
        if (init_scale <= 0.0f) break;
        std::normal_distribution<float> dist(0.0f, init_scale);
        const float bound = 2.0f * init_scale;
        for (int64_t d = 0; d < dim; ++d) {
          float x = dist(*rng);
          while (x < -bound || x > bound) x = dist(*rng);
          row[d] = x;
        }
        break;
      }
    }
    std::memset(row.get() + dim, 0, sizeof(float) * dim * slots);
    float* ptr = row.get();
    rows.emplace(id, std::move(row));
    return ptr;
  }
};

struct Store {
  OptConfig opt;
  std::unordered_map<std::string, std::unique_ptr<Table>> tables;
  uint64_t seed = 0;
  std::mutex tables_mu;
  std::atomic<int64_t> version{0};

  Table* find(const char* name) {
    std::lock_guard<std::mutex> lock(tables_mu);
    auto it = tables.find(name);
    return it == tables.end() ? nullptr : it->second.get();
  }
};

void apply_row(const OptConfig& opt, float* row, const float* grad,
               int64_t dim, float lr, int64_t step) {
  float* w = row;
  switch (opt.type) {
    case OptType::kSGD: {
      for (int64_t d = 0; d < dim; ++d) w[d] -= lr * grad[d];
      break;
    }
    case OptType::kMomentum: {
      float* vel = row + dim;
      if (opt.nesterov) {
        // lookahead step: w -= lr * (g + mu * vel_new)
        for (int64_t d = 0; d < dim; ++d) {
          vel[d] = opt.momentum * vel[d] + grad[d];
          w[d] -= lr * (grad[d] + opt.momentum * vel[d]);
        }
      } else {
        for (int64_t d = 0; d < dim; ++d) {
          vel[d] = opt.momentum * vel[d] + grad[d];
          w[d] -= lr * vel[d];
        }
      }
      break;
    }
    case OptType::kAdagrad: {
      float* acc = row + dim;
      for (int64_t d = 0; d < dim; ++d) {
        acc[d] += grad[d] * grad[d];
        w[d] -= lr * grad[d] / (std::sqrt(acc[d]) + opt.epsilon);
      }
      break;
    }
    case OptType::kAdam: {
      float* m = row + dim;
      float* v = row + 2 * dim;
      float* vmax = opt.amsgrad ? row + 3 * dim : nullptr;
      const float bc1 = 1.0f - std::pow(opt.beta1, (float)step);
      const float bc2 = 1.0f - std::pow(opt.beta2, (float)step);
      for (int64_t d = 0; d < dim; ++d) {
        m[d] = opt.beta1 * m[d] + (1.0f - opt.beta1) * grad[d];
        v[d] = opt.beta2 * v[d] + (1.0f - opt.beta2) * grad[d] * grad[d];
        const float mhat = m[d] / bc1;
        float vv = v[d];
        if (vmax) {
          // amsgrad: denominator uses the running max of v
          vmax[d] = vv > vmax[d] ? vv : vmax[d];
          vv = vmax[d];
        }
        const float vhat = vv / bc2;
        w[d] -= lr * mhat / (std::sqrt(vhat) + opt.epsilon);
      }
      break;
    }
  }
}

}  // namespace

extern "C" {

void* edl_store_create(uint64_t seed) {
  auto* store = new Store();
  store->seed = seed;
  return store;
}

void edl_store_destroy(void* handle) { delete static_cast<Store*>(handle); }

int edl_store_set_optimizer(void* handle, const char* type, float lr,
                            float momentum, float beta1, float beta2,
                            float epsilon) {
  auto* store = static_cast<Store*>(handle);
  {
    // Rows size their slot memory from the optimizer at table-creation
    // time; swapping the optimizer afterwards would make apply_row write
    // past the allocation.
    std::lock_guard<std::mutex> lock(store->tables_mu);
    if (!store->tables.empty()) return -2;
  }
  OptConfig cfg;
  std::string t(type);
  if (t == "sgd") cfg.type = OptType::kSGD;
  else if (t == "momentum") cfg.type = OptType::kMomentum;
  else if (t == "nesterov") { cfg.type = OptType::kMomentum; cfg.nesterov = true; }
  else if (t == "adagrad") cfg.type = OptType::kAdagrad;
  else if (t == "adam") cfg.type = OptType::kAdam;
  else if (t == "amsgrad") { cfg.type = OptType::kAdam; cfg.amsgrad = true; }
  else return -1;
  cfg.lr = lr;
  cfg.momentum = momentum;
  cfg.beta1 = beta1;
  cfg.beta2 = beta2;
  cfg.epsilon = epsilon;
  store->opt = cfg;
  return 0;
}

// init_kind: InitKind value; init_param: scale / constant / stddev.
int edl_store_create_table_init(void* handle, const char* name, int64_t dim,
                                int init_kind, float init_param) {
  if (init_kind < 0 || init_kind > 3) return -2;
  auto* store = static_cast<Store*>(handle);
  std::lock_guard<std::mutex> lock(store->tables_mu);
  auto it = store->tables.find(name);
  if (it != store->tables.end()) {
    if (it->second->dim != dim) return -1;
    // Existing table: adopt the (possibly updated) initializer so a
    // restore-then-register sequence keeps the model's configured init.
    it->second->init_scale = init_param;
    it->second->init_kind = static_cast<InitKind>(init_kind);
    return 0;
  }
  auto table = std::make_unique<Table>();
  table->name = name;
  table->dim = dim;
  table->init_scale = init_param;
  table->init_kind = static_cast<InitKind>(init_kind);
  table->slots = store->opt.slots();
  table->rng.seed(store->seed * 1000003u + std::hash<std::string>{}(name));
  store->tables.emplace(name, std::move(table));
  return 0;
}

int edl_store_create_table(void* handle, const char* name, int64_t dim,
                           float init_scale) {
  return edl_store_create_table_init(
      handle, name, dim, (int)InitKind::kUniform, init_scale);
}

// Batch lookup; missing rows are lazily initialized (the reference's
// GetEmbeddingVector semantics, embedding_table.go:41-58).
int edl_store_lookup(void* handle, const char* name, const int64_t* ids,
                     int64_t n, float* out) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  for (int64_t i = 0; i < n; ++i) {
    const float* row = table->get_or_init(ids[i]);
    std::memcpy(out + i * table->dim, row, sizeof(float) * table->dim);
  }
  return 0;
}

// Sparse apply: grads is [n, dim] row-major, one row per id. lr_scale
// multiplies the configured LR (staleness modulation hook).
int edl_store_push_gradients(void* handle, const char* name,
                             const int64_t* ids, const float* grads,
                             int64_t n, float lr_scale) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  const float lr = store->opt.lr * lr_scale;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  for (int64_t i = 0; i < n; ++i) {
    float* row = table->get_or_init(ids[i]);
    int64_t step = ++table->row_steps[ids[i]];
    apply_row(store->opt, row, grads + i * table->dim, table->dim, lr, step);
  }
  return 0;
}

int64_t edl_store_table_size(void* handle, const char* name) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::shared_lock<std::shared_mutex> lock(table->mu);
  return (int64_t)table->rows.size();
}

int64_t edl_store_version(void* handle) {
  return static_cast<Store*>(handle)->version.load();
}

void edl_store_bump_version(void* handle) {
  static_cast<Store*>(handle)->version.fetch_add(1);
}

// Re-anchor the version clock (PS checkpoint auto-restore): one store,
// not O(version) bump calls at boot.
void edl_store_set_version(void* handle, int64_t version) {
  static_cast<Store*>(handle)->version.store(version);
}

// Export all (id, weight-row) pairs of a table into caller buffers.
// Call with out_ids == nullptr to get the count. Weights-only variant,
// used for serving export and weight inspection; checkpoints use
// edl_store_export_full below so optimizer slot state survives resume.
int64_t edl_store_export(void* handle, const char* name, int64_t* out_ids,
                         float* out_values, int64_t capacity) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::shared_lock<std::shared_mutex> lock(table->mu);
  if (out_ids == nullptr) return (int64_t)table->rows.size();
  int64_t i = 0;
  for (const auto& kv : table->rows) {
    if (i >= capacity) break;
    out_ids[i] = kv.first;
    std::memcpy(out_values + i * table->dim, kv.second.get(),
                sizeof(float) * table->dim);
    ++i;
  }
  return i;
}

// Bulk import rows (checkpoint restore / re-shard). Only ids with
// id % shard_num == shard_id are kept when shard_num > 0.
int edl_store_import(void* handle, const char* name, const int64_t* ids,
                     const float* values, int64_t n, int shard_id,
                     int shard_num) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  for (int64_t i = 0; i < n; ++i) {
    if (shard_num > 0 && (ids[i] % shard_num + shard_num) % shard_num != shard_id)
      continue;
    float* row = table->get_or_init(ids[i]);
    std::memcpy(row, values + i * table->dim, sizeof(float) * table->dim);
  }
  return 0;
}

int edl_store_table_slots(void* handle, const char* name) {
  Table* table = static_cast<Store*>(handle)->find(name);
  return table == nullptr ? -1 : table->slots;
}

// Full-state export: weight+slot rows ([count, (1+slots)*dim] floats)
// plus per-row optimizer step counts. The weights-only export above
// matches the reference's checkpoint content (ps/parameters.py:194-199
// drops slots); this variant closes that gap so a resumed Adam/Adagrad
// continues from its exact slot state instead of restarting bias
// correction (SURVEY.md s7 "optimizer-state checkpointing").
int64_t edl_store_export_full(void* handle, const char* name,
                              int64_t* out_ids, float* out_values,
                              int64_t* out_steps, int64_t capacity) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  std::shared_lock<std::shared_mutex> lock(table->mu);
  if (out_ids == nullptr) return (int64_t)table->rows.size();
  const int64_t row_floats = table->dim * (1 + table->slots);
  int64_t i = 0;
  for (const auto& kv : table->rows) {
    if (i >= capacity) break;
    out_ids[i] = kv.first;
    std::memcpy(out_values + i * row_floats, kv.second.get(),
                sizeof(float) * row_floats);
    auto step_it = table->row_steps.find(kv.first);
    out_steps[i] = step_it == table->row_steps.end() ? 0 : step_it->second;
    ++i;
  }
  return i;
}

// Full-state import. row_floats must equal (1+slots)*dim for the
// CURRENT optimizer; on mismatch (optimizer changed between save and
// restore) only the leading weight segment is imported and steps are
// dropped — degrading to the weights-only semantics instead of failing.
int edl_store_import_full(void* handle, const char* name,
                          const int64_t* ids, const float* values,
                          const int64_t* steps, int64_t n,
                          int64_t row_floats, int shard_id, int shard_num) {
  auto* store = static_cast<Store*>(handle);
  Table* table = store->find(name);
  if (table == nullptr) return -1;
  if (row_floats < table->dim) return -2;
  std::unique_lock<std::shared_mutex> lock(table->mu);
  const int64_t full = table->dim * (1 + table->slots);
  const bool exact = row_floats == full;
  for (int64_t i = 0; i < n; ++i) {
    if (shard_num > 0 && (ids[i] % shard_num + shard_num) % shard_num != shard_id)
      continue;
    float* row = table->get_or_init(ids[i]);
    std::memcpy(row, values + i * row_floats,
                sizeof(float) * (exact ? full : table->dim));
    if (exact && steps != nullptr) table->row_steps[ids[i]] = steps[i];
  }
  return 0;
}

}  // extern "C"
