"""Client-side trace-context propagation over gRPC metadata.

The worker's train-step root span (or the serve tier's per-request
root span) lives in a thread-local (``observability/trace.py``); this
interceptor serializes the active ``SpanContext`` as W3C-traceparent
text under the ``edl-traceparent`` metadata key on every outgoing
unary-unary RPC, so the server handler (``trace.traced_handler``) can
open a child span of the exact RPC attempt that reached it. Wired
through ``common/grpc_utils.build_channel`` — the same seam the fault
injector uses — so every stub in the repo propagates without per-call
plumbing.

**Provably inert when off**: ``intercept_trace_channel`` returns the
channel object it was given when ``EDL_TRACE_DIR`` is unset or
``EDL_TRACE_SAMPLE`` is 0 — no wrapper, no per-call branch, and
therefore no metadata on the wire (the ISSUE 9 overhead acceptance).
The only steady-state cost is one env read per channel BUILD. With the
interceptor installed, a call outside any trace pays a single
thread-local read.
"""

import collections

import grpc

from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.observability import trace


class _CallDetails(
    collections.namedtuple(
        "_CallDetails",
        ("method", "timeout", "metadata", "credentials",
         "wait_for_ready", "compression"),
    ),
    grpc.ClientCallDetails,
):
    """ClientCallDetails replacement carrying amended metadata (the
    stock namedtuple recipe from the grpc interceptor docs)."""


class TraceContextClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    """Injects the active span context; adds nothing when the calling
    thread is outside any trace. The ``sampled=0`` flag propagates too:
    a head-unsampled trace must tell remote roles NOT to record, or
    tail-keep decisions made at the root would disagree with orphaned
    remote spans."""

    def intercept_unary_unary(self, continuation, client_call_details,
                              request):
        ctx = trace.current_context()
        if ctx is None:
            return continuation(client_call_details, request)
        metadata = list(client_call_details.metadata or ())
        metadata.append((trace.METADATA_KEY, ctx.to_traceparent()))
        details = _CallDetails(
            client_call_details.method,
            client_call_details.timeout,
            metadata,
            getattr(client_call_details, "credentials", None),
            getattr(client_call_details, "wait_for_ready", None),
            getattr(client_call_details, "compression", None),
        )
        return continuation(details, request)


def intercept_trace_channel(channel):
    """The channel itself when tracing is disabled or head sampling is
    0 (no trace can ever need propagation); a context-propagating
    wrapper otherwise."""
    if not env_str(trace.TRACE_DIR_ENV, ""):
        return channel
    if trace.sample_rate() <= 0.0:
        return channel
    return grpc.intercept_channel(channel, TraceContextClientInterceptor())
