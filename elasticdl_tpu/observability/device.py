"""Device-runtime observability (ISSUE 18): the XLA side of the job.

Every observability layer before this one watches the HOST — Python
stacks, RPCs, locks, loss scalars. This module watches the device
runtime through three instruments:

1. **Recompile sentinels** — ``instrumented_jit`` wraps ``jax.jit``
   and detects, per wrapped step function, whether each call hit the
   compiled-executable cache or compiled: the jit object's cache size
   moves exactly when a new argument signature compiled. A compile
   records a compile-time histogram sample, a ``compile`` span into
   the PR 9 tracer, and the *shape/dtype provenance* of the new
   signature; a RE-compile (any compile after the wrapper's first)
   additionally journals an ``xla_recompile`` event carrying which
   leaves changed — the flight-recorder answer to "why did step 4127
   take 40 s".
2. **Device-memory accounting** — ``memory_snapshot`` reads the
   runtime allocator (``device.memory_stats()``) where it exists and
   falls back to walking ``jax.live_arrays()`` on backends without an
   HBM allocator (CPU CI), keeping a process-lifetime peak watermark.
   ``EDL_HBM_LIMIT_BYTES`` supplies a synthetic limit where the
   backend reports none, so the ``hbm_pressure`` fleet alert is
   drillable on any box.
3. **Cost-model step attribution** — on a compile the wrapper
   opportunistically AOT-relowers the function
   (``jitted.lower(*args).compile()`` — cheap after the real compile
   warmed XLA, measured ~25 ms vs ~130 ms cold on CPU) and keeps the
   executable's ``cost_analysis()`` FLOPs/bytes. The worker's MFU
   bridge consumes these instead of the hand-coded per-model table,
   and host↔device ``transfer`` counters/spans let
   ``scripts/critical_path.py`` attribute a ``transfer`` segment.

Disabled path (``EDL_DEVICE_OBS=0``): ``instrumented_jit`` returns the
**raw ``jax.jit`` product, unchanged** — no wrapper frame, no per-call
bookkeeping, no module state, no extra metric series or events. The
factory-default program is byte-identical to the pre-ISSUE-18 one
(test-asserted in tests/test_device_obs.py).

Knobs (all via common/env_utils, documented in docs/OBSERVABILITY.md):

- ``EDL_DEVICE_OBS``            (default 1) master gate
- ``EDL_DEVICE_COST_ANALYSIS``  (default 1) AOT cost/memory fetch per
  compile, capped at ``_COST_FETCH_CAP`` per wrapper
- ``EDL_HBM_LIMIT_BYTES``       (default 0) synthetic allocator limit
  for backends whose ``memory_stats()`` reports none
"""

import contextlib
import threading
import time
import weakref

from elasticdl_tpu.common.env_utils import env_bool, env_int
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import events
from elasticdl_tpu.observability import metrics as obs_metrics
from elasticdl_tpu.observability import trace

logger = _logger_factory("elasticdl_tpu.observability.device")

DEVICE_OBS_ENV = "EDL_DEVICE_OBS"
COST_ANALYSIS_ENV = "EDL_DEVICE_COST_ANALYSIS"
HBM_LIMIT_ENV = "EDL_HBM_LIMIT_BYTES"

# AOT cost-analysis relowers per wrapper: each fetch costs one extra
# (warm) XLA compile, so a shape-churning wrapper must not turn the
# sentinel into a compile amplifier
_COST_FETCH_CAP = 8
# provenance payload bounds: journal lines are read by humans and the
# postmortem, not parsed exhaustively
_PROVENANCE_CHANGED_MAX = 8
_PROVENANCE_SIG_MAX = 16

_lock = threading.Lock()
# live wrappers (weak: the device tier rebuilds its jit cache on PS
# restart and the dead wrappers must not pin memory or double-count)
_wrappers = []
# process-lifetime cumulative totals — monotonic even across wrapper
# rebuilds, which is what the fleet recompile_storm detector needs
_totals = {
    "compiles": 0,
    "recompiles": 0,
    "compile_secs": 0.0,
    "h2d_bytes": 0,
    "d2h_bytes": 0,
}
_hbm_peak = 0  # host-side watermark across memory_snapshot() polls

# instruments hoisted to module scope (obs-hot-path discipline): the
# registry returns NOOPs when metrics collection is off. LAZY: the
# trainers import this module before a role's main() publishes
# EDL_METRICS_PORT; an eager counter() here would freeze the process
# registry disabled and blank /metrics for the whole role.
_m_compiles = obs_metrics.lazy_counter(
    "edl_xla_compiles_total",
    "XLA compiles (new argument signatures) per wrapped step fn",
    ("fn",),
)
_m_recompiles = obs_metrics.lazy_counter(
    "edl_xla_recompiles_total",
    "XLA compiles beyond each wrapped step fn's first",
    ("fn",),
)
_m_cache_hits = obs_metrics.lazy_counter(
    "edl_xla_cache_hits_total",
    "Calls served by the jit executable cache per wrapped step fn",
    ("fn",),
)
_m_compile_secs = obs_metrics.lazy_histogram(
    "edl_xla_compile_seconds",
    "Wall seconds of calls that compiled (trace+compile+run)",
    buckets=(0.05, 0.25, 1.0, 5.0, 20.0, 60.0, 180.0),
)
_m_transfer_bytes = obs_metrics.lazy_counter(
    "edl_device_transfer_bytes_total",
    "Host<->device transfer bytes attributed by direction",
    ("direction",),
)
_m_hbm_in_use = obs_metrics.lazy_gauge(
    "edl_device_hbm_bytes_in_use",
    "Device-memory bytes in use (allocator stats, or live-buffer "
    "fallback where the backend has no allocator)",
)
_m_hbm_peak = obs_metrics.lazy_gauge(
    "edl_device_hbm_peak_bytes",
    "Peak device-memory bytes observed (allocator peak, or the "
    "process-lifetime watermark of the fallback)",
)
_m_live_buffers = obs_metrics.lazy_gauge(
    "edl_device_live_buffers",
    "Live device arrays held by this process",
)


def device_obs_enabled():
    """The master gate: EDL_DEVICE_OBS=0 switches every path in this
    module off and makes ``instrumented_jit`` a pure ``jax.jit``."""
    return env_bool(DEVICE_OBS_ENV, True)


def _leaf_spec(leaf):
    """``f32[32,10]``-style spec for one argument leaf; scalars and
    static oddities render as their type name (they still churn the
    cache when they change, so they belong in the provenance)."""
    dtype = getattr(leaf, "dtype", None)
    shape = getattr(leaf, "shape", None)
    if dtype is not None and shape is not None:
        try:
            import jax

            short = jax.dtypes.canonicalize_dtype(dtype).name
        except Exception as e:
            logger.debug("dtype canonicalize failed for %r: %s", dtype, e)
            short = str(dtype)
        return "%s[%s]" % (short, ",".join(str(d) for d in shape))
    return type(leaf).__name__


def _signature(args, kwargs):
    """{leaf path: spec} of a call's arguments, plus the total bytes of
    HOST-resident (numpy) leaves — the h2d payload this signature
    uploads per call."""
    import jax
    import numpy as np

    sig = {}
    host_bytes = 0
    leaves = jax.tree_util.tree_flatten_with_path((args, kwargs))[0]
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path)
        sig[key] = _leaf_spec(leaf)
        if isinstance(leaf, np.ndarray):
            host_bytes += leaf.nbytes
    return sig, host_bytes


def _diff_signatures(old, new):
    """Provenance of a recompile: which leaves changed spec, appeared,
    or vanished relative to the previous compiled signature."""
    changed = []
    for key in sorted(set(old) | set(new)):
        before = old.get(key)
        after = new.get(key)
        if before != after:
            changed.append(
                "%s: %s -> %s" % (key, before or "absent", after or "gone")
            )
    return changed


class _InstrumentedJit:
    """One ``jax.jit`` product plus its sentinel books.

    Per call the steady-state cost is one clock read, the jit call
    itself, one C++ ``_cache_size()`` probe, a counter inc, and two
    integer adds — the 2 % overhead contract in
    scripts/bench_device_obs_overhead.py rides on that list staying
    exactly this short. Signature flattening, provenance diffs, trace
    emission, and the AOT cost fetch all happen only on calls that
    compiled.
    """

    def __init__(self, fn, name, jit_kwargs):
        import jax

        self._jitted = jax.jit(fn, **jit_kwargs)
        self.name = name
        self.compiles = 0
        self.cache_hits = 0
        self.compile_secs = 0.0
        self.last_compile_secs = 0.0
        self.cost_flops = 0.0
        self.cost_bytes = 0.0
        self._cost_fetches = 0
        self._cost_on = env_bool(COST_ANALYSIS_ENV, True)
        self._cache_size = 0
        self._last_sig = None
        self._sig_host_bytes = 0
        self.last_changed = []
        self._m_compiles = _m_compiles.labels(fn=name)
        self._m_recompiles = _m_recompiles.labels(fn=name)
        self._m_hits = _m_cache_hits.labels(fn=name)
        with _lock:
            _wrappers.append(weakref.ref(self))

    @property
    def recompiles(self):
        return max(0, self.compiles - 1)

    def __call__(self, *args, **kwargs):
        t0 = time.time()
        out = self._jitted(*args, **kwargs)
        size = self._jitted._cache_size()
        if size == self._cache_size:
            self.cache_hits += 1
            self._m_hits.inc()
            if self._sig_host_bytes:
                with _lock:
                    _totals["h2d_bytes"] += self._sig_host_bytes
        else:
            self._cache_size = size
            self._on_compile(time.time() - t0, t0, args, kwargs)
        return out

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def __getattr__(self, item):
        # AOT/introspection passthrough (eval_shape, clear_cache, ...)
        return getattr(self._jitted, item)

    # -- compile path (rare by contract) -------------------------------

    def _on_compile(self, elapsed, t0, args, kwargs):
        self.compiles += 1
        self.compile_secs += elapsed
        self.last_compile_secs = elapsed
        recompile = self.compiles > 1
        sig, host_bytes = _signature(args, kwargs)
        self._sig_host_bytes = host_bytes
        changed = (
            _diff_signatures(self._last_sig, sig) if recompile else []
        )
        self._last_sig = sig
        self.last_changed = changed
        self._m_compiles.inc()
        _m_compile_secs.observe(elapsed)
        with _lock:
            _totals["compiles"] += 1
            _totals["compile_secs"] += elapsed
            _totals["h2d_bytes"] += host_bytes
            if recompile:
                _totals["recompiles"] += 1
        trace.complete(
            "compile", t0, fn=self.name, seconds=round(elapsed, 4),
            recompile=recompile,
            changed=changed[:_PROVENANCE_CHANGED_MAX],
        )
        if recompile:
            self._m_recompiles.inc()
            logger.warning(
                "xla recompile #%d of %s (%.2fs): %s",
                self.recompiles, self.name, elapsed,
                "; ".join(changed[:_PROVENANCE_CHANGED_MAX]) or
                "signature unchanged at leaf level",
            )
            events.emit(
                "xla_recompile",
                fn=self.name,
                compiles=self.compiles,
                seconds=round(elapsed, 4),
                changed=changed[:_PROVENANCE_CHANGED_MAX],
                signature=sorted(
                    "%s=%s" % kv for kv in sig.items()
                )[:_PROVENANCE_SIG_MAX],
            )
        if self._cost_on and self._cost_fetches < _COST_FETCH_CAP:
            self._fetch_cost(args, kwargs)

    def _fetch_cost(self, args, kwargs):
        """Executable-reported FLOPs/bytes for the signature that just
        compiled. ``lower().compile()`` after the real call re-runs
        tracing + compilation against a warm XLA (~25 ms on CPU, not a
        second cold compile) and never touches the jit call cache;
        donated-and-consumed arguments are fine (lowering reads only
        avals). Unavailable backends simply leave the table fallback
        in charge."""
        self._cost_fetches += 1
        try:
            compiled = self._jitted.lower(*args, **kwargs).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            self.cost_flops = float(cost.get("flops", 0.0) or 0.0)
            self.cost_bytes = float(
                cost.get("bytes accessed", 0.0) or 0.0
            )
        except Exception as e:
            logger.debug("cost analysis unavailable for %s: %s",
                         self.name, e)


def instrumented_jit(fn, name=None, **jit_kwargs):
    """``jax.jit`` with the recompile sentinel attached — the ONLY
    sanctioned jit entry point in train/ops/serve scopes (edlint rule
    ``obs-bare-jit``). With ``EDL_DEVICE_OBS=0`` this *is* ``jax.jit``:
    the raw PjitFunction comes back untouched."""
    if not device_obs_enabled():
        import jax

        return jax.jit(fn, **jit_kwargs)
    return _InstrumentedJit(
        fn, name or getattr(fn, "__name__", "step_fn"), jit_kwargs
    )


# ---------------------------------------------------------------------------
# host<->device transfer attribution

def record_transfer(direction, nbytes):
    """Fold ``nbytes`` of attributed transfer into the counters
    (direction ``"h2d"`` or ``"d2h"``)."""
    if not device_obs_enabled() or nbytes <= 0:
        return
    _m_transfer_bytes.labels(direction=direction).inc(nbytes)
    with _lock:
        _totals["%s_bytes" % direction] += int(nbytes)


@contextlib.contextmanager
def transfer_span(direction, nbytes=0):
    """Time a host-blocking transfer (the ``np.asarray`` fetch of row
    grads, an eval-output device_get) as a ``transfer`` span — the span
    name scripts/critical_path.py maps to its ``transfer`` segment —
    and count its bytes. Inert when device obs is off."""
    if not device_obs_enabled():
        yield
        return
    t0 = time.time()
    try:
        yield
    finally:
        record_transfer(direction, nbytes)
        trace.complete(
            "transfer", t0, direction=direction, bytes=int(nbytes)
        )


# ---------------------------------------------------------------------------
# device-memory accounting

def memory_snapshot():
    """Allocator view of this process's device memory, JSON-ready.

    ``source`` is ``"allocator"`` where ``device.memory_stats()``
    exists (TPU/GPU), ``"live_arrays"`` on backends without one (CPU
    CI): there the in-use number is the sum of live jax array nbytes
    and the peak is a host-side watermark across polls. ``limit``
    comes from the allocator, or ``EDL_HBM_LIMIT_BYTES`` when it
    reports none."""
    global _hbm_peak
    if not device_obs_enabled():
        return {}
    import jax

    in_use = 0
    peak = 0
    limit = 0
    source = "live_arrays"
    try:
        for dev in jax.local_devices():
            stats = dev.memory_stats() or {}
            if stats.get("bytes_in_use") is not None:
                source = "allocator"
                in_use += int(stats.get("bytes_in_use", 0))
                peak += int(stats.get("peak_bytes_in_use", 0))
                limit += int(stats.get("bytes_limit", 0))
    except Exception as e:
        # degrade to the live-array fallback below; a backend without
        # allocator stats is the expected CPU case, not a fault
        logger.debug("allocator memory_stats unavailable: %s", e)
    arrays = 0
    try:
        live = jax.live_arrays()
        arrays = len(live)
        if source != "allocator":
            in_use = sum(getattr(a, "nbytes", 0) for a in live)
    except Exception as e:
        logger.debug("live_arrays unavailable: %s", e)
    with _lock:
        if in_use > _hbm_peak:
            _hbm_peak = in_use
        if source != "allocator":
            peak = _hbm_peak
    if limit <= 0:
        limit = env_int(HBM_LIMIT_ENV, 0)
    _m_hbm_in_use.set(in_use)
    _m_hbm_peak.set(peak)
    _m_live_buffers.set(arrays)
    return {
        "bytes_in_use": int(in_use),
        "peak_bytes": int(peak),
        "limit_bytes": int(limit),
        "live_buffers": int(arrays),
        "source": source,
    }


# ---------------------------------------------------------------------------
# aggregation (telemetry-RPC rate, never per step)

def _live_wrappers():
    with _lock:
        refs = list(_wrappers)
    alive = []
    dead = False
    for ref in refs:
        wrapper = ref()
        if wrapper is None:
            dead = True
        else:
            alive.append(wrapper)
    if dead:
        with _lock:
            _wrappers[:] = [r for r in _wrappers if r() is not None]
    return alive


def compile_stats():
    """Per-wrapper sentinel books: {name: {...}} for live wrappers.
    Same-named wrappers (the SPMD per-structure jit caches) fold."""
    stats = {}
    for wrapper in _live_wrappers():
        entry = stats.setdefault(wrapper.name, {
            "compiles": 0, "recompiles": 0, "cache_hits": 0,
            "compile_secs": 0.0, "last_compile_secs": 0.0,
            "cost_flops": 0.0, "cost_bytes": 0.0, "last_changed": [],
        })
        entry["compiles"] += wrapper.compiles
        entry["recompiles"] += wrapper.recompiles
        entry["cache_hits"] += wrapper.cache_hits
        entry["compile_secs"] = round(
            entry["compile_secs"] + wrapper.compile_secs, 4
        )
        entry["last_compile_secs"] = max(
            entry["last_compile_secs"],
            round(wrapper.last_compile_secs, 4),
        )
        entry["cost_flops"] += wrapper.cost_flops
        entry["cost_bytes"] += wrapper.cost_bytes
        if wrapper.last_changed:
            entry["last_changed"] = wrapper.last_changed[
                :_PROVENANCE_CHANGED_MAX
            ]
    return stats


def telemetry():
    """The device section of a role's TelemetryBlob: cumulative
    process-lifetime compile/transfer totals + a fresh memory
    snapshot. Called on the RPC path (telemetry provider), never per
    step; empty dict when device obs is off."""
    if not device_obs_enabled():
        return {}
    with _lock:
        totals = dict(_totals)
    mem = memory_snapshot()
    return {
        "xla_compiles": int(totals["compiles"]),
        "xla_recompiles": int(totals["recompiles"]),
        "xla_compile_secs_total": round(totals["compile_secs"], 4),
        "hbm_bytes_in_use": mem.get("bytes_in_use", 0),
        "hbm_peak_bytes": mem.get("peak_bytes", 0),
        "hbm_limit_bytes": mem.get("limit_bytes", 0),
        "device_live_buffers": mem.get("live_buffers", 0),
        "h2d_bytes": int(totals["h2d_bytes"]),
        "d2h_bytes": int(totals["d2h_bytes"]),
    }


def reset_for_tests():
    """Test isolation only: drop wrapper registry and totals."""
    global _hbm_peak
    with _lock:
        _wrappers[:] = []
        for key in _totals:
            _totals[key] = 0.0 if key == "compile_secs" else 0
        _hbm_peak = 0
