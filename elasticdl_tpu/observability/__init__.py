"""Observability subsystem: metrics registry, health endpoints, RPC
instrumentation, and the cross-role task trace.

The reference framework's tracing story is "minimal" (SURVEY §5): a
per-phase wall-clock accumulator dumped at DEBUG. A production elastic
job needs to answer "why is the round not filling", "which worker is
slow", and "is the PS saturated" while the job runs:

- ``metrics``      — stdlib-only Counter/Gauge/Histogram + a
                     process-global registry with Prometheus text
                     exposition (no prometheus_client dependency).
- ``http_server``  — /metrics, /healthz, /readyz daemon served from
                     every role on ``--metrics_port``/``EDL_METRICS_PORT``
                     (0 = disabled, the default).
- ``grpc_metrics`` — server/client interceptors recording per-method
                     request counters, error-code counters, and latency
                     histograms for all Master and Pserver RPCs.
- ``trace``        — lightweight span API buffering Chrome trace-event
                     JSON per role under ``EDL_TRACE_DIR``; task_id is
                     the correlation key and ``scripts/merge_trace.py``
                     stitches the roles onto one Perfetto timeline.
"""

from elasticdl_tpu.observability import metrics  # noqa: F401
