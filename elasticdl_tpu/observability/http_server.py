"""Metrics exposition + liveness/readiness endpoints for every role.

A stdlib ``http.server`` daemon thread serving:

- ``GET /metrics`` — Prometheus text format 0.0.4 from the registry
- ``GET /healthz`` — liveness: 200 while the process serves at all
- ``GET /readyz``  — readiness: 200 only when every registered
  role-specific check passes (master → servicer started; PS → model
  initialized; worker → master channel ready), else 503 listing the
  failing checks — the pod manager's signal to hold traffic, not
  restart.
- ``GET /profilez`` — the continuous profiler (ISSUE 14): the rolling
  ring snapshot by default, ``?seconds=N`` for an on-demand window
  capture, ``&format=collapsed`` for flamegraph-ready text instead of
  JSON. Answers 404 when the profiler is disabled (``EDL_PROF_HZ``
  unset) — the disabled state must be visible, not an empty profile.
- role-registered JSON endpoints (``add_json_handler``): the master
  mounts ``/statusz`` (full fleet telemetry snapshot) and ``/alerts``
  (firing anomaly detectors) here — see master/fleet.py.

Knobs: ``--metrics_port`` on each role's CLI, falling back to
``EDL_METRICS_PORT``; 0 (the default) starts nothing, so tests/CI and
benchmarks are unaffected unless they opt in.
"""

import http.server
import json
import threading
import urllib.parse

from elasticdl_tpu.common.env_utils import env_int, env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import metrics as metrics_mod

logger = _logger_factory("elasticdl_tpu.observability.http_server")

PORT_ENV = metrics_mod.PORT_ENV

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)
EXEMPLARS_ENV = metrics_mod.EXEMPLARS_ENV


def resolve_port(cli_port=None):
    """Effective metrics port: CLI flag wins, then EDL_METRICS_PORT,
    then 0 (disabled)."""
    if cli_port:
        return int(cli_port)
    return env_int(PORT_ENV, 0)


class ObservabilityServer:
    """Daemon-thread HTTP server for one role's /metrics + probes."""

    def __init__(self, role, port, registry=None):
        self.role = role
        self.port = int(port)
        self.registry = registry or metrics_mod.default_registry()
        self._checks = []  # [(name, callable -> bool)]
        self._json_handlers = {}  # path -> callable -> JSON-able obj
        self._httpd = None
        self._thread = None
        self.registry.gauge(
            "edl_up", "1 while the role's process is serving", ("role",)
        ).labels(role=role).set(1)

    def add_readiness_check(self, name, check):
        """``check()`` -> truthy when this aspect of the role is ready.
        A check that raises counts as not ready."""
        self._checks.append((name, check))

    def add_json_handler(self, path, fn):
        """Serve ``fn()`` (any JSON-serializable object) on GET
        ``path``. A raising handler answers 500 with the error text —
        a broken snapshot source must not take the whole server down."""
        self._json_handlers[path] = fn

    def readiness(self):
        """(ready, [failing check names])."""
        failing = []
        for name, check in self._checks:
            try:
                ok = bool(check())
            except Exception as e:
                logger.warning("readiness check %s raised: %s", name, e)
                ok = False
            if not ok:
                failing.append(name)
        return not failing, failing

    # ------------------------------------------------------------------
    def start(self):
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    # exemplars (ISSUE 9) ride only the content-
                    # negotiated OpenMetrics path or the explicit env
                    # opt-in. Negotiation is deliberately EXCLUSIVE: a
                    # stock Prometheus advertises openmetrics AND a
                    # text/plain fallback in its default Accept, and
                    # switching it onto this pragmatic exposition
                    # (0.0.4 naming + exemplar suffixes) would regress
                    # a consumer that parsed fine yesterday — so any
                    # client offering a text/plain fallback gets plain
                    # 0.0.4, and only a deliberate openmetrics-only
                    # Accept (an operator chasing an exemplar) switches.
                    accept = self.headers.get("Accept", "") or ""
                    negotiated = (
                        "application/openmetrics-text" in accept
                        and "text/plain" not in accept
                    )
                    env_gated = env_str(
                        EXEMPLARS_ENV, ""
                    ) not in ("", "0")
                    text = server.registry.render(
                        exemplars=negotiated or env_gated
                    )
                    content_type = CONTENT_TYPE
                    if negotiated:
                        text += "# EOF\n"
                        content_type = OPENMETRICS_CONTENT_TYPE
                    self._reply(200, text.encode("utf-8"), content_type)
                elif path == "/healthz":
                    self._reply(200, b"ok\n")
                elif path == "/readyz":
                    ready, failing = server.readiness()
                    if ready:
                        self._reply(200, b"ready\n")
                    else:
                        self._reply(
                            503,
                            ("unready: %s\n" % ",".join(failing)).encode(),
                        )
                elif path == "/profilez":
                    self._serve_profilez()
                elif path in server._json_handlers:
                    try:
                        body = json.dumps(
                            server._json_handlers[path]()
                        ).encode("utf-8")
                    except Exception as e:
                        # a broken snapshot source degrades to a 500,
                        # never takes the probe server down
                        logger.warning("%s handler failed: %s", path, e)
                        self._reply(
                            500, ("error: %s\n" % e).encode("utf-8")
                        )
                        return
                    self._reply(200, body, "application/json")
                else:
                    self._reply(404, b"not found\n")

            def _serve_profilez(self):
                # imported lazily: the probe server must not pull the
                # profiler module in for roles that never profile
                from elasticdl_tpu.observability import profiler

                sampler = profiler.sampler()
                if sampler is None:
                    self._reply(
                        404,
                        b"profiler disabled (set EDL_PROF_HZ)\n",
                    )
                    return
                query = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query
                )
                try:
                    seconds = float(query.get("seconds", ["0"])[0] or 0)
                except ValueError:
                    self._reply(400, b"bad seconds parameter\n")
                    return
                fmt = query.get("format", ["json"])[0]
                if fmt not in ("json", "collapsed"):
                    self._reply(
                        400, b"format must be json or collapsed\n"
                    )
                    return
                try:
                    # a window capture blocks only THIS handler thread
                    # (ThreadingHTTPServer); probes keep answering
                    snap = (
                        sampler.capture(seconds)
                        if seconds > 0
                        else sampler.snapshot()
                    )
                except Exception as e:
                    logger.warning("/profilez failed: %s", e)
                    self._reply(
                        500, ("error: %s\n" % e).encode("utf-8")
                    )
                    return
                if fmt == "collapsed":
                    self._reply(
                        200, profiler.collapsed(snap).encode("utf-8")
                    )
                else:
                    self._reply(
                        200,
                        json.dumps(snap).encode("utf-8"),
                        "application/json",
                    )

            def _reply(self, status, body, content_type="text/plain"):
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # probe traffic must not spam the job log

        self._httpd = http.server.ThreadingHTTPServer(
            ("0.0.0.0", self.port), Handler
        )
        self._httpd.daemon_threads = True
        # port may have been 0-adjacent (tests pass an ephemeral 0 via
        # explicit Server construction); record what the OS gave us
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="edl-observability-%s" % self.role,
            daemon=True,
        )
        self._thread.start()
        logger.info(
            "%s observability on :%d (/metrics /healthz /readyz)",
            self.role, self.port,
        )
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def maybe_start(role, cli_port=None, registry=None):
    """Start an ObservabilityServer when a port is configured; None
    otherwise. The single call every role entry point makes."""
    port = resolve_port(cli_port)
    if port <= 0:
        return None
    try:
        return ObservabilityServer(role, port, registry=registry).start()
    except OSError as e:
        # a busy port must not kill the job — telemetry is best-effort
        logger.warning(
            "could not start %s observability server on :%d: %s",
            role, port, e,
        )
        return None
