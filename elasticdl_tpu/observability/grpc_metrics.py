"""gRPC server/client interceptors feeding the metrics registry.

Per-method request counters (labeled by status code), and latency
histograms for every Master and Pserver RPC. The PR-1 deadline
discipline put a ``timeout=`` on every stub call; these interceptors
make the misses visible — a DEADLINE_EXCEEDED is a counted series on
the client graph, not just a log line.

Series (all labeled ``service``, ``method``; counters also ``code``):

- ``edl_grpc_server_handled_total`` / ``edl_grpc_server_latency_seconds``
- ``edl_grpc_client_handled_total`` / ``edl_grpc_client_latency_seconds``

Known method series are pre-registered at interceptor construction so
``/metrics`` exposes every RPC's histogram at zero before first
traffic (probes and dashboards see a stable series set).

Installed by ``common/grpc_utils.build_server`` (server side, via
``server_interceptors()``) and the worker/PS channel builders
(``instrument_channel``). When metrics are disabled (EDL_METRICS=0)
both helpers are no-ops: no interceptor sits on the hot path at all.
"""

import time

import grpc

from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.observability import metrics
from elasticdl_tpu.observability import trace


def _split_method(full_method):
    """"/elasticdl_tpu.Master/get_task" -> ("Master", "get_task")."""
    try:
        _, service, method = full_method.split("/")
        return service.rsplit(".", 1)[-1], method
    except ValueError:
        return "unknown", full_method


def _known_methods():
    """[(service short name, method name)] for every RPC we serve."""
    from elasticdl_tpu.proto import services

    return [
        ("Master", name) for name in services._MASTER_METHODS
    ] + [
        ("Pserver", name) for name in services._PSERVER_METHODS
    ] + [
        ("Serve", name) for name in services._SERVE_METHODS
    ]


class ServerMetricsInterceptor(grpc.ServerInterceptor):
    """Counts + times every unary-unary RPC a server handles."""

    def __init__(self, registry=None, preregister=None):
        reg = registry or metrics.default_registry()
        self._handled = reg.counter(
            "edl_grpc_server_handled_total",
            "RPCs handled by this server, by method and status code",
            ("service", "method", "code"),
        )
        self._latency = reg.histogram(
            "edl_grpc_server_latency_seconds",
            "Server-side RPC handling latency",
            ("service", "method"),
        )
        for service, method in (
            _known_methods() if preregister is None else preregister
        ):
            self._handled.labels(service=service, method=method, code="OK")
            self._latency.labels(service=service, method=method)

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or not handler.unary_unary:
            return handler  # only unary-unary RPCs exist in this proto
        service, method = _split_method(handler_call_details.method)
        inner = handler.unary_unary
        handled = self._handled
        latency = self._latency

        def wrapped(request, context):
            start = time.perf_counter()
            code = "OK"
            try:
                return inner(request, context)
            except BaseException:
                # an abort() raises after set_code; a servicer bug
                # surfaces as UNKNOWN on the wire — count it as such
                code = "UNKNOWN"
                raise
            finally:
                latency.labels(service=service, method=method).observe(
                    time.perf_counter() - start
                )
                handled.labels(
                    service=service, method=method, code=code
                ).inc()

        traced = trace.traced_handler(wrapped, service, method)
        return grpc.unary_unary_rpc_method_handler(
            traced,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


class ClientMetricsInterceptor(grpc.UnaryUnaryClientInterceptor):
    """Counts + times every unary-unary RPC a channel issues. The
    status-code label is where deadline misses become visible:
    ``code="DEADLINE_EXCEEDED"`` is a graphable series."""

    def __init__(self, registry=None, preregister=None):
        reg = registry or metrics.default_registry()
        self._handled = reg.counter(
            "edl_grpc_client_handled_total",
            "RPCs issued by this process, by method and status code",
            ("service", "method", "code"),
        )
        self._latency = reg.histogram(
            "edl_grpc_client_latency_seconds",
            "Client-side RPC latency (includes retries' individual calls)",
            ("service", "method"),
        )
        for service, method in (
            _known_methods() if preregister is None else preregister
        ):
            self._handled.labels(service=service, method=method, code="OK")
            self._latency.labels(service=service, method=method)

    def intercept_unary_unary(self, continuation, client_call_details,
                              request):
        service, method = _split_method(client_call_details.method)
        start = time.perf_counter()
        outcome = continuation(client_call_details, request)
        elapsed = time.perf_counter() - start
        try:
            code = outcome.code()
            code_name = code.name if code is not None else "OK"
        # a future-like outcome without a synchronous code() must not
        # break the RPC; the counter degrades to UNKNOWN
        except Exception:  # edlint: disable=ft-swallowed-except
            code_name = "UNKNOWN"
        self._latency.labels(service=service, method=method).observe(
            elapsed
        )
        self._handled.labels(
            service=service, method=method, code=code_name
        ).inc()
        return outcome


# ---------------------------------------------------------------------------
# install helpers (the only API the wiring code uses)

# (registry, interceptor): rebuilt when the default registry is reset
# (tests flip collection on/off within one process)
_client_cache = (None, None)


class TraceServerInterceptor(grpc.ServerInterceptor):
    """Span-only interceptor for trace-without-metrics runs (the
    metrics interceptor already traces; this keeps EDL_TRACE_DIR
    useful when metrics collection is off)."""

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or not handler.unary_unary:
            return handler
        service, method = _split_method(handler_call_details.method)
        return grpc.unary_unary_rpc_method_handler(
            trace.traced_handler(handler.unary_unary, service, method),
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


def server_interceptors(registry=None):
    """Interceptor tuple for grpc.server(); empty when both metrics
    and tracing are disabled."""
    if registry is None and not metrics.metrics_enabled():
        if env_str(trace.TRACE_DIR_ENV, ""):
            return (TraceServerInterceptor(),)
        return ()
    return (ServerMetricsInterceptor(registry=registry),)


def instrument_channel(channel, registry=None):
    """Wrap a channel with the client metrics interceptor (shared
    process-wide so counters aggregate across stubs); returns the
    channel untouched when metrics are disabled."""
    global _client_cache
    if registry is not None:
        return grpc.intercept_channel(
            channel, ClientMetricsInterceptor(registry=registry)
        )
    if not metrics.metrics_enabled():
        return channel
    default = metrics.default_registry()
    cached_registry, interceptor = _client_cache
    if interceptor is None or cached_registry is not default:
        interceptor = ClientMetricsInterceptor()
        _client_cache = (default, interceptor)
    return grpc.intercept_channel(channel, interceptor)
