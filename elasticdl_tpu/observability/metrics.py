"""Thread-safe metrics core with Prometheus text exposition.

Pure stdlib (no prometheus_client): a process-global ``Registry`` of
``Counter`` / ``Gauge`` / ``Histogram`` instruments with label support,
rendered in Prometheus text format 0.0.4 by
``observability/http_server.py``.

Hot-path discipline: collection is OFF unless requested — on when
``EDL_METRICS`` is set nonzero or an exposition port
(``EDL_METRICS_PORT``/``--metrics_port``) is configured, and
``EDL_METRICS=0`` forces off. Disabled, every constructor returns a
shared no-op instrument whose ``inc``/``set``/``observe``/``labels``
are empty methods — instrumented code pays one attribute call and
nothing else, and the registry renders empty (see
``metrics_enabled``). The knob must be in the environment before the
first instrument is constructed: role entry points publish
``--metrics_port`` into ``EDL_METRICS_PORT`` first thing for exactly
this reason.
"""

import threading
import time
from elasticdl_tpu.common.env_utils import env_int, env_str

ENABLE_ENV = "EDL_METRICS"
PORT_ENV = "EDL_METRICS_PORT"
EXEMPLARS_ENV = "EDL_METRICS_EXEMPLARS"

# a histogram series' exemplar is the SLOWEST recent observation that
# happened inside a sampled trace; "recent" is this window — past it
# any traced observation replaces the stale exemplar, so the linked
# trace_id always points at a trace an operator can still find
EXEMPLAR_WINDOW_SECS = 60.0

# exponential latency buckets (seconds), prometheus client defaults —
# spans sub-ms in-process RPCs up to the 120 s PS retry budget
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

_INF = float("inf")


def metrics_enabled():
    """Metrics collection master switch.

    On when EDL_METRICS is set nonzero, or implicitly when an
    exposition port (EDL_METRICS_PORT) is configured; EDL_METRICS=0
    forces off. With neither knob the registry is the shared no-op —
    instrumented hot paths pay a single empty method call, which is
    what keeps benchmark step time identical to the uninstrumented
    build (ISSUE 2 acceptance)."""
    flag = env_str(ENABLE_ENV, "")
    if flag == "0":
        return False
    if flag:
        return True
    return env_int(PORT_ENV, 0) > 0


def _escape_label_value(value):
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_labels(labelnames, labelvalues, extra=()):
    pairs = [
        '%s="%s"' % (n, _escape_label_value(v))
        for n, v in zip(labelnames, labelvalues)
    ]
    pairs.extend('%s="%s"' % (n, _escape_label_value(v)) for n, v in extra)
    return "{%s}" % ",".join(pairs) if pairs else ""


def _format_value(value):
    if value != value:  # NaN (the render path's own substitute for a
        return "NaN"    # failing callback gauge must itself render)
    if value == _INF:
        return "+Inf"
    if value == -_INF:
        return "-Inf"
    if value == int(value):
        return str(int(value))
    return repr(float(value))


class _NoopInstrument:
    """Shared do-nothing stand-in for every instrument type."""

    def labels(self, *values, **kv):
        return self

    def inc(self, amount=1):
        pass

    def dec(self, amount=1):
        pass

    def set(self, value):
        pass

    def set_function(self, fn):
        pass

    def observe(self, value):
        pass

    def get(self, *labelvalues):
        return 0.0


NOOP = _NoopInstrument()


def _label_key(name, labelnames, values, kv):
    """Validated labelvalues tuple from positional or keyword form."""
    if kv:
        if values or set(kv) != set(labelnames):
            raise ValueError(
                "%s expects labels %r, got %r"
                % (name, labelnames, tuple(kv))
            )
        values = tuple(kv[n] for n in labelnames)
    elif len(values) != len(labelnames):
        raise ValueError(
            "%s expects labels %r, got %r" % (name, labelnames, values)
        )
    return tuple(str(v) for v in values)


class _Child:
    """One labeled series of a Counter/Gauge."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key

    def inc(self, amount=1):
        self._metric._add(self._key, amount)

    def dec(self, amount=1):
        self._metric._add(self._key, -amount)

    def set(self, value):
        self._metric._set(self._key, value)

    def set_function(self, fn):
        self._metric._set_function(self._key, fn)


class _Metric:
    kind = "untyped"

    def __init__(self, name, help_text, labelnames=()):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._values = {}     # labelvalues tuple -> float
        self._functions = {}  # labelvalues tuple -> callable

    def labels(self, *values, **kv):
        key = _label_key(self.name, self.labelnames, values, kv)
        with self._lock:
            # touch so the series is exposed at zero before first use
            self._values.setdefault(key, 0.0)
        return _Child(self, key)

    # unlabeled conveniences ------------------------------------------
    def inc(self, amount=1):
        self._add((), amount)

    def dec(self, amount=1):
        self._add((), -amount)

    def set(self, value):
        self._set((), value)

    def set_function(self, fn):
        self._set_function((), fn)

    def get(self, *labelvalues):
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            fn = self._functions.get(key)
            if fn is not None:
                return float(fn())
            return self._values.get(key, 0.0)

    # internals --------------------------------------------------------
    def _add(self, key, amount):
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def _set(self, key, value):
        with self._lock:
            self._values[key] = float(value)

    def _set_function(self, key, fn):
        with self._lock:
            self._values.setdefault(key, 0.0)
            self._functions[key] = fn

    def render(self):
        lines = [
            "# HELP %s %s" % (self.name, self.help),
            "# TYPE %s %s" % (self.name, self.kind),
        ]
        with self._lock:
            snapshot = dict(self._values)
            functions = dict(self._functions)
        for key, fn in functions.items():
            try:
                snapshot[key] = float(fn())
            except Exception as e:  # pragma: no cover - defensive
                # a broken callback gauge must not take /metrics down
                snapshot[key] = float("nan")
                _logger().warning(
                    "callback gauge %s%r failed: %s", self.name, key, e
                )
        for key in sorted(snapshot):
            lines.append(
                "%s%s %s"
                % (
                    self.name,
                    _format_labels(self.labelnames, key),
                    _format_value(snapshot[key]),
                )
            )
        return lines


class Counter(_Metric):
    kind = "counter"

    def dec(self, amount=1):
        raise TypeError("counters only go up")

    def _add(self, key, amount):
        if amount < 0:
            raise ValueError("counters only go up")
        _Metric._add(self, key, amount)


class Gauge(_Metric):
    kind = "gauge"


class _HistogramChild:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key

    def observe(self, value):
        self._metric._observe(self._key, value)


class Histogram:
    """Fixed-bucket histogram (cumulative counts, Prometheus shape)."""

    kind = "histogram"

    def __init__(
        self, name, help_text, labelnames=(),
        buckets=DEFAULT_LATENCY_BUCKETS,
    ):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets)) + (_INF,)
        self._lock = threading.Lock()
        # labelvalues tuple -> [per-bucket counts, sum, count]
        self._series = {}
        # labelvalues tuple -> (value, trace_id, unix ts): the slowest
        # recent observation made under a sampled span context
        self._exemplars = {}

    def labels(self, *values, **kv):
        key = _label_key(self.name, self.labelnames, values, kv)
        with self._lock:
            self._touch_locked(key)
        return _HistogramChild(self, key)

    def observe(self, value):
        self._observe((), value)

    def get_count(self, *labelvalues):
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            series = self._series.get(key)
            return int(series[2]) if series else 0

    def _touch_locked(self, key):
        if key not in self._series:
            self._series[key] = [[0] * len(self.buckets), 0.0, 0]
        return self._series[key]

    def _observe(self, key, value):
        value = float(value)
        # exemplar candidacy costs one thread-local read when no trace
        # is active (the overwhelmingly common case)
        ctx = _trace_context()
        with self._lock:
            counts, _sum, _n = series = self._touch_locked(key)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            series[1] = _sum + value
            series[2] = _n + 1
            if ctx is not None and ctx.sampled:
                now = time.time()
                exemplar = self._exemplars.get(key)
                if (
                    exemplar is None
                    or value >= exemplar[0]
                    or now - exemplar[2] > EXEMPLAR_WINDOW_SECS
                ):
                    self._exemplars[key] = (value, ctx.trace_id, now)

    def render(self, exemplars=False):
        """Prometheus 0.0.4 lines; with ``exemplars`` each series'
        exemplar rides its bucket line in OpenMetrics syntax
        (``... # {trace_id="..."} value ts``). Exemplars are OFF on the
        default path on purpose: the ``#`` suffix is an OpenMetrics
        construct some 0.0.4 consumers reject, so only the
        content-negotiated/env-gated exposition carries them."""
        lines = [
            "# HELP %s %s" % (self.name, self.help),
            "# TYPE %s histogram" % self.name,
        ]
        with self._lock:
            snapshot = {
                key: (list(counts), s, n)
                for key, (counts, s, n) in self._series.items()
            }
            exemplar_snapshot = dict(self._exemplars) if exemplars else {}
        for key in sorted(snapshot):
            counts, total, n = snapshot[key]
            exemplar = exemplar_snapshot.get(key)
            for bound, count in zip(self.buckets, counts):
                line = "%s_bucket%s %d" % (
                    self.name,
                    _format_labels(
                        self.labelnames, key,
                        extra=(("le", _format_value(bound)),),
                    ),
                    count,
                )
                # the exemplar attaches to the FIRST bucket containing
                # its value (OpenMetrics: an exemplar must lie within
                # its bucket's range)
                if exemplar is not None and exemplar[0] <= bound:
                    line += ' # {trace_id="%s"} %s %.3f' % (
                        exemplar[1],
                        _format_value(exemplar[0]),
                        exemplar[2],
                    )
                    exemplar = None
                lines.append(line)
            labels = _format_labels(self.labelnames, key)
            lines.append("%s_sum%s %s" % (self.name, labels,
                                          _format_value(total)))
            lines.append("%s_count%s %d" % (self.name, labels, n))
        return lines


class Registry:
    """Named instrument collection; get-or-create semantics so wiring
    code can declare its instruments idempotently (roles are
    constructed repeatedly inside one test process)."""

    def __init__(self, enabled=None):
        if enabled is None:
            enabled = metrics_enabled()
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics = {}  # name -> instrument

    def _get_or_create(self, cls, name, help_text, labelnames, **kwargs):
        if not self.enabled:
            return NOOP
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls(name, help_text, labelnames, **kwargs)
                self._metrics[name] = metric
            elif tuple(labelnames) != metric.labelnames:
                raise ValueError(
                    "metric %s re-declared with labels %r (was %r)"
                    % (name, tuple(labelnames), metric.labelnames)
                )
            return metric

    def counter(self, name, help_text, labelnames=()):
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(self, name, help_text, labelnames=()):
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(self, name, help_text, labelnames=(),
                  buckets=DEFAULT_LATENCY_BUCKETS):
        return self._get_or_create(
            Histogram, name, help_text, labelnames, buckets=buckets
        )

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def render(self, exemplars=False):
        """Prometheus text exposition format 0.0.4; ``exemplars=True``
        adds OpenMetrics exemplar suffixes to histogram bucket lines
        (the /metrics content-negotiated path, http_server.py)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for metric in metrics:
            if isinstance(metric, Histogram):
                lines.extend(metric.render(exemplars=exemplars))
            else:
                lines.extend(metric.render())
        return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# process-global default registry

_default_lock = threading.Lock()
_default_registry = None


def default_registry():
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = Registry()
        return _default_registry


def reset_default_registry():
    """Drop the process-global registry so the next use re-evaluates
    the env knobs; tests use it to flip collection on/off within one
    process. (Role entry points don't need it: they publish
    --metrics_port into the environment before the first instrument is
    constructed.)"""
    global _default_registry
    with _default_lock:
        _default_registry = None


def counter(name, help_text, labelnames=()):
    return default_registry().counter(name, help_text, labelnames)


def gauge(name, help_text, labelnames=()):
    return default_registry().gauge(name, help_text, labelnames)


def histogram(name, help_text, labelnames=(),
              buckets=DEFAULT_LATENCY_BUCKETS):
    return default_registry().histogram(
        name, help_text, labelnames, buckets=buckets
    )


class _LazyInstrument:
    """Module-scope instrument declaration whose registry resolution is
    deferred to the first recording call.

    Library modules that role entry points import before ``main()``
    publishes EDL_METRICS_PORT (common.overload via common.grpc_utils,
    observability.device via the trainers) must not touch
    ``default_registry()`` at import time: the registry snapshots
    ``metrics_enabled()`` once, so an import-time construction freezes
    the whole process's /metrics exposition disabled — every role's
    scrape comes back empty. The proxy keeps the declaration at module
    scope (obs-hot-path: no per-call construction) while resolving the
    real instrument on first use, after the role has set its env."""

    __slots__ = ("_factory", "_real")

    def __init__(self, factory):
        self._factory = factory
        self._real = None

    def _resolve(self):
        real = self._real
        if real is None:
            real = self._real = self._factory()
        return real

    def labels(self, *values, **kv):
        return self._resolve().labels(*values, **kv)

    def inc(self, amount=1):
        self._resolve().inc(amount)

    def dec(self, amount=1):
        self._resolve().dec(amount)

    def set(self, value):
        self._resolve().set(value)

    def set_function(self, fn):
        self._resolve().set_function(fn)

    def observe(self, value):
        self._resolve().observe(value)

    def get(self, *labelvalues):
        return self._resolve().get(*labelvalues)


def lazy_counter(name, help_text, labelnames=()):
    return _LazyInstrument(lambda: counter(name, help_text, labelnames))


def lazy_gauge(name, help_text, labelnames=()):
    return _LazyInstrument(lambda: gauge(name, help_text, labelnames))


def lazy_histogram(name, help_text, labelnames=(),
                   buckets=DEFAULT_LATENCY_BUCKETS):
    return _LazyInstrument(
        lambda: histogram(name, help_text, labelnames, buckets=buckets)
    )


def _logger():
    from elasticdl_tpu.common.log_utils import default_logger

    return default_logger("elasticdl_tpu.observability.metrics")


# trace.current_context bound once on first observation: metrics must
# stay importable before (and without) the trace module, but the
# per-observe cost must be one global read + the thread-local lookup,
# not import machinery on every histogram observation
_current_context = None


def _trace_context():
    """Active sampled-trace context, for exemplar candidacy."""
    global _current_context
    read = _current_context
    if read is None:
        from elasticdl_tpu.observability import trace

        read = _current_context = trace.current_context
    return read()
