"""Cross-role task trace: Chrome trace-event JSON per role.

``with span("train_batch", task_id=...)`` buffers a complete ("X")
trace event; each role's buffer flushes to
``$EDL_TRACE_DIR/<role>-<pid>.trace.json`` (atomic rename) on a size
threshold, on ``flush()``, and at interpreter exit. Timestamps are
wall-clock microseconds, so per-role files line up on one timeline when
``scripts/merge_trace.py`` merges them; ``task_id`` is the correlation
key that stitches dispatch (master) → pull/train/push (worker) → apply
(PS) into one story, carried automatically by a thread-local context
(``task_context``) so instrumentation deep in the PS client doesn't
need task plumbing.

Disabled (EDL_TRACE_DIR unset) the module is inert: ``span`` costs one
module-global None check.
"""

import atexit
import contextlib
import json
import os
import threading
import time

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.observability.trace")

TRACE_DIR_ENV = "EDL_TRACE_DIR"

_FLUSH_EVERY = 2048  # events buffered before an incremental flush

_writer = None
_writer_lock = threading.Lock()
_tls = threading.local()


class TraceWriter:
    """Buffers events and APPENDS them to the role file on flush.

    The file is the Chrome trace-event "JSON Array Format": a ``[``
    followed by one event object per line, each with a trailing comma,
    and — per the format spec — the closing ``]`` is optional, so the
    file is Perfetto-loadable at any point, including after a crash
    mid-run. Appending the delta (instead of rewriting the history)
    keeps memory bounded and flush cost O(events since last flush) on
    whatever hot-path thread crossed the buffer threshold; a
    multi-million-step traced job would otherwise hold every event in
    RAM and rewrite the whole file each flush."""

    def __init__(self, role, trace_dir, pid=None):
        self.role = role
        self.dir = trace_dir
        # pid override for tests that emulate several roles in one
        # process (real roles are separate processes)
        self.pid = os.getpid() if pid is None else pid
        self.path = os.path.join(
            trace_dir, "%s-%d.trace.json" % (role, self.pid)
        )
        # RLock: the SIGTERM crash hook (observability/events.py) calls
        # trace.flush() on the main thread, which may have been
        # interrupted inside add()/flush() while holding this lock
        self._lock = threading.RLock()
        self._file_started = False
        self._events = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.pid,
                "tid": 0,
                "args": {"name": role},
            }
        ]

    def add(self, event):
        flush_now = False
        with self._lock:
            self._events.append(event)
            flush_now = len(self._events) >= _FLUSH_EVERY
        if flush_now:
            self.flush()

    def flush(self):
        with self._lock:
            events, self._events = self._events, []
        if not events:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            with self._lock:  # serialize appends across threads
                with open(self.path, "a", encoding="utf-8") as f:
                    if not self._file_started:
                        f.write("[\n")
                        self._file_started = True
                    f.write(
                        "".join(json.dumps(e) + ",\n" for e in events)
                    )
        except OSError as e:
            logger.warning("trace flush to %s failed: %s", self.path, e)


def configure(role):
    """Install the per-process writer when EDL_TRACE_DIR is set; call
    once from each role's entry point (extra calls re-bind the role).
    Returns the writer or None when tracing is disabled."""
    global _writer
    trace_dir = os.environ.get(TRACE_DIR_ENV, "")
    with _writer_lock:
        if not trace_dir:
            _writer = None
            return None
        _writer = TraceWriter(role, trace_dir)
        return _writer


def enabled():
    return _writer is not None


def flush():
    writer = _writer
    if writer is not None:
        writer.flush()


atexit.register(flush)


# ---------------------------------------------------------------------------
# span API

def task_context(task_id):
    """Thread-local task id merged into every span's args (the PS
    client's pull/push spans inherit the worker loop's current task
    without parameter plumbing). Use as a context manager."""
    return _TaskContext(task_id)


class _TaskContext:
    __slots__ = ("task_id", "_previous")

    def __init__(self, task_id):
        self.task_id = task_id
        self._previous = None

    def __enter__(self):
        self._previous = getattr(_tls, "task_id", None)
        _tls.task_id = self.task_id
        return self

    def __exit__(self, *exc):
        _tls.task_id = self._previous
        return False


def current_task_id():
    return getattr(_tls, "task_id", None)


@contextlib.contextmanager
def span(name, **args):
    """Time a block as a complete ("X") trace event."""
    writer = _writer
    if writer is None:
        yield
        return
    start = time.time()
    try:
        yield
    finally:
        _emit(writer, name, start, time.time(), args)


def complete(name, start, **args):
    """Emit a complete event for a block timed by the caller (``start``
    from ``time.time()``); for sites where the span name/args are only
    known at the end — e.g. the dispatcher learns the task_id when the
    pop returns."""
    writer = _writer
    if writer is None:
        return
    _emit(writer, name, start, time.time(), args)


def instant(name, **args):
    """A zero-duration marker event."""
    writer = _writer
    if writer is None:
        return
    task_id = args.pop("task_id", current_task_id())
    if task_id is not None:
        args["task_id"] = task_id
    writer.add(
        {
            "name": name,
            "ph": "i",
            "s": "p",
            "ts": time.time() * 1e6,
            "pid": writer.pid,
            "tid": threading.get_ident() & 0xFFFFFF,
            "args": args,
        }
    )


def _emit(writer, name, start, end, args):
    task_id = args.pop("task_id", None)
    if task_id is None:
        task_id = current_task_id()
    if task_id is not None:
        args["task_id"] = task_id
    writer.add(
        {
            "name": name,
            "ph": "X",
            "ts": start * 1e6,
            "dur": max(0.0, (end - start) * 1e6),
            "pid": writer.pid,
            "tid": threading.get_ident() & 0xFFFFFF,
            "args": args,
        }
    )


def traced_handler(handler, service, method):
    """Wrap a gRPC handler so each invocation is a span (used by the
    server metrics interceptor; separate so tracing works with metrics
    disabled and vice versa)."""

    name = "%s/%s" % (service, method)

    def wrapped(request, context):
        writer = _writer
        if writer is None:
            return handler(request, context)
        start = time.time()
        try:
            return handler(request, context)
        finally:
            _emit(writer, name, start, time.time(),
                  {"kind": "grpc_server"})

    return wrapped
