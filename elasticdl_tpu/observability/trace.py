"""Cross-role distributed trace: Chrome trace-event JSON per role,
threaded by a W3C-traceparent-style span context.

``with span("train_batch", task_id=...)`` buffers a complete ("X")
trace event; each role's buffer flushes to
``$EDL_TRACE_DIR/<role>-<pid>.trace.json`` (atomic rename) on a size
threshold, on ``flush()``, and at interpreter exit. Timestamps are
wall-clock microseconds, so per-role files line up on one timeline when
``scripts/merge_trace.py`` merges them.

Two correlation layers stitch the roles together:

- ``task_id`` (thread-local ``task_context``): the PR-2 coarse key —
  dispatch (master) → pull/train/push (worker) → apply (PS) spans of
  one task share it without parameter plumbing.
- **span context** (ISSUE 9): a ``trace_id``/``span_id``/``sampled``
  triple carried on a thread-local stack. ``root_span`` opens a trace
  (one per worker train step / serve predict request); nested ``span``
  blocks become children with explicit ``parent_id``; the context
  crosses gRPC hops as ``edl-traceparent`` metadata (W3C traceparent
  format, ``observability/trace_propagation.py`` client-side,
  ``traced_handler`` server-side), so a remote handler's span is a
  child of the exact RPC attempt that reached it.

Sampling (``EDL_TRACE_SAMPLE``):

- unset / ``1`` — every root span starts a sampled trace (the pre-
  ISSUE-9 behavior: EDL_TRACE_DIR alone traces everything);
- ``0`` — provably inert: ``root_span`` yields None without touching
  an RNG, no context exists, and ``trace_propagation`` adds NO gRPC
  metadata (the interceptor is not even installed);
- ``0 < p < 1`` — head-based: the root draws once; an unsampled trace
  records nothing anywhere (the ``sampled=0`` flag propagates, so
  remote roles skip their spans too) unless tail-keep retains it.

Tail-keep (``EDL_TRACE_TAIL_KEEP_MS``): with head sampling below 1, an
unsampled root still buffers its LOCAL spans in memory; if the root
runs at least this many milliseconds, the buffer is flushed (root arg
``tail_kept: true``) — the slow outliers survive even at aggressive
sampling rates. Remote children of a tail-kept trace are absent by
construction (the remote saw ``sampled=0`` and recorded nothing).

Disabled (EDL_TRACE_DIR unset) the module is inert: ``span`` costs one
module-global None check.
"""

import atexit
import contextlib
import json
import os
import threading
import time

from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.observability.trace")

TRACE_DIR_ENV = "EDL_TRACE_DIR"
SAMPLE_ENV = "EDL_TRACE_SAMPLE"
TAIL_KEEP_ENV = "EDL_TRACE_TAIL_KEEP_MS"

# gRPC metadata key carrying the serialized span context; the value is
# the W3C traceparent wire format ("00-<trace_id>-<span_id>-<flags>")
# so any standard tracing sidecar can read it off the wire
METADATA_KEY = "edl-traceparent"

_FLUSH_EVERY = 2048  # events buffered before an incremental flush

_writer = None
_writer_lock = threading.Lock()
_tls = threading.local()

# (env string, parsed) caches: re-read the env var on every use so
# tests can monkeypatch it, but parse only on change (faults.py's
# discipline — the hot path pays a dict-free string compare)
_sample_cache = (None, 1.0)
_tail_cache = (None, 0.0)

# sampling decisions only — span/trace ids come from os.urandom so a
# test seeding this RNG for a deterministic sampling schedule cannot
# collide ids across processes
import random as _random_mod  # noqa: E402

_rng = _random_mod.Random()


def sample_rate():
    """Head-sampling probability for new root spans: EDL_TRACE_SAMPLE,
    default 1.0 (EDL_TRACE_DIR alone keeps tracing everything)."""
    global _sample_cache
    raw = env_str(SAMPLE_ENV, "")
    if raw == _sample_cache[0]:
        return _sample_cache[1]
    try:
        rate = float(raw) if raw else 1.0
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", SAMPLE_ENV, raw)
        rate = 1.0
    _sample_cache = (raw, rate)
    return rate


def tail_keep_ms():
    """Tail-keep threshold (ms): an UNSAMPLED root span at least this
    slow flushes its locally buffered spans anyway. 0 (default) = off."""
    global _tail_cache
    raw = env_str(TAIL_KEEP_ENV, "")
    if raw == _tail_cache[0]:
        return _tail_cache[1]
    try:
        ms = float(raw) if raw else 0.0
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", TAIL_KEEP_ENV, raw)
        ms = 0.0
    _tail_cache = (raw, ms)
    return ms


class SpanContext:
    """One span's identity within a trace; immutable by convention."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id, span_id, sampled):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)

    def child(self):
        return SpanContext(self.trace_id, _new_span_id(), self.sampled)

    def to_traceparent(self):
        return "00-%s-%s-%s" % (
            self.trace_id, self.span_id, "01" if self.sampled else "00"
        )

    def __repr__(self):  # pragma: no cover - debugging aid
        return "SpanContext(%s, %s, sampled=%s)" % (
            self.trace_id, self.span_id, self.sampled
        )


def parse_traceparent(text):
    """SpanContext from a traceparent string; None when malformed (a
    peer speaking a future version or garbage must not break the RPC)."""
    try:
        parts = text.strip().split("-")
        if len(parts) != 4:
            return None
        _version, trace_id, span_id, flags = parts
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        int(trace_id, 16)
        int(span_id, 16)
        return SpanContext(trace_id, span_id, int(flags, 16) & 1 == 1)
    except (ValueError, AttributeError):
        return None


def extract_context(metadata):
    """SpanContext from gRPC invocation metadata; None when absent."""
    if not metadata:
        return None
    for key, value in metadata:
        if key == METADATA_KEY:
            return parse_traceparent(value)
    return None


class _EntropyPool:
    """Buffered span/trace-id entropy (ISSUE 15 satellite): PR 14's
    profiler measured the per-span ``os.urandom`` syscall at ~5-7% of
    traced-run host samples. One 4 KiB refill amortizes the syscall
    over ~512 span ids; ``take`` under the lock is a slice + index
    bump. Fork safety: ``os.register_at_fork`` empties the child's
    buffer, so a forked process can never re-deal its parent's bytes
    (duplicate ids across processes would corrupt trace threading)."""

    __slots__ = ("_lock", "_buf", "_pos", "_size")

    def __init__(self, size=4096):
        self._lock = threading.Lock()
        self._buf = b""
        self._pos = 0
        self._size = int(size)

    def take(self, n):
        with self._lock:
            if self._pos + n > len(self._buf):
                self._buf = os.urandom(self._size)
                self._pos = 0
            out = self._buf[self._pos:self._pos + n]
            self._pos += n
            return out

    def reset(self):
        with self._lock:
            self._buf = b""
            self._pos = 0


_entropy = _EntropyPool()
if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_entropy.reset)


def _new_trace_id():
    return _entropy.take(16).hex()


def _new_span_id():
    return _entropy.take(8).hex()


class TraceWriter:
    """Buffers events and APPENDS them to the role file on flush.

    The file is the Chrome trace-event "JSON Array Format": a ``[``
    followed by one event object per line, each with a trailing comma,
    and — per the format spec — the closing ``]`` is optional, so the
    file is Perfetto-loadable at any point, including after a crash
    mid-run. Appending the delta (instead of rewriting the history)
    keeps memory bounded and flush cost O(events since last flush) on
    whatever hot-path thread crossed the buffer threshold; a
    multi-million-step traced job would otherwise hold every event in
    RAM and rewrite the whole file each flush."""

    def __init__(self, role, trace_dir, pid=None):
        self.role = role
        self.dir = trace_dir
        # pid override for tests that emulate several roles in one
        # process (real roles are separate processes)
        self.pid = os.getpid() if pid is None else pid
        self.path = os.path.join(
            trace_dir, "%s-%d.trace.json" % (role, self.pid)
        )
        # RLock: the SIGTERM crash hook (observability/events.py) calls
        # trace.flush() on the main thread, which may have been
        # interrupted inside add()/flush() while holding this lock
        self._lock = threading.RLock()
        self._file_started = False
        self._events = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.pid,
                "tid": 0,
                "args": {"name": role},
            }
        ]

    def add(self, event):
        flush_now = False
        with self._lock:
            self._events.append(event)
            flush_now = len(self._events) >= _FLUSH_EVERY
        if flush_now:
            self.flush()

    def add_all(self, events):
        """Batch append (the tail-keep flush path)."""
        flush_now = False
        with self._lock:
            self._events.extend(events)
            flush_now = len(self._events) >= _FLUSH_EVERY
        if flush_now:
            self.flush()

    def flush(self):
        with self._lock:
            events, self._events = self._events, []
        if not events:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            with self._lock:  # serialize appends across threads
                with open(self.path, "a", encoding="utf-8") as f:
                    if not self._file_started:
                        f.write("[\n")
                        self._file_started = True
                    f.write(
                        "".join(json.dumps(e) + ",\n" for e in events)
                    )
        except OSError as e:
            logger.warning("trace flush to %s failed: %s", self.path, e)


def configure(role):
    """Install the per-process writer when EDL_TRACE_DIR is set; call
    once from each role's entry point (extra calls re-bind the role).
    Returns the writer or None when tracing is disabled."""
    global _writer
    trace_dir = env_str(TRACE_DIR_ENV, "")
    with _writer_lock:
        if not trace_dir:
            _writer = None
            return None
        _writer = TraceWriter(role, trace_dir)
        return _writer


def enabled():
    return _writer is not None


def flush():
    writer = _writer
    if writer is not None:
        writer.flush()


atexit.register(flush)


# ---------------------------------------------------------------------------
# span context plumbing

def current_context():
    """The thread's active SpanContext, or None outside any trace."""
    return getattr(_tls, "ctx", None)


# ---------------------------------------------------------------------------
# continuous-profiler correlation (ISSUE 14)
#
# The sampling profiler's thread cannot read another thread's
# thread-local span stack, so while a sampler is attached each thread
# publishes its innermost open sampled span that the profiler can MAP
# to a critical-path segment, as {thread_ident: (trace_id, span_name)}.
# "Mapped" matters: critical_path.py attributes an unmapped span's
# time (rpc_attempt, ps_apply_round, future names) to its nearest
# mapped ANCESTOR's segment, so an unmapped span must keep the
# enclosing publication instead of overwriting it — otherwise the
# profiler files the same wall time under "other" that the trace
# analyzer files under pull/push/apply. The profiler passes its
# mapped-name predicate at attach time (None = publish everything).
# Guarded by one module-global bool check per span enter/exit, so the
# tracing hot path pays nothing when no profiler runs; plain-dict
# get/set under the GIL is safe for the single-writer-per-key access
# pattern (each thread writes only its own ident; the sampler only
# reads).

_prof_spans = {}
_prof_active = False
_prof_mapped = None  # predicate(name) -> bool, or None = all names


def _profiler_attach(mapped=None):
    global _prof_active, _prof_mapped
    _prof_mapped = mapped
    _prof_active = True


def _profiler_detach():
    global _prof_active, _prof_mapped
    _prof_active = False
    _prof_mapped = None
    _prof_spans.clear()


def profiled_spans():
    """The live {thread_ident: (trace_id, span_name)} map (read by the
    sampler thread; empty whenever no profiler is attached)."""
    return _prof_spans


def _current_sink():
    return getattr(_tls, "sink", None)


@contextlib.contextmanager
def adopt_context(ctx, sink=None):
    """Run a block under ``ctx`` (and, for tail-keep traces, its span
    buffer): server handlers adopt the propagated remote context, and
    ``bind_context``/``capture_context`` re-adopt a caller's context on
    worker-pool threads."""
    prev_ctx = getattr(_tls, "ctx", None)
    prev_sink = getattr(_tls, "sink", None)
    _tls.ctx = ctx
    _tls.sink = sink
    try:
        yield ctx
    finally:
        _tls.ctx = prev_ctx
        _tls.sink = prev_sink


def bind_context(fn):
    """Capture the calling thread's span context and return a callable
    that re-adopts it wherever it runs — the bridge for thread-pool
    fan-out (PS client per-shard futures, the async-push executor):
    without it the pool thread has no context and the RPC leaves the
    trace. Identity when no context is active."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return fn
    sink = getattr(_tls, "sink", None)

    def bound(*args, **kwargs):
        with adopt_context(ctx, sink):
            return fn(*args, **kwargs)

    return bound


# a single reusable do-nothing adoption for context-less captures:
# nullcontext is stateless, so one instance serves every caller — the
# serve admission path allocates nothing per request when tracing is
# off or the request arrived untraced
_NULL_ADOPTION = contextlib.nullcontext()


def _null_capture():
    return _NULL_ADOPTION


def capture_context():
    """Snapshot the caller's context as a zero-arg context-manager
    factory (the serve batcher stores one per request at admission and
    the formation thread adopts the batch head's). Returns a shared
    no-op factory when no context is active — zero per-request
    allocation on the untraced serving hot path."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return _null_capture
    sink = getattr(_tls, "sink", None)

    def factory():
        return adopt_context(ctx, sink)

    return factory


class _TailSink:
    """Span buffer for an unsampled tail-keep candidate trace. Events
    buffer until the root closes and the keep/drop decision is FINAL;
    after that, a kept sink forwards late arrivals (async-push spans
    bound to the step's context outlive the root) straight to the
    writer, and a dropped sink discards them — either way nothing
    lands in a list nobody will ever flush. The lock closes the race
    between a pool thread's append and the root's close."""

    __slots__ = ("_writer", "_events", "_decided", "_kept", "_lock")

    def __init__(self, writer):
        self._writer = writer
        self._events = []
        self._decided = False
        self._kept = False
        self._lock = threading.Lock()

    def append(self, event):
        with self._lock:
            if not self._decided:
                self._events.append(event)
                return
            kept = self._kept
        if kept:
            self._writer.add(event)

    def close(self, kept):
        with self._lock:
            self._decided = True
            self._kept = kept
            events, self._events = self._events, []
        if kept and events:
            self._writer.add_all(events)


def _suppressed(ctx):
    """True for an UNSAMPLED context with no tail-keep buffer — the
    one state in which span/complete/instant record nothing: the whole
    point of sampled=0 propagation is that such a request records
    nothing anywhere. The single definition every recording primitive
    consults (drift here would make span() disagree with complete())."""
    return (
        ctx is not None
        and not ctx.sampled
        and getattr(_tls, "sink", None) is None
    )


def _recording():
    return not _suppressed(getattr(_tls, "ctx", None))


def _write(writer, event):
    sink = getattr(_tls, "sink", None)
    if sink is not None:
        sink.append(event)
    else:
        writer.add(event)


def annotate(**args):
    """Merge args into the innermost OPEN recording span — for facts
    only known mid-block. The load-bearing user is the serve abort
    path: grpc's ``context.abort`` raises a bare ``Exception`` that
    carries no status, so without this the shed root span would never
    record the code critical_path.py classifies sheds by."""
    stack = getattr(_tls, "open_args", None)
    if stack:
        stack[-1].update(args)


def _push_open(args):
    stack = getattr(_tls, "open_args", None)
    if stack is None:
        stack = _tls.open_args = []
    stack.append(args)


def _pop_open():
    stack = getattr(_tls, "open_args", None)
    if stack:
        stack.pop()


# ---------------------------------------------------------------------------
# span API

def task_context(task_id):
    """Thread-local task id merged into every span's args (the PS
    client's pull/push spans inherit the worker loop's current task
    without parameter plumbing). Use as a context manager."""
    return _TaskContext(task_id)


class _TaskContext:
    __slots__ = ("task_id", "_previous")

    def __init__(self, task_id):
        self.task_id = task_id
        self._previous = None

    def __enter__(self):
        self._previous = getattr(_tls, "task_id", None)
        _tls.task_id = self.task_id
        return self

    def __exit__(self, *exc):
        _tls.task_id = self._previous
        return False


def current_task_id():
    return getattr(_tls, "task_id", None)


@contextlib.contextmanager
def root_span(name, **args):
    """Open a trace: one per worker train step / serve predict request.
    Yields the new SpanContext (None when tracing is off or sampling is
    0 — the caller can branch on it, but needn't). If a context is
    ALREADY active (a propagated parent adopted by the server handler),
    the "root" degrades to a child span so the caller's trace stays
    whole instead of forking a second trace_id."""
    writer = _writer
    if writer is None:
        yield None
        return
    existing = getattr(_tls, "ctx", None)
    if existing is not None:
        with span(name, **args):
            yield existing
        return
    rate = sample_rate()
    if rate <= 0.0:
        # the provably inert fast path: no ids, no RNG draw, no
        # context for the propagation interceptor to serialize
        yield None
        return
    sampled = rate >= 1.0 or _rng.random() < rate
    tail_ms = tail_keep_ms()
    ctx = SpanContext(_new_trace_id(), _new_span_id(), sampled)
    sink = _TailSink(writer) if (not sampled and tail_ms > 0) else None
    prev_sink = getattr(_tls, "sink", None)
    _tls.ctx = ctx
    _tls.sink = sink
    published = _prof_active and sampled
    if published:
        _prof_spans[threading.get_ident()] = (ctx.trace_id, name)
    _push_open(args)
    start = time.time()
    error = None
    try:
        yield ctx
    except BaseException as e:
        error = e
        raise
    finally:
        end = time.time()
        _pop_open()
        _tls.ctx = None
        _tls.sink = prev_sink
        if published:
            _prof_spans.pop(threading.get_ident(), None)
        keep_tail = (
            sink is not None and (end - start) * 1e3 >= tail_ms
        )
        if sampled or keep_tail:
            if error is not None:
                _note_error(args, error)
            if keep_tail:
                args["tail_kept"] = True
            args["trace_id"] = ctx.trace_id
            args["span_id"] = ctx.span_id
            task_id = args.pop("task_id", current_task_id())
            if task_id is not None:
                args["task_id"] = task_id
            event = {
                "name": name,
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(0.0, (end - start) * 1e6),
                "pid": writer.pid,
                "tid": threading.get_ident() & 0xFFFFFF,
                "args": args,
            }
            if sink is not None:
                sink.append(event)
            else:
                writer.add(event)
        if sink is not None:
            # decision is final: flush-or-drop the buffer, and route
            # LATE spans (a bound async push finishing after the root)
            # to the writer or the void accordingly
            sink.close(keep_tail)


def _note_error(args, error):
    """Fold an exception into span args: failed RPC attempts and shed
    requests must be visible as failed spans, not silent gaps."""
    args.setdefault("error", type(error).__name__)
    code = getattr(error, "code", None)
    if callable(code):
        try:
            status = code()
            args.setdefault(
                "code", getattr(status, "name", None) or str(status)
            )
        except Exception:  # edlint: disable=ft-swallowed-except
            pass  # a half-built RpcError's code() must not mask it


@contextlib.contextmanager
def span(name, **args):
    """Time a block as a complete ("X") trace event. Under an active
    span context the event becomes a CHILD span (fresh span_id, parent
    = the enclosing span) and nested spans chain below it; with no
    context it is the PR-2 standalone task_id-correlated span."""
    writer = _writer
    if writer is None:
        yield
        return
    ctx = getattr(_tls, "ctx", None)
    if _suppressed(ctx):
        yield  # unsampled trace: record nothing, anywhere
        return
    child = ctx.child() if ctx is not None else None
    if child is not None:
        _tls.ctx = child
    published = (
        _prof_active
        and child is not None
        and ctx.sampled
        and (_prof_mapped is None or _prof_mapped(name))
    )
    if published:
        ident = threading.get_ident()
        prev_published = _prof_spans.get(ident)
        _prof_spans[ident] = (ctx.trace_id, name)
    _push_open(args)
    start = time.time()
    error = None
    try:
        yield
    except BaseException as e:
        error = e
        raise
    finally:
        _pop_open()
        if child is not None:
            _tls.ctx = ctx
        if published:
            # restore the enclosing span's publication (unless the
            # profiler detached mid-span — then leave nothing behind)
            if prev_published is not None and _prof_active:
                _prof_spans[ident] = prev_published
            else:
                _prof_spans.pop(ident, None)
        if error is not None:
            _note_error(args, error)
        _emit(writer, name, start, time.time(), args,
              ctx=child, parent=ctx)


def complete(name, start, **args):
    """Emit a complete event for a block timed by the caller (``start``
    from ``time.time()``); for sites where the span name/args are only
    known at the end — e.g. the dispatcher learns the task_id when the
    pop returns. Under an active context the event is a child of the
    current span."""
    writer = _writer
    if writer is None:
        return
    if not _recording():
        return
    ctx = getattr(_tls, "ctx", None)
    child = ctx.child() if ctx is not None else None
    _emit(writer, name, start, time.time(), args, ctx=child, parent=ctx)


def instant(name, **args):
    """A zero-duration marker event."""
    writer = _writer
    if writer is None:
        return
    if not _recording():
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        args["trace_id"] = ctx.trace_id
        args["parent_id"] = ctx.span_id
    task_id = args.pop("task_id", current_task_id())
    if task_id is not None:
        args["task_id"] = task_id
    _write(
        writer,
        {
            "name": name,
            "ph": "i",
            "s": "p",
            "ts": time.time() * 1e6,
            "pid": writer.pid,
            "tid": threading.get_ident() & 0xFFFFFF,
            "args": args,
        },
    )


def _emit(writer, name, start, end, args, ctx=None, parent=None):
    if ctx is not None:
        args["trace_id"] = ctx.trace_id
        args["span_id"] = ctx.span_id
        if parent is not None:
            args["parent_id"] = parent.span_id
    task_id = args.pop("task_id", None)
    if task_id is None:
        task_id = current_task_id()
    if task_id is not None:
        args["task_id"] = task_id
    _write(
        writer,
        {
            "name": name,
            "ph": "X",
            "ts": start * 1e6,
            "dur": max(0.0, (end - start) * 1e6),
            "pid": writer.pid,
            "tid": threading.get_ident() & 0xFFFFFF,
            "args": args,
        },
    )


def traced_handler(handler, service, method):
    """Wrap a gRPC handler so each invocation is a span (used by the
    server metrics interceptor; separate so tracing works with metrics
    disabled and vice versa).

    ISSUE 9: when the request carries ``edl-traceparent`` metadata, the
    handler runs UNDER the propagated context — its span is a child of
    the exact client-side RPC attempt, and spans opened inside the
    handler (PS apply, dispatch) chain below it. A propagated
    ``sampled=0`` suppresses recording for the whole handler."""

    name = "%s/%s" % (service, method)

    def wrapped(request, context):
        writer = _writer
        if writer is None:
            return handler(request, context)
        remote = None
        if context is not None:
            try:
                remote = extract_context(context.invocation_metadata())
            except Exception:  # edlint: disable=ft-swallowed-except
                remote = None  # metadata must never break the RPC
        if remote is None:
            # no propagated parent: the PR-2 standalone server span
            start = time.time()
            try:
                return handler(request, context)
            finally:
                _emit(writer, name, start, time.time(),
                      {"kind": "grpc_server"})
        with adopt_context(remote):
            if not remote.sampled:
                return handler(request, context)
            with span(name, kind="grpc_server"):
                return handler(request, context)

    return wrapped


def _reset_for_tests():
    """Drop the writer and thread-local state (tests only)."""
    global _writer, _sample_cache, _tail_cache
    with _writer_lock:
        _writer = None
    _sample_cache = (None, 1.0)
    _tail_cache = (None, 0.0)
    _profiler_detach()
    for attr in ("ctx", "sink", "task_id", "open_args"):
        if hasattr(_tls, attr):
            delattr(_tls, attr)
