"""Continuous profiling: an always-on sampling stack profiler per role.

The fourth observability pillar (after metrics, the flight recorder,
and distributed tracing): when ``scripts/critical_path.py`` says a step
spent 40% of its time in ``apply`` or ``other``, this module answers
*which Python frames* burned it — without hand-instrumenting suspects.

A single daemon thread walks ``sys._current_frames()`` at
``EDL_PROF_HZ`` and aggregates each thread's stack into collapsed form
(root-first ``module:function`` frames). Aggregates live in a bounded
ring of time buckets, so memory stays constant no matter how long the
role runs or how much the code paths churn:

- one in-progress bucket aggregates the last ``_BUCKET_SECS`` of
  samples; full buckets rotate into a ``deque`` bounded to
  ``EDL_PROF_RING_SECS`` worth of history;
- each bucket holds at most ``EDL_PROF_MAX_STACKS`` distinct collapsed
  stacks — overflow samples land in a counted ``(overflow)`` entry
  instead of growing the dict (zero heap growth under stack churn).

**Span correlation.** A sample landing while a *sampled* trace span is
open on that thread (``observability/trace.py`` publishes the
innermost open *mapped* span per thread while the profiler is
attached) is tagged with the span's ``trace_id`` and the critical-path
segment its span name maps to (``train_batch`` → ``compute``,
``ps_apply_push`` → ``apply``, ...). Spans whose names map to no
segment (``rpc_attempt``, ``ps_apply_round``, future names) do not
publish: their samples keep the nearest mapped ancestor's tag, exactly
mirroring how ``scripts/critical_path.py`` attributes an unmapped
span's self time to its nearest mapped ancestor's segment.
``critical_path.py --frames`` then breaks its per-segment attribution
down into the top frame stacks that actually ran inside each segment.

**Exposure.** Every role's HTTP daemon serves the sampler as
``GET /profilez`` (observability/http_server.py):

- no query → the rolling ring snapshot (the last ``EDL_PROF_RING_SECS``
  of aggregated stacks);
- ``?seconds=N`` → an on-demand window capture: only samples landing
  during the next N seconds (capped at ``_MAX_CAPTURE_SECS``);
- ``&format=collapsed`` → flamegraph-ready collapsed text
  (``frame;frame;... count`` lines, segment folded in as a leading
  ``[segment]`` frame) instead of the default JSON.

**Inert when disabled.** With ``EDL_PROF_HZ`` unset/0 (the default)
``maybe_start`` returns None without constructing anything: no thread,
no trace hook, and ``/profilez`` answers 404. The sampler skips its own
thread (and capture threads while they sleep), so the profiler never
profiles itself.

**Overhead contract.** At the default 29 Hz the measured steps/s cost
on the deepfm local-executor bench must stay within 3%
(``scripts/bench_profiler_overhead.py``, gated in CI tier 1f). 29 is
deliberately not a divisor of common 10/50/100 ms periods, so the
sampler does not alias against periodic work. The sampler exports its
own cost as ``edl_prof_overhead_ratio`` (fraction of wall time spent
walking stacks) next to ``edl_prof_samples_total``.
"""

import collections
import os
import sys
import threading
import time

from elasticdl_tpu.common.env_utils import env_float, env_int
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import events
from elasticdl_tpu.observability import metrics as metrics_mod
from elasticdl_tpu.observability import trace

logger = _logger_factory("elasticdl_tpu.observability.profiler")

HZ_ENV = "EDL_PROF_HZ"
RING_SECS_ENV = "EDL_PROF_RING_SECS"
MAX_STACKS_ENV = "EDL_PROF_MAX_STACKS"

DEFAULT_HZ = 29.0  # documented default; see module docstring
DEFAULT_RING_SECS = 120.0
DEFAULT_MAX_STACKS = 512

_BUCKET_SECS = 5.0
_MAX_DEPTH = 64
_MAX_CAPTURE_SECS = 60.0
OVERFLOW_STACK = ("(overflow)",)

# span name -> critical-path segment, mirroring the exact-name map in
# scripts/critical_path.py (segment_of) so a tagged sample lands in the
# same bucket the trace's self-time attribution lands in
_SEGMENT_BY_SPAN = {
    "train_batch": "compute",
    "serve_batch_run": "compute",
    "dispatch": "queue_wait",
    "serve_predict": "queue_wait",
    "ps_pull": "pull",
    "ps_pull_batch": "pull",
    "ps_push": "push",
    "ps_push_rows": "push",
    "ps_apply_push": "apply",
    # device runtime (ISSUE 18): the recompile sentinel's compile
    # spans and explicit host<->device transfer spans
    "compile": "compile",
    "transfer": "transfer",
}


def segment_of_span(name):
    """Critical-path segment for an open span name. Never None —
    ``other`` for unmapped names; note unmapped names never PUBLISH
    (``_mapped_span``), so ``other`` tags only reach samples via an
    unmapped root, same as critical_path's root attribution."""
    seg = _SEGMENT_BY_SPAN.get(name)
    if seg is not None:
        return seg
    if name.startswith("Pserver/pull"):
        return "pull"
    if name.startswith("Pserver/push"):
        return "apply"
    if name.startswith("Master/"):
        return "queue_wait"
    return "other"


def configured_hz():
    """Sampling rate from EDL_PROF_HZ; 0 (disabled) when unset, empty,
    non-positive, or non-numeric."""
    hz = env_float(HZ_ENV, 0.0)
    return hz if hz > 0 else 0.0


class _Agg:
    """One bounded aggregation bucket: collapsed stack -> tally.

    ``stacks`` maps ``(segment, stack_tuple)`` to ``[count,
    last_trace_id]`` — the trace_id is an exemplar (the most recent
    sampled trace that ran this stack), not a per-sample record, which
    is what keeps aggregation O(distinct stacks) instead of O(samples).
    """

    __slots__ = ("stacks", "samples", "overflow", "started")

    def __init__(self):
        self.stacks = {}
        self.samples = 0
        self.overflow = 0
        self.started = time.time()

    def add(self, key, trace_id, max_stacks):
        self.samples += 1
        entry = self.stacks.get(key)
        if entry is not None:
            entry[0] += 1
            if trace_id is not None:
                entry[1] = trace_id
        elif len(self.stacks) < max_stacks:
            self.stacks[key] = [1, trace_id]
        else:
            # bounded under churn: past the cap, samples still count
            # but land in one shared overflow entry
            self.overflow += 1


class StackSampler:
    """Daemon-thread sampling profiler for one role's process."""

    def __init__(self, role, hz, ring_secs=None, max_stacks=None,
                 registry=None):
        self.role = role
        self.hz = float(hz)
        if ring_secs is None:
            ring_secs = env_float(RING_SECS_ENV, DEFAULT_RING_SECS)
        if max_stacks is None:
            max_stacks = env_int(MAX_STACKS_ENV, DEFAULT_MAX_STACKS)
        self.ring_secs = float(ring_secs)
        self.max_stacks = max(1, int(max_stacks))
        buckets = max(1, int(round(self.ring_secs / _BUCKET_SECS)))
        self._ring = collections.deque(maxlen=buckets)
        self._current = _Agg()
        self._captures = []  # window-capture buckets being fed live
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        # thread idents never sampled: the sampler itself, plus any
        # thread currently sleeping inside capture() — the profiler
        # must not profile itself
        self._skip = set()
        self._walk_secs = 0.0
        self._started_at = None
        self._stopped_at = None
        registry = registry or metrics_mod.default_registry()
        self._samples_metric = registry.counter(
            "edl_prof_samples_total",
            "stack samples taken by the continuous profiler",
            ("role",),
        ).labels(role=role)
        self._overhead_gauge = registry.gauge(
            "edl_prof_overhead_ratio",
            "fraction of wall time the profiler spends walking stacks",
            ("role",),
        ).labels(role=role)
        self._overhead_gauge.set_function(self.overhead_ratio)

    # ------------------------------------------------------------------
    @staticmethod
    def _mapped_span(name):
        """Publication predicate for trace.py: only span names that map
        to a real segment publish; an unmapped nested span (rpc_attempt,
        ps_apply_round) keeps its enclosing span's publication, so its
        samples inherit the ancestor's segment exactly the way
        critical_path.py inherits its self time."""
        return segment_of_span(name) != "other"

    def start(self):
        self._started_at = time.monotonic()
        self._stopped_at = None
        self._overhead_gauge.set_function(self.overhead_ratio)
        self._thread = threading.Thread(
            target=self._run,
            name="edl-prof-%s" % self.role,
            daemon=True,
        )
        self._thread.start()
        # from here on, span enter/exit publishes the innermost open
        # MAPPED sampled span per thread for the sampler to read
        trace._profiler_attach(self._mapped_span)
        logger.info(
            "continuous profiler on: %s at %.1f Hz (ring %ds, "
            "max %d stacks/bucket)",
            self.role, self.hz, int(self.ring_secs), self.max_stacks,
        )
        return self

    def stop(self):
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            self._thread = None
        trace._profiler_detach()
        self._stopped_at = time.monotonic()
        # freeze the exported ratio at its final running value and drop
        # the gauge's reference to this sampler: a stopped sampler must
        # neither read as a silently-decaying live ratio nor pin its
        # ring in memory for the rest of the process
        final = self.overhead_ratio()
        self._overhead_gauge.set_function(lambda final=final: final)

    def running(self):
        thread = self._thread
        return thread is not None and thread.is_alive()

    def overhead_ratio(self):
        """Measured duty cycle: seconds spent walking stacks over wall
        seconds while RUNNING (the clock stops with the sampler). The
        self-reported half of the <=3% contract (the other half is the
        A/B bench)."""
        if self._started_at is None:
            return 0.0
        end = self._stopped_at
        if end is None:
            end = time.monotonic()
        wall = end - self._started_at
        if wall <= 0:
            return 0.0
        with self._lock:
            walk = self._walk_secs
        return walk / wall

    # ------------------------------------------------------------------
    def _run(self):
        self._skip.add(threading.get_ident())
        interval = 1.0 / self.hz
        next_at = time.monotonic() + interval
        while not self._stop.wait(max(0.0, next_at - time.monotonic())):
            next_at += interval
            now = time.monotonic()
            if next_at < now:
                # fell behind (suspend/GIL stall): re-anchor instead of
                # bursting to catch up
                next_at = now + interval
            t0 = time.perf_counter()
            try:
                self._sample_once()
            except Exception as e:
                # a torn frame walk must never kill the sampler; one
                # missed tick is noise
                logger.warning("profiler sample failed: %s", e)
            walked = time.perf_counter() - t0
            with self._lock:
                self._walk_secs += walked

    def _sample_once(self):
        frames = sys._current_frames()
        spans = trace.profiled_spans()
        tallies = []
        for ident, frame in frames.items():
            if ident in self._skip:
                continue
            stack = self._collapse(frame)
            if not stack:
                continue
            published = spans.get(ident)
            if published is not None:
                trace_id, span_name = published
                key = (segment_of_span(span_name), stack)
            else:
                trace_id = None
                key = (None, stack)
            tallies.append((key, trace_id))
        del frames  # drop live-frame refs before taking the lock
        if not tallies:
            return
        with self._lock:
            self._rotate_locked()
            for key, trace_id in tallies:
                self._current.add(key, trace_id, self.max_stacks)
                for capture_agg in self._captures:
                    capture_agg.add(key, trace_id, self.max_stacks)
        self._samples_metric.inc(len(tallies))

    @staticmethod
    def _collapse(frame):
        """Collapsed stack for one thread: root-first
        ``module:function`` tuple, depth-capped at _MAX_DEPTH."""
        parts = []
        depth = 0
        while frame is not None and depth < _MAX_DEPTH:
            code = frame.f_code
            module = frame.f_globals.get("__name__", "?")
            name = getattr(code, "co_qualname", None) or code.co_name
            parts.append("%s:%s" % (module, name))
            frame = frame.f_back
            depth += 1
        parts.reverse()
        return tuple(parts)

    def _rotate_locked(self, now=None):
        if (now or time.time()) - self._current.started >= _BUCKET_SECS:
            if self._current.samples:
                self._ring.append(self._current)
            self._current = _Agg()

    # ------------------------------------------------------------------
    def snapshot(self):
        """The rolling-ring view: every aggregated stack from the last
        ``ring_secs`` (bounded), merged across buckets."""
        with self._lock:
            aggs = list(self._ring) + [self._current]
            merged = {}
            samples = 0
            overflow = 0
            oldest = aggs[0].started if aggs else time.time()
            for agg in aggs:
                samples += agg.samples
                overflow += agg.overflow
                for key, (count, trace_id) in agg.stacks.items():
                    entry = merged.get(key)
                    if entry is None:
                        merged[key] = [count, trace_id]
                    else:
                        entry[0] += count
                        if trace_id is not None:
                            entry[1] = trace_id
        window = max(0.0, time.time() - oldest)
        return self._render(merged, samples, overflow, window)

    def capture(self, seconds):
        """On-demand window capture: only samples landing during the
        next ``seconds`` (capped). Blocks the calling thread — which is
        skipped by the sampler while it sleeps here, so the capture
        never profiles its own wait."""
        seconds = min(max(float(seconds), 0.05), _MAX_CAPTURE_SECS)
        agg = _Agg()
        ident = threading.get_ident()
        own = ident not in self._skip
        if own:
            self._skip.add(ident)
        with self._lock:
            self._captures.append(agg)
        try:
            time.sleep(seconds)
        finally:
            with self._lock:
                self._captures.remove(agg)
            if own:
                self._skip.discard(ident)
        result = self._render(
            agg.stacks, agg.samples, agg.overflow, seconds
        )
        events.emit(
            "profile_captured", seconds=round(seconds, 3),
            samples=agg.samples, stacks=len(agg.stacks),
        )
        return result

    def _render(self, merged, samples, overflow, window_secs):
        stacks = [
            {
                "stack": list(stack),
                "count": entry[0],
                "segment": segment,
                "trace_id": entry[1],
            }
            for (segment, stack), entry in merged.items()
        ]
        stacks.sort(key=lambda s: (-s["count"], s["stack"]))
        return {
            "role": self.role,
            "hz": self.hz,
            "samples": samples,
            "overflow": overflow,
            "window_secs": round(window_secs, 3),
            "stacks": stacks,
        }


def collapsed(snapshot):
    """Flamegraph-ready collapsed text for a snapshot/capture dict:
    one ``frame;frame;... count`` line per aggregated stack, the
    segment (when tagged) folded in as a leading ``[segment]`` frame so
    a flamegraph groups by critical-path segment at the root."""
    lines = []
    for entry in snapshot.get("stacks", ()):
        frames = list(entry["stack"])
        if entry.get("segment"):
            frames.insert(0, "[%s]" % entry["segment"])
        lines.append("%s %d" % (";".join(frames), entry["count"]))
    overflow = snapshot.get("overflow", 0)
    if overflow:
        lines.append("%s %d" % (OVERFLOW_STACK[0], overflow))
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# per-process singleton (the role entry points' single call)

_sampler = None
_sampler_lock = threading.Lock()


def maybe_start(role, registry=None):
    """Start the role's sampler when EDL_PROF_HZ is configured; None
    otherwise — and then PROVABLY inert: nothing constructed, no
    thread, no trace hook (extra calls re-bind the role)."""
    global _sampler
    hz = configured_hz()
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None
        if hz <= 0:
            return None
        _sampler = StackSampler(role, hz, registry=registry).start()
        sampler_started = _sampler
    events.emit(
        "profiler_started", hz=hz,
        ring_secs=sampler_started.ring_secs,
    )
    return sampler_started


def sampler():
    """The process's live sampler, or None when profiling is off."""
    return _sampler


def enabled():
    return _sampler is not None


def stop():
    """Stop and drop the singleton (drain paths and benches)."""
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


def _reset_for_tests():
    stop()
