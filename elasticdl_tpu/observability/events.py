"""Structured event journal: the cluster flight recorder.

Schema'd NDJSON lifecycle events per role under ``$EDL_EVENTS_DIR``:
``<role>-<pid>.events.ndjson``, one JSON object per line. Every line
carries the envelope (``ts`` wall-clock seconds, ``role``, ``pid``,
``seq`` monotonic per process, ``job`` from ``EDL_JOB_NAME``, ``event``)
plus the event's own correlation fields (``worker``, ``task``,
``version``, ...) — the keys ``scripts/postmortem.py`` threads a dead
job's artifacts together by.

Durability model (this is a black box, not a log):

- The journal is written THROUGH — every line is appended and flushed
  before ``emit`` returns. Lifecycle events are task-/round-rate, not
  step-internal-rate, so a flush per line is noise next to the RPC that
  produced the event, and it is the only discipline that survives
  SIGKILL/OOM-kill: whatever the kernel let us write is on disk.
- A bounded ring buffer (last ``_RING_SIZE`` events) additionally lives
  in memory; ``dump(reason)`` writes it with the crash reason to
  ``<role>-<pid>.dump.json``. Crash hooks (``install_crash_hooks``:
  SIGTERM + uncaught-exception hook; role mains call it) dump the ring
  so an evicted pod's last moments are one self-contained file even
  when the journal itself is on slow/contended storage.

Disabled (``EDL_EVENTS_DIR`` unset) the module is inert: ``emit`` costs
one module-global None check — the PR 2 disabled-is-no-op discipline.
"""

import json
import os
import signal
import sys
import threading
import time

from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.observability.events")

EVENTS_DIR_ENV = "EDL_EVENTS_DIR"
JOB_NAME_ENV = "EDL_JOB_NAME"

_RING_SIZE = 256

# The event vocabulary: postmortem tooling and tests key off these
# names, so emitting an unknown type is a programming error (caught
# loudly in emit). Fields beyond the envelope are free-form but the
# comments document the correlation keys each type carries.
EVENT_TYPES = frozenset({
    # role lifecycle
    "role_start",            # role came up (worker: + incarnation epoch)
    "role_stop",             # orderly exit
    "crash_dump",            # ring dumped from a crash path (+ reason)
    # worker <-> master
    "worker_register",       # reset_worker served (+ worker, epoch)
    "worker_presumed_dead",  # liveness/timeout eviction (+ worker)
    "mesh_epoch_restart",    # worker exiting to rejoin a new mesh epoch
    # control-plane crash recovery (ISSUE 4)
    "master_restarted",      # journal replayed (+ master_epoch, todo,
                             #   requeued, epochs_left)
    "ps_restored",           # PS auto-restored a checkpoint at boot
                             #   (+ version, ps)
    "worker_resynced",       # worker detected a PS state regression and
                             #   re-pushed its model (+ shard, version)
    "checkpoint_skipped",    # corrupt/incomplete checkpoint version
                             #   skipped during restore (+ version, why)
    # elasticity control loop (ISSUE 7)
    "scale_decision",        # autoscaler resize (+direction, delta,
                             #   workers, queue_depth, reasons)
    "worker_draining",       # graceful drain begun (+worker, reason,
                             #   initiator master|worker)
    "drain_ack",             # drain completed: task reported, push
                             #   joined, tier flushed (+worker, reason)
                             #   — journaled by the MASTER on the
                             #   deregister RPC; exactly one per drain
    "drain_unacked",         # worker finished flushing but the master
                             #   never acknowledged the deregister
                             #   (old master / RPC failure); the
                             #   worker-side record of the drain
    "drain_expired",         # drain deadline passed; requeue-on-death
                             #   fallback fired (+worker)
    # task lifecycle (+ task, worker)
    "task_dispatch",
    "task_report",           # + ok, err
    "task_requeue",          # + retries, counted
    "job_failed",            # retry cap exhausted (+ task)
    # sync-PS rounds (+ version)
    "round_open",            # first push buffered for a round
    "round_fill",            # push buffered (+ fill)
    "round_close",           # round applied (+ pushes)
    "stale_push_rejected",   # + worker, version, store_version
    "dead_incarnation_dropped",  # + worker, incarnation
    # checkpoints (+ version)
    "checkpoint_saved",
    # fleet detectors (+ alert, target)
    "alert_raised",
    "alert_cleared",
    # online serving tier (ISSUE 8)
    "model_loaded",          # serve role loaded its first export
                             #   (+ step, stamp, path)
    "version_swapped",       # hot swap completed; in-flight requests
                             #   finished on the old version
                             #   (+ from_step, to_step, stamp)
    "requests_shed",         # admission control shed load — RATE-
                             #   LIMITED to ~1 line/s (+ reason, count
                             #   since last line, total)
    "serve_drained",         # SIGTERM drain: admissions stopped, queue
                             #   flushed (+ reason, flushed, served,
                             #   shed)
    # serving fleet (ISSUE 17): router-side replica lifecycle + canary
    "replica_registered",    # replica joined the router's ring
                             #   (+ replica, addr, stamp)
    "replica_lost",          # heartbeats stopped; pulled from the ring
                             #   (+ replica, silent_secs)
    "replica_draining",      # router stopped routing to a shrink
                             #   victim (+ replica, reason)
    "canary_started",        # new export takes the canary slice
                             #   (+ export, members, fraction)
    "canary_promoted",       # judge passed; fleet directed to the new
                             #   export (+ export, reasons)
    "canary_rolled_back",    # judge failed; canary members directed
                             #   back to incumbent (+ export, reasons)
    # distributed tracing (ISSUE 9)
    "trace_flushed",         # a drain path flushed the trace buffer to
                             #   EDL_TRACE_DIR (+ reason)
    # continuous profiling (ISSUE 14)
    "profiler_started",      # the role's stack sampler came up
                             #   (+ hz, ring_secs)
    "profile_captured",      # an on-demand /profilez window capture
                             #   completed (+ seconds, samples, stacks)
    # continual streaming training (ISSUE 12)
    "row_admitted",          # ids passed frequency admission and
                             #   materialized real rows (+ table,
                             #   count, ids[:128])
    "row_evicted",           # lifecycle sweep tombstone: rows deleted
                             #   from the store (+ table, reason
                             #   ttl|lfu, count, ids[:128]) — the
                             #   postmortem answer to "why is this row
                             #   cold"
    "stream_watermark",      # watermark progress marker (+ watermark,
                             #   minted, kind window|export|checkpoint
                             #   |closed) — the streaming durability
                             #   clock the checkpoint/export cadence
                             #   rides
    # training-health sentinels (ISSUE 15)
    "health_nonfinite",      # nonfinite loss/grads streak OPENED
                             #   (+ loss, grad_norm, action; edge-
                             #   journaled so a NaN-wedged job can't
                             #   flood the journal)
    "health_loss_spike",     # robust-z loss spike (+ loss, ewma)
    "health_grad_explosion",  # grad-norm explosion (+ grad_norm, ewma)
    "health_halt",           # EDL_HEALTH_ON_NONFINITE=halt tripped:
                             #   the task fails loudly and the process
                             #   exits nonzero (+ loss, grad_norm,
                             #   streak)
    "health_table_exploding",  # PS table-health scan found sampled
                             #   rows beyond EDL_HEALTH_ROW_NORM_MAX
                             #   (+ ps, rows, tables, norm_max; edge-
                             #   journaled per scan transition)
    # overload plane (ISSUE 19)
    "ps_overload_enter",     # PS apply backlog crossed
                             #   EDL_PS_MAX_PENDING_APPLIES; admission
                             #   now answers RESOURCE_EXHAUSTED with a
                             #   retry-after hint (+ ps_id, depth,
                             #   max_pending, method; edge-journaled)
    "ps_overload_clear",     # backlog drained below the limit
                             #   (+ ps_id, depth)
    "circuit_open",          # per-(target, method-class) breaker
                             #   tripped (+ target, method_class,
                             #   previous, consecutive_failures,
                             #   reset_secs)
    "circuit_half_open",     # probe window opened: one trial RPC
                             #   admitted (+ target, method_class)
    "circuit_closed",        # probe succeeded; normal pacing resumed
                             #   (+ target, method_class)
    "degraded_pull",         # brownout: pull served bounded-staleness
                             #   cached/cold-init rows instead of the
                             #   open-circuited PS (+ table, rows,
                             #   cached, cold)
    "brownout_skipped_push",  # trainer dropped a batch's push after
                             #   EDL_BROWNOUT_SKIP_AFTER consecutive
                             #   failures (+ skipped, version)
    "brownout_recovered",    # pushes landing again after a brownout
                             #   skip streak (+ skipped, version)
    # device-runtime observability (ISSUE 18)
    "xla_recompile",         # a wrapped step fn compiled AGAIN — a new
                             #   argument signature after warmup
                             #   (+ fn, compiles, seconds, changed
                             #   [leaf: old -> new provenance],
                             #   signature) — the journal line the
                             #   recompile_storm postmortem reads
})


class EventJournal:
    """Write-through NDJSON journal + in-memory ring for one role."""

    def __init__(self, role, events_dir, pid=None):
        self.role = role
        self.dir = events_dir
        # pid override for tests emulating several roles in one process
        self.pid = os.getpid() if pid is None else pid
        self.job = env_str(JOB_NAME_ENV, "")
        self.path = os.path.join(
            events_dir, "%s-%d.events.ndjson" % (role, self.pid)
        )
        self.dump_path = os.path.join(
            events_dir, "%s-%d.dump.json" % (role, self.pid)
        )
        # RLock, not Lock: the SIGTERM crash hook runs dump()/flush()
        # on the main thread, and the signal may land while that same
        # thread is inside emit() holding this lock — a plain Lock
        # would deadlock the dying pod and lose the dump it exists to
        # produce
        self._lock = threading.RLock()
        self._seq = 0
        self._ring = []  # bounded to _RING_SIZE below
        self._file = None
        self._dumped = False

    def emit(self, event, fields):
        record = {
            "ts": time.time(),
            "role": self.role,
            "pid": self.pid,
            "event": event,
        }
        if self.job:
            record["job"] = self.job
        record.update(fields)
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            line = json.dumps(record)
            self._ring.append(record)
            del self._ring[:-_RING_SIZE]
            try:
                if self._file is None:
                    os.makedirs(self.dir, exist_ok=True)
                    self._file = open(self.path, "a", encoding="utf-8")
                self._file.write(line + "\n")
                # write-through: the journal must survive SIGKILL, and
                # lifecycle events are rare enough that a flush per
                # line costs nothing next to the RPC that produced it
                self._file.flush()
            except (OSError, RuntimeError) as e:
                # RuntimeError: reentrant TextIOWrapper call when a
                # signal handler (SIGTERM drain hook) emits while the
                # interrupted thread is inside this same write(); the
                # record is still in the ring, and losing one journal
                # line beats crashing the drain
                logger.warning("event journal write failed: %s", e)

    def dump(self, reason):
        """Write the last-K ring (+ reason) as one self-contained JSON
        file — the crash-path black box. First reason wins: a SIGTERM
        followed by the dying interpreter's excepthook must not
        overwrite the original cause."""
        with self._lock:
            if self._dumped:
                return None
            self._dumped = True
            ring = list(self._ring)
        payload = {
            "role": self.role,
            "pid": self.pid,
            "job": self.job,
            "reason": reason,
            "dumped_at": time.time(),
            "events": ring,
        }
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(self.dump_path, "w", encoding="utf-8") as f:
                json.dump(payload, f)
        except OSError as e:
            logger.warning("ring dump to %s failed: %s", self.dump_path, e)
            return None
        return self.dump_path

    def flush(self):
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                except (OSError, RuntimeError):
                    # RuntimeError: reentrant BufferedWriter call when
                    # the crash hook interrupted emit() mid-write; the
                    # torn line is tolerated by the postmortem parser
                    pass

    def close(self):
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


_journal = None
_journal_lock = threading.Lock()


def configure(role):
    """Install the per-process journal when EDL_EVENTS_DIR is set; call
    once from each role's entry point (extra calls re-bind the role).
    Returns the journal or None when journaling is disabled."""
    global _journal
    events_dir = env_str(EVENTS_DIR_ENV, "")
    with _journal_lock:
        if not events_dir:
            _journal = None
            return None
        _journal = EventJournal(role, events_dir)
        return _journal


def enabled():
    return _journal is not None


def emit(event, **fields):
    """Append one lifecycle event; inert without EDL_EVENTS_DIR."""
    journal = _journal
    if journal is None:
        return
    if event not in EVENT_TYPES:
        raise ValueError("unknown event type %r" % event)
    journal.emit(event, fields)


def flush():
    journal = _journal
    if journal is not None:
        journal.flush()


def dump(reason):
    """Force the ring buffer to disk (crash paths); returns the dump
    path or None when disabled/failed."""
    journal = _journal
    if journal is not None:
        return journal.dump(reason)
    return None


# ---------------------------------------------------------------------------
# crash hooks: the black box must outlive the pod

_hooks_installed = False


def install_crash_hooks():
    """Arrange for the flight recorder to survive this process's death:

    - SIGTERM (K8s eviction): dump the ring, flush the journal and the
      trace buffer, then chain to the previously installed handler —
      or exit 0 if there was none, matching the graceful-eviction
      contract (SystemExit unwinds through the role main's
      try/finally, so in-flight state still flushes).
    - uncaught exception: dump the ring with the exception type as the
      reason, then defer to the original excepthook.

    Call from role MAINS only (signal handlers need the main thread).
    Idempotent; the hooks re-check journal state at fire time, so a
    main may install them before deciding whether to configure()."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    from elasticdl_tpu.observability import trace

    previous_term = signal.getsignal(signal.SIGTERM)

    def _on_term(signum, frame):
        dump("sigterm")
        flush()
        trace.flush()
        if callable(previous_term):
            previous_term(signum, frame)
        else:
            sys.exit(0)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        # not the main thread (embedded use) — journal write-through
        # still covers the SIGKILL story; only the dump convenience
        # is lost
        logger.warning("not on main thread; SIGTERM hook not installed")

    previous_hook = sys.excepthook

    def _on_uncaught(exc_type, exc, tb):
        dump("uncaught:%s" % exc_type.__name__)
        flush()
        trace.flush()
        previous_hook(exc_type, exc, tb)

    sys.excepthook = _on_uncaught


def _reset_for_tests():
    """Drop the journal and hook state (tests only)."""
    global _journal, _hooks_installed
    with _journal_lock:
        _journal = None
    _hooks_installed = False
