"""Client CLI (`edl`): zoo image workflow + train/evaluate/predict.

Reference parity: elasticdl_client/ (SURVEY.md §2.9).
"""
