"""`edl` CLI entry point.

Reference parity: elasticdl_client/main.py:28-88 — the command tree
`zoo init|build|push` and `train|evaluate|predict`.
"""

import argparse
import sys

from elasticdl_tpu.client import api
from elasticdl_tpu.client import args as client_args


def build_parser():
    parser = argparse.ArgumentParser(
        "edl", description="elasticdl_tpu client"
    )
    subparsers = parser.add_subparsers(dest="command", required=True)

    zoo = subparsers.add_parser("zoo", help="model zoo image workflow")
    zoo_sub = zoo.add_subparsers(dest="zoo_command", required=True)
    p = zoo_sub.add_parser("init")
    client_args.add_zoo_init_arguments(p)
    p.set_defaults(func=api.init_zoo)
    p = zoo_sub.add_parser("build")
    client_args.add_zoo_build_arguments(p)
    p.set_defaults(func=api.build_zoo)
    p = zoo_sub.add_parser("push")
    client_args.add_zoo_push_arguments(p)
    p.set_defaults(func=api.push_zoo)

    p = subparsers.add_parser("train")
    client_args.add_common_arguments(p)
    client_args.add_train_arguments(p)
    p.set_defaults(func=api.train)

    p = subparsers.add_parser("evaluate")
    client_args.add_common_arguments(p)
    client_args.add_evaluate_arguments(p)
    p.set_defaults(func=api.evaluate)

    p = subparsers.add_parser(
        "predict",
        help="batch prediction job, or --serving_addr for online "
        "predictions against a live serving role",
    )
    client_args.add_common_arguments(p)
    client_args.add_predict_arguments(p)
    p.set_defaults(func=api.predict)

    p = subparsers.add_parser(
        "serve",
        help="long-running online serving role over a train export "
        "(micro-batched Predict RPC, zero-downtime version swap; "
        "docs/SERVING.md)",
    )
    client_args.add_common_arguments(p)
    client_args.add_serve_arguments(p)
    p.set_defaults(func=api.serve)

    return parser


def main(argv=None):
    parsed = build_parser().parse_args(argv)
    return parsed.func(parsed)


def cli(argv=None):
    """Process entry point: command handlers return their result object
    (tests consume it — e.g. the dry-run manifest), which must NOT
    become the exit code (sys.exit(dict) exits 1)."""
    main(argv)
    return 0


if __name__ == "__main__":
    sys.exit(cli())
