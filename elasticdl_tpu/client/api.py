"""Client API: zoo image workflow + job submission.

Reference parity: elasticdl_client/api.py — init_zoo renders a
Dockerfile embedding the model zoo (:52-90), build_zoo/push_zoo drive
docker (:93-113), train/evaluate/predict re-serialize args into a master
pod command line and create the master pod or dump YAML (:116-248).

Docker here goes through the `docker` CLI via subprocess (the docker
python SDK is not in this image); clusterless workflows use --dry_run /
--yaml, which never touch a cluster or daemon.
"""

import os
import shlex
import subprocess

import yaml

from elasticdl_tpu.client import args as client_args
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.client.api")

_DOCKERFILE_TEMPLATE = """\
FROM {base_image}

RUN pip install {index_args}elasticdl_tpu {extra_packages}
COPY . /model_zoo
ENV PYTHONPATH=/model_zoo:$PYTHONPATH
"""


def init_zoo(parsed):
    """Render a Dockerfile into the current directory (api.py:52-90)."""
    extra = " ".join(parsed.extra_pypi_package)
    index = getattr(parsed, "extra_pypi_index", "")
    content = _DOCKERFILE_TEMPLATE.format(
        base_image=parsed.base_image,
        extra_packages=extra,
        index_args="--extra-index-url %s " % index if index else "",
    )
    if parsed.cluster_spec:
        content += "COPY %s /cluster_spec/\n" % parsed.cluster_spec
    with open("Dockerfile", "w") as f:
        f.write(content)
    logger.info("Wrote Dockerfile (base image %s)", parsed.base_image)


def build_zoo(parsed):
    _docker(parsed, "build", "-t", parsed.image, parsed.path)


def push_zoo(parsed):
    _docker(parsed, "push", parsed.image)


def _docker(parsed, *args):
    """Shell out to the docker CLI, honoring the daemon-connection
    flags (reference drives the docker SDK with base_url/tls,
    elasticdl_client/api.py:93-113)."""
    command = ["docker"]
    base_url = getattr(parsed, "docker_base_url", "")
    if base_url:
        command += ["--host", base_url]
    tlscert = getattr(parsed, "docker_tlscert", "")
    tlskey = getattr(parsed, "docker_tlskey", "")
    if bool(tlscert) != bool(tlskey):
        raise ValueError(
            "--docker_tlscert and --docker_tlskey are both required "
            "for a TLS daemon connection (got only one)"
        )
    if tlscert:
        # --tls (not --tlsverify): client-cert auth without requiring a
        # CA file, matching the reference SDK's TLSConfig(client_cert=)
        command += ["--tls", "--tlscert", tlscert, "--tlskey", tlskey]
    command += args
    logger.info("Running: %s", " ".join(shlex.quote(a) for a in command))
    subprocess.run(command, check=True)


# ----------------------------------------------------------------------
def train(parsed):
    return _submit_job(parsed, "train")


def evaluate(parsed):
    return _submit_job(parsed, "evaluate")


def predict(parsed):
    if getattr(parsed, "serving_addr", ""):
        return _predict_online(parsed)
    if not parsed.checkpoint_dir_for_init:
        raise ValueError(
            "predict needs --checkpoint_dir_for_init (batch job) or "
            "--serving_addr (online, against a live serving role)"
        )
    return _submit_job(parsed, "predict")


def _predict_online(parsed):
    """Stream the prediction data through a LIVE serving role's
    Predict RPC (ISSUE 8) — no job submission, no cluster, no
    checkpoint restore: the serving tier already holds the model. Rows
    route through the model-zoo ``dataset_fn`` exactly like the batch
    path, land on ``PredictionOutputsProcessor`` when the module
    defines one, and are returned as a list of per-batch output
    arrays (the LocalExecutor.predict contract)."""
    import numpy as np

    from elasticdl_tpu.common.args import (
        parse_params_string,
        symbol_overrides_from_args,
    )
    from elasticdl_tpu.data.pipeline import batch_real_count
    from elasticdl_tpu.data.readers import create_data_reader
    from elasticdl_tpu.models.registry import get_model_spec
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
    from elasticdl_tpu.serve.client import ServeClient

    spec = get_model_spec(
        parsed.model_zoo,
        model_def=parsed.model_def,
        model_params=parsed.model_params,
        symbol_overrides=symbol_overrides_from_args(parsed),
    )
    reader = create_data_reader(
        parsed.prediction_data,
        **parse_params_string(parsed.data_reader_params),
    )

    def records():
        for shard_name, (start, count) in reader.create_shards().items():
            task = pb.Task(
                shard_name=shard_name, start=start, end=start + count
            )
            yield from reader.read_records(task)

    from elasticdl_tpu.data.pipeline import Dataset

    client = ServeClient(parsed.serving_addr)
    # the server rejects requests larger than its compiled batch shape
    # (INVALID_ARGUMENT), so clamp our batching to its advertised cap —
    # --minibatch_size's default (64) exceeds the serve default (32)
    batch_size = parsed.minibatch_size
    server_max = client.model_info().get("max_batch", 0)
    if server_max and server_max < batch_size:
        logger.info(
            "clamping --minibatch_size %d to the serving role's "
            "max_batch %d", batch_size, server_max,
        )
        batch_size = server_max
    dataset = spec.dataset_fn(
        Dataset(records), "prediction", reader.metadata
    ).batch(batch_size)
    processor_cls = spec.prediction_outputs_processor
    processor = processor_cls() if processor_cls else None
    results = []
    try:
        for batch in dataset:
            real = batch_real_count(batch)
            features = batch["features"]
            if isinstance(features, dict):
                features = {
                    k: np.asarray(v)[:real] for k, v in features.items()
                }
            else:
                features = np.asarray(features)[:real]
            outputs, _, _ = client.predict(
                features,
                affinity_key=getattr(parsed, "affinity_key", 0),
            )
            if processor is not None:
                processor.process(outputs, 0)
            results.append(outputs["output"])
        if processor is not None and hasattr(processor, "close"):
            processor.close()
    finally:
        client.close()
    logger.info(
        "served %d prediction batches through %s",
        len(results), parsed.serving_addr,
    )
    return results


def serve(parsed):
    """Submit the online serving role's pod (or dump YAML): the
    ``elasticdl predict`` job type grown into a long-running
    low-latency tier (docs/SERVING.md). With ``--router`` the pod is
    the fleet's router (ISSUE 17): replicas are serve pods submitted
    with ``--router_addr`` pointing at it."""
    if getattr(parsed, "router", False):
        command = [
            "python", "-m", "elasticdl_tpu.serve.router_main",
            "--router_id=0",
            "--port=%d" % parsed.port,
        ]
        if parsed.min_replicas >= 0:
            command.append("--min_replicas=%d" % parsed.min_replicas)
        if parsed.max_replicas >= 0:
            command.append("--max_replicas=%d" % parsed.max_replicas)
        role, index_name = "router", "router-0"
    else:
        if not parsed.model_zoo or not parsed.export_dir:
            raise ValueError(
                "edl serve needs --model_zoo and --export_dir "
                "(or --router for the fleet router pod)"
            )
        command = [
            "python", "-m", "elasticdl_tpu.serve.main",
            "--serve_id=0",
            "--port=%d" % parsed.port,
            "--model_zoo=%s" % parsed.model_zoo,
            "--export_dir=%s" % parsed.export_dir,
        ]
        for flag in ("model_def", "model_params", "ps_addrs",
                     "master_addr", "compute_dtype", "router_addr"):
            value = getattr(parsed, flag, "")
            if value:
                command.append("--%s=%s" % (flag, value))
        if parsed.max_batch:
            command.append("--max_batch=%d" % parsed.max_batch)
        if parsed.max_delay_ms >= 0:
            command.append("--max_delay_ms=%s" % parsed.max_delay_ms)
        if parsed.queue_depth:
            command.append("--queue_depth=%d" % parsed.queue_depth)
        if parsed.deadline_ms >= 0:
            command.append("--deadline_ms=%s" % parsed.deadline_ms)
        role, index_name = "serve", "serve-0"
    if parsed.metrics_port:
        command.append("--metrics_port=%d" % parsed.metrics_port)

    from elasticdl_tpu.k8s.client import Client

    api = _make_api(parsed)
    client = Client(
        api,
        parsed.job_name,
        image_name=parsed.image_name,
        cluster_spec=getattr(parsed, "cluster_spec", ""),
    )
    manifest = client.build_pod_manifest(
        "elasticdl-%s-%s" % (parsed.job_name, index_name),
        role,
        0,
        command,
        resource_requests=client_args.parse_resource_string(
            parsed.worker_resource_request
        ),
        resource_limits=client_args.parse_resource_string(
            parsed.worker_resource_limit
        )
        or None,
        env=client_args.parse_envs_string(parsed.envs),
        restart_policy="Always",  # a serving pod is a long-running tier
        priority_class=parsed.worker_pod_priority or None,
        volumes=client_args.parse_volume_string(parsed.volume),
        image_pull_policy=parsed.image_pull_policy or None,
    )
    return _emit_or_submit(
        parsed, api, manifest, "serve",
        "Submitted serving role for job %s on port %d"
        % (parsed.job_name, parsed.port),
    )


def _submit_job(parsed, job_kind):
    """Build the master pod manifest; submit it or dump YAML
    (api.py:193-248)."""
    if os.path.exists(getattr(parsed, "cluster_spec", "") or ""):
        # a cluster_spec FILE path is client-local; the master runs
        # inside the zoo image, where `zoo init` placed the module
        # under /cluster_spec/ — forward THAT path (the client-side
        # master-pod hook below still loads the local file). A dotted
        # module name passes through untouched: it resolves by import
        # inside the image.
        import argparse as _argparse

        forwarded = _argparse.Namespace(**vars(parsed))
        forwarded.cluster_spec = "/cluster_spec/%s" % os.path.basename(
            parsed.cluster_spec
        )
        master_args = client_args.build_master_arguments(forwarded)
    else:
        master_args = client_args.build_master_arguments(parsed)
    command = [
        "python",
        "-m",
        "elasticdl_tpu.master.main",
    ] + master_args

    from elasticdl_tpu.k8s.client import Client

    api = _make_api(parsed)
    client = Client(
        api,
        parsed.job_name,
        image_name=parsed.image_name,
        cluster_spec=getattr(parsed, "cluster_spec", ""),
    )
    manifest = client.build_pod_manifest(
        client.get_master_pod_name(),
        "master",
        0,
        command,
        resource_requests=client_args.parse_resource_string(
            parsed.master_resource_request
        ),
        resource_limits=client_args.parse_resource_string(
            parsed.master_resource_limit
        )
        or None,
        env=dict(
            client_args.parse_envs_string(parsed.envs),
            EDL_JOB_KIND=job_kind,
        ),
        restart_policy=parsed.restart_policy,
        priority_class=parsed.master_pod_priority or None,
        volumes=client_args.parse_volume_string(parsed.volume),
        image_pull_policy=parsed.image_pull_policy or None,
    )
    return _emit_or_submit(
        parsed, api, manifest, "master",
        "Submitted %s job %s (master pod %s)"
        % (job_kind, parsed.job_name, client.get_master_pod_name()),
    )


def _emit_or_submit(parsed, api, manifest, what, submitted_msg):
    """Shared tail of every pod-submitting command: dump the manifest
    (--dry_run prints, --yaml writes) or create the pod for real."""
    if parsed.dry_run or parsed.yaml:
        text = yaml.safe_dump(manifest, sort_keys=False)
        if parsed.yaml:
            with open(parsed.yaml, "w") as f:
                f.write(text)
            logger.info("Wrote %s pod manifest to %s", what, parsed.yaml)
        else:
            print(text)
        return manifest
    api.create_pod(manifest)
    logger.info(submitted_msg)
    return manifest


def _make_api(parsed):
    """In-cluster/kubeconfig-less API, or an inert stub for dry runs."""
    if parsed.dry_run or parsed.yaml:
        class _DryRunApi:
            namespace = parsed.namespace

        return _DryRunApi()
    from elasticdl_tpu.k8s.api import K8sApi

    return K8sApi(namespace=parsed.namespace)
