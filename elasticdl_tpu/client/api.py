"""Client API: zoo image workflow + job submission.

Reference parity: elasticdl_client/api.py — init_zoo renders a
Dockerfile embedding the model zoo (:52-90), build_zoo/push_zoo drive
docker (:93-113), train/evaluate/predict re-serialize args into a master
pod command line and create the master pod or dump YAML (:116-248).

Docker here goes through the `docker` CLI via subprocess (the docker
python SDK is not in this image); clusterless workflows use --dry_run /
--yaml, which never touch a cluster or daemon.
"""

import os
import shlex
import subprocess

import yaml

from elasticdl_tpu.client import args as client_args
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.client.api")

_DOCKERFILE_TEMPLATE = """\
FROM {base_image}

RUN pip install {index_args}elasticdl_tpu {extra_packages}
COPY . /model_zoo
ENV PYTHONPATH=/model_zoo:$PYTHONPATH
"""


def init_zoo(parsed):
    """Render a Dockerfile into the current directory (api.py:52-90)."""
    extra = " ".join(parsed.extra_pypi_package)
    index = getattr(parsed, "extra_pypi_index", "")
    content = _DOCKERFILE_TEMPLATE.format(
        base_image=parsed.base_image,
        extra_packages=extra,
        index_args="--extra-index-url %s " % index if index else "",
    )
    if parsed.cluster_spec:
        content += "COPY %s /cluster_spec/\n" % parsed.cluster_spec
    with open("Dockerfile", "w") as f:
        f.write(content)
    logger.info("Wrote Dockerfile (base image %s)", parsed.base_image)


def build_zoo(parsed):
    _docker(parsed, "build", "-t", parsed.image, parsed.path)


def push_zoo(parsed):
    _docker(parsed, "push", parsed.image)


def _docker(parsed, *args):
    """Shell out to the docker CLI, honoring the daemon-connection
    flags (reference drives the docker SDK with base_url/tls,
    elasticdl_client/api.py:93-113)."""
    command = ["docker"]
    base_url = getattr(parsed, "docker_base_url", "")
    if base_url:
        command += ["--host", base_url]
    tlscert = getattr(parsed, "docker_tlscert", "")
    tlskey = getattr(parsed, "docker_tlskey", "")
    if bool(tlscert) != bool(tlskey):
        raise ValueError(
            "--docker_tlscert and --docker_tlskey are both required "
            "for a TLS daemon connection (got only one)"
        )
    if tlscert:
        # --tls (not --tlsverify): client-cert auth without requiring a
        # CA file, matching the reference SDK's TLSConfig(client_cert=)
        command += ["--tls", "--tlscert", tlscert, "--tlskey", tlskey]
    command += args
    logger.info("Running: %s", " ".join(shlex.quote(a) for a in command))
    subprocess.run(command, check=True)


# ----------------------------------------------------------------------
def train(parsed):
    return _submit_job(parsed, "train")


def evaluate(parsed):
    return _submit_job(parsed, "evaluate")


def predict(parsed):
    return _submit_job(parsed, "predict")


def _submit_job(parsed, job_kind):
    """Build the master pod manifest; submit it or dump YAML
    (api.py:193-248)."""
    if os.path.exists(getattr(parsed, "cluster_spec", "") or ""):
        # a cluster_spec FILE path is client-local; the master runs
        # inside the zoo image, where `zoo init` placed the module
        # under /cluster_spec/ — forward THAT path (the client-side
        # master-pod hook below still loads the local file). A dotted
        # module name passes through untouched: it resolves by import
        # inside the image.
        import argparse as _argparse

        forwarded = _argparse.Namespace(**vars(parsed))
        forwarded.cluster_spec = "/cluster_spec/%s" % os.path.basename(
            parsed.cluster_spec
        )
        master_args = client_args.build_master_arguments(forwarded)
    else:
        master_args = client_args.build_master_arguments(parsed)
    command = [
        "python",
        "-m",
        "elasticdl_tpu.master.main",
    ] + master_args

    from elasticdl_tpu.k8s.client import Client

    api = _make_api(parsed)
    client = Client(
        api,
        parsed.job_name,
        image_name=parsed.image_name,
        cluster_spec=getattr(parsed, "cluster_spec", ""),
    )
    manifest = client.build_pod_manifest(
        client.get_master_pod_name(),
        "master",
        0,
        command,
        resource_requests=client_args.parse_resource_string(
            parsed.master_resource_request
        ),
        resource_limits=client_args.parse_resource_string(
            parsed.master_resource_limit
        )
        or None,
        env=dict(
            client_args.parse_envs_string(parsed.envs),
            EDL_JOB_KIND=job_kind,
        ),
        restart_policy=parsed.restart_policy,
        priority_class=parsed.master_pod_priority or None,
        volumes=client_args.parse_volume_string(parsed.volume),
        image_pull_policy=parsed.image_pull_policy or None,
    )
    if parsed.dry_run or parsed.yaml:
        text = yaml.safe_dump(manifest, sort_keys=False)
        if parsed.yaml:
            with open(parsed.yaml, "w") as f:
                f.write(text)
            logger.info("Wrote master pod manifest to %s", parsed.yaml)
        else:
            print(text)
        return manifest
    api_obj = client._api  # real submission path
    api_obj.create_pod(manifest)
    logger.info(
        "Submitted %s job %s (master pod %s)",
        job_kind,
        parsed.job_name,
        client.get_master_pod_name(),
    )
    return manifest


def _make_api(parsed):
    """In-cluster/kubeconfig-less API, or an inert stub for dry runs."""
    if parsed.dry_run or parsed.yaml:
        class _DryRunApi:
            namespace = parsed.namespace

        return _DryRunApi()
    from elasticdl_tpu.k8s.api import K8sApi

    return K8sApi(namespace=parsed.namespace)
