"""Client CLI argument surface.

Reference parity: elasticdl_client/common/args.py:78-503 (the canonical
~60-flag surface: resources, priorities, volumes, distribution_strategy,
checkpoint/eval/prediction groups, envs) and
build_arguments_from_parsed_result (:543-565), which re-serializes parsed
args into the master pod's command line.

TPU additions: --tpu_resource (chips per worker pod), --mesh (dp,fsdp,
tp,sp axis sizes), --num_ps meaning *sparse host-PS* count (the dense
path has no PS).
"""

import argparse

from elasticdl_tpu.common.args import (
    LOG_LOSS_STEPS_DEFAULT,
    add_bool_argument,
    add_logging_arguments,
    add_symbol_override_arguments,
)


def add_zoo_init_arguments(parser):
    parser.add_argument(
        "--base_image", default="python:3.12", help="Docker base image"
    )
    parser.add_argument(
        "--extra_pypi_package",
        action="append",
        default=[],
        help="extra pip packages baked into the image",
    )
    parser.add_argument(
        "--extra_pypi_index",
        default="",
        help="extra pip index URL for the image's installs",
    )
    parser.add_argument(
        "--cluster_spec",
        default="",
        help="python file customizing pod specs for your cluster",
    )


def _add_docker_connection_arguments(parser):
    parser.add_argument("--docker_base_url", default="")
    parser.add_argument("--docker_tlscert", default="")
    parser.add_argument("--docker_tlskey", default="")


def add_zoo_build_arguments(parser):
    parser.add_argument("path", help="model zoo directory")
    parser.add_argument(
        "--image", required=True, help="tag for the built image"
    )
    _add_docker_connection_arguments(parser)


def add_zoo_push_arguments(parser):
    parser.add_argument("image", help="image tag to push")
    # push must reach the same daemon the image was built on
    _add_docker_connection_arguments(parser)


def add_common_arguments(parser):
    parser.add_argument("--job_name", required=True)
    parser.add_argument("--image_name", default="")
    parser.add_argument(
        "--cluster_spec",
        default="",
        help="python module exporting `cluster` with "
        "with_pod/with_service manifest hooks; applied to every pod "
        "and service this job creates (in-cluster, the zoo image "
        "carries it under /cluster_spec/)",
    )
    parser.add_argument("--namespace", default="default")
    parser.add_argument(
        "--distribution_strategy",
        default="AllreduceStrategy",
        choices=[
            "Local",
            "AllreduceStrategy",  # dense SPMD over ICI (the default)
            "ParameterServerStrategy",  # + sparse host-PS
        ],
    )
    parser.add_argument("--num_workers", type=int, default=1)
    parser.add_argument(
        "--num_ps_pods",
        type=int,
        default=0,
        help="sparse host-PS pod count (dense gradients never touch a PS)",
    )
    parser.add_argument("--worker_resource_request", default="cpu=1,memory=4096Mi")
    parser.add_argument("--worker_resource_limit", default="")
    parser.add_argument("--ps_resource_request", default="cpu=1,memory=4096Mi")
    parser.add_argument("--ps_resource_limit", default="")
    parser.add_argument("--master_resource_request", default="cpu=0.5,memory=1024Mi")
    parser.add_argument("--master_resource_limit", default="")
    parser.add_argument(
        "--tpu_resource",
        default="",
        help='TPU chips per worker pod, e.g. "google.com/tpu=8"',
    )
    parser.add_argument(
        "--mesh",
        default="",
        help='mesh axis sizes, e.g. "dp=4,fsdp=2" (defaults to all-dp)',
    )
    parser.add_argument("--master_pod_priority", default="")
    parser.add_argument("--worker_pod_priority", default="")
    parser.add_argument("--ps_pod_priority", default="")
    parser.add_argument(
        "--volume",
        default="",
        help='e.g. "claim_name=mypvc,mount_path=/data"',
    )
    parser.add_argument(
        "--envs", default="", help="k1=v1,k2=v2 env vars for all pods"
    )
    parser.add_argument("--restart_policy", default="Never")
    parser.add_argument("--image_pull_policy", default="Always")
    parser.add_argument(
        "--dry_run",
        action="store_true",
        help="print the master pod manifest as YAML instead of submitting",
    )
    parser.add_argument(
        "--yaml",
        default="",
        help="dump the master pod manifest to this file",
    )


def add_train_arguments(parser):
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", default="")
    parser.add_argument("--model_params", default="")
    parser.add_argument("--training_data", default="")
    parser.add_argument("--validation_data", default="")
    parser.add_argument("--data_reader_params", default="")
    parser.add_argument("--minibatch_size", type=int, default=64)
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--records_per_task", type=int, default=1024)
    parser.add_argument("--evaluation_steps", type=int, default=0)
    parser.add_argument("--evaluation_throttle_secs", type=int, default=0)
    parser.add_argument("--evaluation_start_delay_secs", type=int, default=0)
    parser.add_argument("--task_timeout_secs", type=float, default=300.0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--checkpoint_steps", type=int, default=0)
    parser.add_argument("--async_checkpoint", type=int, default=0)
    parser.add_argument("--grad_accum_steps", type=int, default=1)
    parser.add_argument("--keep_checkpoint_max", type=int, default=3)
    parser.add_argument("--checkpoint_dir_for_init", default="")
    parser.add_argument("--output", default="")
    parser.add_argument("--compute_dtype", default="bfloat16")
    # sparse host-PS mode (reference client flags,
    # /root/reference/elasticdl_client/common/args.py: use_async,
    # grads_to_wait, lr_staleness_modulation, sync_version_tolerance);
    # forwarded to the master, which marshals them into PS pod commands
    add_bool_argument(parser, "--use_async", default=0)
    parser.add_argument("--grads_to_wait", type=int, default=1)
    parser.add_argument("--sync_version_tolerance", type=int, default=0)
    add_bool_argument(parser, "--lr_staleness_modulation", default=0)
    # lockstep consensus cadence; forwarded master -> worker pods
    parser.add_argument("--consensus_interval", type=int, default=1)
    parser.add_argument("--tensorboard_log_dir", default="")
    parser.add_argument(
        "--num_minibatches_per_task", type=int, default=0
    )
    parser.add_argument(
        "--log_loss_steps", type=int, default=LOG_LOSS_STEPS_DEFAULT
    )
    _add_model_symbol_and_log_arguments(parser)


def _add_model_symbol_and_log_arguments(parser):
    # contract symbol-name overrides + logging (reference
    # model_utils.py:139-150, client args :369,392) — shared helpers so
    # the client surface cannot drift from the master/worker parsers
    add_symbol_override_arguments(parser)
    add_logging_arguments(parser)


def add_evaluate_arguments(parser):
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", default="")
    # must match the train-time binding or checkpoint restore sees a
    # different architecture
    parser.add_argument("--model_params", default="")
    parser.add_argument("--validation_data", required=True)
    parser.add_argument("--data_reader_params", default="")
    parser.add_argument("--minibatch_size", type=int, default=64)
    parser.add_argument("--records_per_task", type=int, default=1024)
    parser.add_argument("--checkpoint_dir_for_init", required=True)
    parser.add_argument("--compute_dtype", default="bfloat16")
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--evaluation_steps", type=int, default=0)
    parser.add_argument("--tensorboard_log_dir", default="")
    parser.add_argument(
        "--num_minibatches_per_task", type=int, default=0
    )
    _add_model_symbol_and_log_arguments(parser)


def add_predict_arguments(parser):
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", default="")
    # must match the train-time binding or checkpoint restore sees a
    # different architecture
    parser.add_argument("--model_params", default="")
    parser.add_argument("--prediction_data", required=True)
    parser.add_argument("--data_reader_params", default="")
    parser.add_argument("--minibatch_size", type=int, default=64)
    parser.add_argument("--records_per_task", type=int, default=1024)
    # required for the batch-job path; the online path
    # (--serving_addr) restores nothing client-side, so the check is
    # deferred to api.predict
    parser.add_argument("--checkpoint_dir_for_init", default="")
    parser.add_argument("--compute_dtype", default="bfloat16")
    parser.add_argument(
        "--num_minibatches_per_task", type=int, default=0
    )
    parser.add_argument(
        "--serving_addr",
        default="",
        help="host:port of a live serving role (ISSUE 8): stream the "
        "prediction data through its Predict RPC instead of submitting "
        "a batch prediction job",
    )
    parser.add_argument(
        "--affinity_key",
        type=int,
        default=0,
        help="online mode against a fleet router (ISSUE 17): affinity "
        "key stamped on every request so this stream keeps hitting the "
        "same replica (and its hot embedding cache); 0 = spread",
    )
    _add_model_symbol_and_log_arguments(parser)


def add_serve_arguments(parser):
    """``edl serve``: submit the online serving role (ISSUE 8) —
    loads a train/export.py artifact, serves Predict, hot-swaps new
    export versions with zero downtime (docs/SERVING.md). With
    ``--router`` (ISSUE 17) the pod is the fleet ROUTER instead:
    affinity routing + failover + canary over replicas that register
    via ``--router_addr``."""
    parser.add_argument(
        "--router", action="store_true", default=False,
        help="submit the serving-fleet router (serve.router_main) "
        "instead of a single serve pod; replicas are serve pods "
        "submitted with --router_addr (docs/SERVING.md 'Fleet "
        "topology')",
    )
    parser.add_argument(
        "--router_addr", default="",
        help="host:port of a fleet router this serve pod should "
        "register with (replica mode); empty = standalone pod",
    )
    parser.add_argument(
        "--min_replicas", type=int, default=-1,
        help="router mode: autoscaler floor (<0 = EDL_SERVE_MIN_REPLICAS)",
    )
    parser.add_argument(
        "--max_replicas", type=int, default=-1,
        help="router mode: autoscaler ceiling "
        "(<0 = EDL_SERVE_MAX_REPLICAS)",
    )
    # required for serve pods, unused by --router (validated in
    # api.serve — argparse can't express the either/or)
    parser.add_argument("--model_zoo", default="")
    parser.add_argument("--model_def", default="")
    parser.add_argument("--model_params", default="")
    parser.add_argument(
        "--export_dir", default="",
        help="train/export.py artifact directory (typically a shared "
        "volume the training job exports into); required unless "
        "--router",
    )
    parser.add_argument("--ps_addrs", default="")
    parser.add_argument("--master_addr", default="")
    parser.add_argument("--port", type=int, default=50052)
    parser.add_argument("--compute_dtype", default="")
    parser.add_argument("--max_batch", type=int, default=0)
    parser.add_argument("--max_delay_ms", type=float, default=-1.0)
    parser.add_argument("--queue_depth", type=int, default=0)
    parser.add_argument("--deadline_ms", type=float, default=-1.0)
    parser.add_argument("--metrics_port", type=int, default=0)


# flags that belong to the client only and must NOT be forwarded to the
# master process command line
_CLIENT_ONLY = {
    "namespace",
    "dry_run",
    "yaml",
    # online-predict mode runs entirely client-side (api.predict)
    "serving_addr",
    "affinity_key",
    "docker_base_url",
    "docker_tlscert",
    "docker_tlskey",
    # the master pod's own spec is built by the client; everything the
    # MASTER needs to build worker/PS pod specs (image, resources,
    # priorities, volume, tpu_resource, pull/restart policy) is
    # forwarded — reference master.py:392-539 re-emits these
    "master_resource_request",
    "master_resource_limit",
    "master_pod_priority",
}


def build_master_arguments(parsed):
    """Re-serialize parsed args into the master command line
    (reference args.py:543-565 build_arguments_from_parsed_result)."""
    parts = []
    for key, value in sorted(vars(parsed).items()):
        if key in _CLIENT_ONLY or key in ("command", "zoo_command", "func"):
            continue
        # identity check for False: `0 in ("", None, False)` is True
        # (0 == False), which would silently drop meaningful zeros like
        # --use_async=0 and leave the master on its own default
        if value is None or value == "" or value is False or value == []:
            continue
        if value is True:
            parts.append("--%s" % key)
        else:
            parts.append("--%s=%s" % (key, value))
    return parts


def parse_resource_string(spec):
    """'cpu=1,memory=4096Mi' -> {'cpu': '1', 'memory': '4096Mi'}
    (reference elasticdl_client/common/k8s_resource.py)."""
    resources = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError("Bad resource segment %r" % part)
        key, value = part.split("=", 1)
        resources[key.strip()] = value.strip()
    return resources


def parse_envs_string(spec):
    envs = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, value = part.split("=", 1)
        envs[key.strip()] = value.strip()
    return envs


def parse_volume_string(spec):
    """'claim_name=x,mount_path=/data' -> pod volume + mount dicts
    (reference elasticdl_client/common/k8s_volume.py). Also supports
    'host_path=/p,mount_path=/data'."""
    if not spec:
        return None
    fields = parse_resource_string(spec)
    mount_path = fields.get("mount_path")
    if not mount_path:
        raise ValueError("volume spec needs mount_path")
    name = "edl-volume-0"
    if "claim_name" in fields:
        volume = {
            "name": name,
            "persistentVolumeClaim": {"claimName": fields["claim_name"]},
        }
    elif "host_path" in fields:
        volume = {"name": name, "hostPath": {"path": fields["host_path"]}}
    else:
        raise ValueError("volume spec needs claim_name or host_path")
    return [{"volume": volume, "mount": {"name": name, "mountPath": mount_path}}]
