"""Serving-fleet data plane: consistent-hash affinity routing (ISSUE 17).

The router is the tier's front door: it exposes the SAME
``elasticdl_tpu.Serve`` surface as a single serve pod (clients point
``--serving_addr`` at it unchanged) plus the replica-facing
``elasticdl_tpu.Router`` control surface, and forwards each predict to
one of N registered replicas:

- **affinity** — requests hash onto a consistent-hash ring
  (``HashRing``: ~64 virtual nodes per replica on a sha256 u64 circle)
  by ``PredictRequest.affinity_key``, so the same user/id range keeps
  landing on the same replica and its hot ``EmbeddingClient`` cache
  stays hot for exactly that id range. A single replica join/leave
  moves ~1/N of the key space (property-tested). Requests without a
  key (0) spread by an internal sequence instead of all hashing to one
  point.
- **failover** — on UNAVAILABLE (replica died, or is mid-drain refusing
  admissions) the router retries the ring's NEXT distinct replica, at
  most ``EDL_ROUTER_FAILOVER_RETRIES`` extra attempts, never the same
  replica twice and never one the registry marked draining. Any other
  status propagates to the caller untouched (a replica's shed is the
  tier's shed — retrying a RESOURCE_EXHAUSTED elsewhere would just
  smear the overload).
- **in-flight caps** — at most ``EDL_ROUTER_INFLIGHT_CAP`` outstanding
  forwards per replica; past the cap the request is SHED
  (RESOURCE_EXHAUSTED) instead of queueing on the slow replica and
  poisoning the whole tier's latency.
- **canary slicing** — when ``serve/canary.py`` runs a rollout, the
  ``EDL_CANARY_FRACTION`` slice of the key space routes only to canary
  members (and the rest only to incumbents); responses feed the
  judge's per-stamp books.
"""

import bisect
import threading

import grpc
import numpy as np

from elasticdl_tpu.common.env_utils import env_int
from elasticdl_tpu.common.hash_utils import stable_u64
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.common import overload
from elasticdl_tpu.common.tensor_utils import blob_to_ndarray
from elasticdl_tpu.observability import metrics as obs_metrics
from elasticdl_tpu.observability import trace
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.serve.canary import CanaryController
from elasticdl_tpu.serve.fleet import ReplicaRegistry

logger = _logger_factory("elasticdl_tpu.serve.router")

INFLIGHT_CAP_ENV = "EDL_ROUTER_INFLIGHT_CAP"
FAILOVER_RETRIES_ENV = "EDL_ROUTER_FAILOVER_RETRIES"

# virtual nodes per replica: enough that one join/leave rebalances
# smoothly (the stddev of the moved-key fraction shrinks ~1/sqrt(v)),
# small enough that ring rebuilds stay trivial at fleet sizes
_VNODES = 64

# forward timeout when the caller sent no deadline at all (transport
# without a timeout): the router must not hold a thread forever
_DEFAULT_FORWARD_SECS = 10.0


class HashRing:
    """Consistent-hash ring over replica ids, sha256-placed vnodes.

    Placement is process-stable (``stable_u64``), so a router restart
    rebuilds the exact same ring from the re-registered replicas and
    affinity survives the restart.
    """

    def __init__(self, vnodes=_VNODES):
        self._vnodes = max(1, int(vnodes))
        self._lock = threading.Lock()
        self._points = {}  # replica_id -> [u64 ring positions]
        self._ring = []  # sorted [(position, replica_id)]

    def add(self, replica_id):
        points = [
            stable_u64("%s#%d" % (replica_id, i))
            for i in range(self._vnodes)
        ]
        with self._lock:
            if replica_id in self._points:
                return
            self._points[replica_id] = points
            self._ring = sorted(
                self._ring + [(p, replica_id) for p in points]
            )

    def remove(self, replica_id):
        with self._lock:
            if self._points.pop(replica_id, None) is None:
                return
            self._ring = [
                (p, rid) for p, rid in self._ring if rid != replica_id
            ]

    def members(self):
        with self._lock:
            return list(self._points)

    def lookup(self, key_hash):
        """The key's primary replica, or None on an empty ring."""
        for rid in self.successors(key_hash):
            return rid
        return None

    def successors(self, key_hash):
        """Distinct replica ids in ring order from the key's position —
        element 0 is the primary, the rest is the failover order. The
        iteration walks a snapshot, so concurrent joins/leaves can't
        tear it mid-request."""
        with self._lock:
            ring = list(self._ring)
        if not ring:
            return
        idx = bisect.bisect_right(ring, (key_hash, chr(0x10FFFF)))
        seen = set()
        for i in range(len(ring)):
            rid = ring[(idx + i) % len(ring)][1]
            if rid not in seen:
                seen.add(rid)
                yield rid


class RouterServicer:
    """Both gRPC surfaces of the router role.

    Client-facing ``Serve`` (predict/model_info — drop-in for a single
    serve pod) and replica-facing ``Router`` (register/heartbeat/
    deregister). The registry/ring/canary trio is owned here so the
    three stay consistent: a replica that leaves (ack, loss, or
    administrative ``forget_replica``) is removed from the ring AND its
    in-flight book in one place.
    """

    def __init__(self, heartbeat_secs=None, replica_timeout_secs=None,
                 inflight_cap=None, failover_retries=None,
                 canary=None, ring=None):
        self._cap = max(1, int(
            inflight_cap
            if inflight_cap is not None
            else env_int(INFLIGHT_CAP_ENV, 64)
        ))
        self._retries = max(0, int(
            failover_retries
            if failover_retries is not None
            else env_int(FAILOVER_RETRIES_ENV, 2)
        ))
        self._ring = ring if ring is not None else HashRing()
        self._registry = ReplicaRegistry(
            on_join=self._ring.add,
            on_leave=self._on_replica_leave,
            heartbeat_secs=heartbeat_secs,
            timeout_secs=replica_timeout_secs,
        )
        self._canary = (
            canary if canary is not None
            else CanaryController(self._registry)
        )
        self._inflight_lock = threading.Lock()
        self._inflight = {}  # replica_id -> outstanding forwards
        self._seq_lock = threading.Lock()
        self._seq = 0
        self._m_requests = obs_metrics.counter(
            "edl_router_requests_total",
            "Routed predict RPCs by replica and outcome",
            ("replica", "outcome"),
        )
        self._m_failovers = obs_metrics.counter(
            "edl_router_failovers_total",
            "Predict forwards retried on a ring successor",
        )

    @property
    def registry(self):
        return self._registry

    @property
    def canary(self):
        return self._canary

    @property
    def ring(self):
        return self._ring

    def tick(self, now=None):
        """Control-plane pass for the role's 1 Hz loop: expire silent
        replicas, advance the canary state machine."""
        self._registry.expire(now)
        self._canary.tick(now)

    def state(self):
        """JSON-ready /statusz section."""
        with self._inflight_lock:
            inflight = dict(self._inflight)
        return {
            "replicas": self._registry.state(),
            "ring": sorted(self._ring.members()),
            "inflight": inflight,
            "canary": self._canary.state(),
        }

    # -- replica control surface (elasticdl_tpu.Router) ----------------
    def register_replica(self, request, context):
        target = self._registry.register(request)
        return pb.RegisterReplicaResponse(
            accepted=True,
            heartbeat_secs=self._registry.heartbeat_secs,
            target_export=target,
        )

    def heartbeat_replica(self, request, context):
        known, drain, target = self._registry.heartbeat(request)
        return pb.ReplicaHeartbeatResponse(
            known=known, drain=drain, target_export=target
        )

    def deregister_replica(self, request, context):
        self._registry.deregister(request)
        return pb.Empty()

    # -- client surface (elasticdl_tpu.Serve) --------------------------
    def predict(self, request, context):
        with trace.root_span("router_predict", role="router"):
            return self._predict(request, context)

    def _predict(self, request, context):
        key_hash = self._key_hash(request.affinity_key)
        arm = self._canary.assign_arm(key_hash)
        allowed = self._arm_members(arm)
        deadline = context.time_remaining()
        if deadline is None or deadline <= 0:
            deadline = _DEFAULT_FORWARD_SECS
        attempts = 0
        tried = set()
        last = None  # (code, detail) of the last forward failure
        for rid in self._ring.successors(key_hash):
            if attempts > self._retries:
                break
            if rid in tried:
                continue  # successors() already dedups; belt+braces
            if not self._registry.is_routable(rid):
                continue  # draining or already gone — never a target
            if allowed is not None and rid not in allowed:
                continue  # the other arm's replica
            stub = self._registry.stub(rid)
            if stub is None:
                continue
            if not self._acquire(rid):
                # the slow-replica guard: shed HERE rather than queue a
                # request behind a replica already at its cap
                self._count(rid, "shed")
                self._canary.note_result(
                    self._arm_stamp(arm), None, "shed"
                )
                self._abort(
                    context, grpc.StatusCode.RESOURCE_EXHAUSTED,
                    "replica %s at in-flight cap %d" % (rid, self._cap),
                )
            tried.add(rid)
            attempts += 1
            if attempts > 1:
                self._m_failovers.inc()
            try:
                response = stub.predict(request, timeout=deadline)
            except grpc.RpcError as e:
                code = e.code()
                detail = e.details() or code.name
                last = (code, detail)
                if code == grpc.StatusCode.UNAVAILABLE:
                    # dead or draining-refusing replica: fail over to
                    # the ring's next distinct replica (bounded, and
                    # `tried` guarantees never the same one twice)
                    self._count(rid, "unavailable")
                    self._canary.note_result(
                        self._arm_stamp(arm), None, "unavailable"
                    )
                    continue
                outcome = (
                    "shed"
                    if code == grpc.StatusCode.RESOURCE_EXHAUSTED
                    else "error"
                )
                self._count(rid, outcome)
                self._canary.note_result(
                    self._arm_stamp(arm), None, outcome
                )
                self._abort(context, code, detail)
            finally:
                self._release(rid)
            self._count(rid, "ok")
            self._canary.note_result(
                response.model_stamp, _mean_prediction(response), "ok"
            )
            return response
        if last is not None:
            self._abort(
                context, grpc.StatusCode.UNAVAILABLE,
                "all %d routable replicas failed; last: %s"
                % (attempts, last[1]),
            )
        self._count("none", "no_replica")
        self._abort(
            context, grpc.StatusCode.UNAVAILABLE,
            "no routable replica registered",
        )

    def model_info(self, request, context):
        """The fleet's identity: a routable replica's answer with
        ``max_batch`` tightened to the fleet minimum (a client sizing
        batches against the router must fit EVERY replica a failover
        could land on)."""
        fleet_cap = self._registry.min_max_batch()
        for rid in self._registry.routable_ids():
            stub = self._registry.stub(rid)
            if stub is None:
                continue
            try:
                info = stub.model_info(
                    pb.Empty(), timeout=overload.rpc_timeout(5.0)
                )
            except grpc.RpcError:
                continue
            if fleet_cap > 0:
                info.max_batch = min(info.max_batch, fleet_cap) \
                    if info.max_batch > 0 else fleet_cap
            return info
        return pb.ModelInfoResponse(loaded=False)

    # -- internals ------------------------------------------------------
    def _key_hash(self, affinity_key):
        if affinity_key:
            return stable_u64("k:%d" % affinity_key)
        # unkeyed requests spread round-robin-ish over the ring instead
        # of all hashing onto one point
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        return stable_u64("seq:%d" % seq)

    def _arm_members(self, arm):
        """The replica subset this arm may route to, or None for "any
        routable". Falls back to None when the arm's subset is empty —
        availability beats slicing purity."""
        if not self._canary.active():
            return None
        members = set(self._canary.canary_members())
        if arm == "canary":
            allowed = members
        else:
            allowed = {
                rid for rid in self._registry.routable_ids()
                if rid not in members
            }
        return allowed or None

    def _arm_stamp(self, arm):
        """Best-effort stamp for booking a FAILED forward (no response
        to read it from): the arm the request was sliced to."""
        state = self._canary.state()
        side = "canary" if arm == "canary" else "incumbent"
        return state[side]["stamp"]

    def _on_replica_leave(self, replica_id):
        self._ring.remove(replica_id)
        with self._inflight_lock:
            self._inflight.pop(replica_id, None)

    def _acquire(self, replica_id):
        with self._inflight_lock:
            n = self._inflight.get(replica_id, 0)
            if n >= self._cap:
                return False
            self._inflight[replica_id] = n + 1
            return True

    def _release(self, replica_id):
        with self._inflight_lock:
            n = self._inflight.get(replica_id, 0)
            if n <= 1:
                self._inflight.pop(replica_id, None)
            else:
                self._inflight[replica_id] = n - 1

    def _count(self, replica_id, outcome):
        self._m_requests.labels(replica=replica_id, outcome=outcome).inc()

    def _abort(self, context, code, detail):
        # same contract as ServeServicer._abort: stamp the status onto
        # the open router_predict span, then abort (which raises)
        trace.annotate(code=code.name)
        context.abort(code, detail)


def _mean_prediction(response):
    """Scalar summary of a response for the canary's distribution book:
    the mean of the first output tensor. None when unreadable (the
    judge just skips the sample)."""
    for blob in response.outputs.values():
        try:
            return float(np.mean(blob_to_ndarray(blob)))
        except Exception:
            logger.debug("unreadable prediction blob", exc_info=True)
            return None
    return None
