"""Serving role entry point.

Usage: python -m elasticdl_tpu.serve.main --model_zoo=... \
    --export_dir=/artifacts/model --port=50052 [--ps_addrs=...]

The full platform treatment of the other roles: /metrics /healthz
/readyz (ready = model loaded), flight-recorder journal, deterministic
fault injection, SIGTERM graceful drain (stop admitting -> flush the
queue -> deregister from the journal's point of view -> exit 0), and —
when a master is running — the same 5 s telemetry piggyback the PS
rides, so /statusz shows the inference side of the fleet.
"""

import argparse
import os
import signal
import sys
import threading
import time

from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.serve.main")


def parse_serve_args(argv=None):
    parser = argparse.ArgumentParser("elasticdl_tpu serve")
    parser.add_argument("--serve_id", type=int, default=0)
    parser.add_argument("--port", type=int, default=50052)
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", default="")
    parser.add_argument("--model_params", default="")
    parser.add_argument(
        "--export_dir", required=True,
        help="train/export.py artifact directory (watched for new "
        "versions; hot-swapped with zero request failures)",
    )
    parser.add_argument(
        "--ps_addrs", default="",
        help="comma-separated PS addresses for sparse-embedding models",
    )
    parser.add_argument(
        "--master_addr", default="",
        help="optional: piggyback serving telemetry on the master's "
        "fleet view (/statusz)",
    )
    parser.add_argument(
        "--router_addr", default="",
        help="fleet mode (ISSUE 17): register with the serving router "
        "at this address and heartbeat telemetry + export versions; "
        "--export_dir then names the VERSIONED export root (one "
        "subdirectory per bundle) and the router directs which "
        "version this replica loads",
    )
    parser.add_argument(
        "--advertise_addr", default="",
        help="address the router should reach this replica at "
        "(default 127.0.0.1:<port> — the local-subprocess topology)",
    )
    # must match the training job's compute dtype for prediction parity
    parser.add_argument("--compute_dtype", default="")
    parser.add_argument(
        "--max_batch", type=int, default=0,
        help="rows per formed batch (0 = EDL_SERVE_MAX_BATCH or 32)",
    )
    parser.add_argument(
        "--max_delay_ms", type=float, default=-1.0,
        help="batch formation window (<0 = EDL_SERVE_MAX_DELAY_MS or 5)",
    )
    parser.add_argument(
        "--queue_depth", type=int, default=0,
        help="admission bound; beyond it requests shed "
        "(0 = EDL_SERVE_QUEUE_DEPTH or 256)",
    )
    parser.add_argument(
        "--deadline_ms", type=float, default=-1.0,
        help="default per-request budget when the RPC carries none "
        "(<0 = EDL_SERVE_DEADLINE_MS or 1000)",
    )
    parser.add_argument(
        "--cache_ttl_secs", type=float, default=-1.0,
        help="embedding row cache TTL (<0 = EDL_SERVE_CACHE_TTL_SECS "
        "or 2; 0 disables the cache)",
    )
    parser.add_argument(
        "--watch_secs", type=float, default=-1.0,
        help="export watch interval (<0 = EDL_SERVE_WATCH_SECS or 2)",
    )
    parser.add_argument("--metrics_port", type=int, default=0)
    return parser.parse_args(argv)


class ServeRole:
    def __init__(self, args):
        from elasticdl_tpu.serve.engine import ServingEngine

        self.args = args
        ps_client = None
        if args.ps_addrs:
            from elasticdl_tpu.worker.ps_client import PSClient

            ps_client = PSClient(args.ps_addrs)
        self.engine = ServingEngine(
            args.model_zoo,
            args.export_dir,
            ps_client=ps_client,
            model_def=args.model_def,
            model_params=args.model_params,
            compute_dtype=args.compute_dtype or None,
            max_batch=args.max_batch or None,
            max_delay_ms=(
                args.max_delay_ms if args.max_delay_ms >= 0 else None
            ),
            queue_depth=args.queue_depth or None,
            deadline_ms=(
                args.deadline_ms if args.deadline_ms >= 0 else None
            ),
            cache_ttl_secs=(
                args.cache_ttl_secs if args.cache_ttl_secs >= 0 else None
            ),
            watch_secs=args.watch_secs if args.watch_secs >= 0 else None,
            directed=bool(args.router_addr),
        )
        self._master_client = None
        if args.master_addr:
            from elasticdl_tpu.worker.master_client import MasterClient

            # worker_host="": the serve role is not a mesh member; the
            # negative id namespace keeps it out of the worker id space
            # (the PS uses -(ps_id+1); serving sits below at -1000)
            self._master_client = MasterClient(
                args.master_addr,
                worker_id=-(1000 + args.serve_id),
                worker_host="",
            )
            if env_str("EDL_TELEMETRY", "") != "0":
                self._master_client.telemetry_provider = self.telemetry_blob
        self.server = None
        self.observability = None
        # fleet link (ISSUE 17): register/heartbeat with the router
        self.replica_id = "serve-%d-%d" % (args.serve_id, os.getpid())
        self._advertise_addr = (
            args.advertise_addr or "127.0.0.1:%d" % args.port
        )
        self._router_stub = None
        self._fleet_thread = None
        self._registered = False
        self._drain_reason = "sigterm"
        self._drained = threading.Event()
        # SIGTERM arrival marker: a plain bool write is the only thing
        # the signal handler does (atomic, lock-free, reentrant-safe);
        # run() polls it and performs the actual drain (_finish_term)
        self._term_flag = False
        self._term_previous = None
        self._qps_window = (time.monotonic(), 0)  # (ts, served_total)

    def telemetry_blob(self):
        from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

        batcher = self.engine.batcher
        now = time.monotonic()
        last_ts, last_served = self._qps_window
        served = batcher.served_total
        elapsed = max(now - last_ts, 1e-6)
        self._qps_window = (now, served)
        info = self.engine.model_info()
        blob = pb.TelemetryBlob(
            role="serve-%d" % self.args.serve_id,
            serve_qps=(served - last_served) / elapsed,
            serve_queue_depth=batcher.pending_count(),
            serve_shed_total=batcher.shed_total,
            model_version=max(info["step"], 0),
            tier_hit_rate=(
                self.engine.cache.hit_rate()
                if self.engine.cache is not None
                else 0.0
            ),
        )
        # device runtime (ISSUE 18): the replica's compile ledger +
        # HBM gauges — a serve recompile means a request batch dodged
        # the padded-shape contract, which the fleet's recompile_storm
        # detector should hear about like any worker's churn
        from elasticdl_tpu.observability import device as device_obs

        dev = device_obs.telemetry()
        if dev:
            blob.xla_compiles = dev["xla_compiles"]
            blob.xla_recompiles = dev["xla_recompiles"]
            blob.xla_compile_secs_total = dev["xla_compile_secs_total"]
            blob.hbm_bytes_in_use = dev["hbm_bytes_in_use"]
            blob.hbm_peak_bytes = dev["hbm_peak_bytes"]
            blob.hbm_limit_bytes = dev["hbm_limit_bytes"]
            blob.device_live_buffers = dev["device_live_buffers"]
            blob.h2d_bytes = dev["h2d_bytes"]
            blob.d2h_bytes = dev["d2h_bytes"]
        return blob

    # ------------------------------------------------------------------
    def prepare(self):
        from elasticdl_tpu.common.grpc_utils import build_server
        from elasticdl_tpu.observability import (
            events,
            http_server,
            profiler,
            trace,
        )
        from elasticdl_tpu.proto.services import (
            add_serve_servicer_to_server,
        )
        from elasticdl_tpu.serve.servicer import ServeServicer

        role = "serve-%d" % self.args.serve_id
        trace.configure(role)
        events.configure(role)
        events.emit("role_start", port=self.args.port)
        # continuous profiler (ISSUE 14): always-on when EDL_PROF_HZ is
        # set, served as /profilez on the observability port below
        profiler.maybe_start(role)
        self.engine.start()
        self.server = build_server()
        add_serve_servicer_to_server(ServeServicer(self.engine), self.server)
        self.server.add_insecure_port("[::]:%d" % self.args.port)
        self.server.start()
        self.observability = http_server.maybe_start(
            role, cli_port=self.args.metrics_port
        )
        if self.observability is not None:
            # readiness milestone: a loaded model — before it, predict
            # answers FAILED_PRECONDITION and the pod must hold traffic
            self.observability.add_readiness_check(
                "model_loaded", lambda: self.engine.loaded
            )
        self._install_sigterm_drain()
        if self.args.router_addr:
            self._start_fleet_link()
        logger.info(
            "serve %d on :%d (export %s)",
            self.args.serve_id, self.args.port, self.args.export_dir,
        )
        return self

    # -- fleet link (ISSUE 17) -----------------------------------------
    def _start_fleet_link(self):
        from elasticdl_tpu.common.grpc_utils import build_channel
        from elasticdl_tpu.proto import services

        self._router_stub = services.RouterStub(
            build_channel(self.args.router_addr)
        )
        self._fleet_thread = threading.Thread(
            target=self._fleet_loop, name="edl-serve-fleet", daemon=True
        )
        self._fleet_thread.start()

    def _fleet_loop(self):
        """Register with the router, then heartbeat until drained.

        Heartbeats carry telemetry + the loaded/newest-available export
        versions UP and directives DOWN: ``target_export`` steers the
        directed engine (canary/promote/rollback) and ``drain`` routes
        this replica through the exact SIGTERM drain path a kubelet
        eviction would (stop admitting, flush, deregister, exit 0) —
        the run loop just sees the same flag the signal handler sets."""
        from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
        from elasticdl_tpu.serve.fleet import scan_export_versions

        heartbeat_secs = 2.0
        while not (self._drained.is_set() or self._term_flag):
            try:
                if not self._registered:
                    resp = self._router_stub.register_replica(
                        pb.RegisterReplicaRequest(
                            replica_id=self.replica_id,
                            addr=self._advertise_addr,
                            max_batch=self.engine.batcher.max_batch,
                            model_stamp=self.engine.model_info()["stamp"],
                            telemetry=self.telemetry_blob(),
                        ),
                        timeout=5.0,
                    )
                    if resp.heartbeat_secs > 0:
                        heartbeat_secs = resp.heartbeat_secs
                    if resp.target_export:
                        self.engine.set_target(resp.target_export)
                    self._registered = True
                    logger.info(
                        "registered with router %s as %s",
                        self.args.router_addr, self.replica_id,
                    )
                else:
                    versions = scan_export_versions(self.args.export_dir)
                    newest = versions[-1] if versions else ("", 0, "")
                    info = self.engine.model_info()
                    resp = self._router_stub.heartbeat_replica(
                        pb.ReplicaHeartbeatRequest(
                            replica_id=self.replica_id,
                            loaded_export=self.engine.loaded_export,
                            loaded_stamp=info["stamp"],
                            available_export=newest[0],
                            available_stamp=newest[2],
                            draining=self._term_flag,
                            telemetry=self.telemetry_blob(),
                        ),
                        timeout=5.0,
                    )
                    if not resp.known:
                        # the router restarted (or expired us while
                        # partitioned): re-register from scratch
                        self._registered = False
                        continue
                    if resp.target_export:
                        self.engine.set_target(resp.target_export)
                    if resp.drain:
                        self._drain_reason = "router_drain"
                        self._term_flag = True
                        return
            except Exception:
                # router unreachable: keep trying — the tier outlives
                # a router restart, and re-registration is idempotent
                logger.debug("router link hiccup", exc_info=True)
            time.sleep(heartbeat_secs if self._registered else 1.0)

    def _deregister(self, reason):
        """The exactly-once drain ack (fleet mode): tell the router the
        queue is flushed so it forgets this replica with no
        ``replica_lost`` alert. Best-effort — a dead router just means
        the heartbeat timeout journals the loss instead."""
        if self._router_stub is None or not self._registered:
            return
        self._registered = False
        from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

        try:
            self._router_stub.deregister_replica(
                pb.DeregisterReplicaRequest(
                    replica_id=self.replica_id,
                    reason=reason,
                    served=self.engine.batcher.served_total,
                    shed=self.engine.batcher.shed_total,
                ),
                timeout=5.0,
            )
        except Exception:
            logger.warning(
                "drain ack to router failed (router gone?)", exc_info=True
            )

    def _install_sigterm_drain(self):
        self._term_previous = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            # Flag-only: the handler interrupts the main thread, which
            # may be inside the batcher or the event journal holding
            # their locks — draining here (MicroBatcher.drain takes
            # _cond and joins the batch thread) self-deadlocks until
            # the pod's SIGKILL. run() observes the flag within one
            # poll tick and drains with no lock held (_finish_term).
            self._term_flag = True

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            logger.warning(
                "not on main thread; serve SIGTERM drain not installed"
            )

    def _finish_term(self):
        """The deferred SIGTERM drain (what the handler used to do
        inline), on the run loop with no lock held; then chains the
        flight-recorder hook (which dumps the ring and exits 0). A
        router drain directive funnels through the same flag with its
        own reason — the ISSUE 7/8 contract: shrink victims exit
        through the graceful path, not a bare kill."""
        self.drain(reason=self._drain_reason)
        previous = self._term_previous
        if callable(previous):
            previous(signal.SIGTERM, None)
        return 0

    def drain(self, reason="shutdown"):
        """Stop admitting, flush the queue, stop the server. Idempotent
        (the SIGTERM handler and an orderly exit may both arrive)."""
        from elasticdl_tpu.observability import events, trace

        if self._drained.is_set():
            return
        self._drained.set()
        flushed = self.engine.drain()
        # drain ack AFTER the flush (the count in the ack is final)
        # and BEFORE the server stops — the router already stopped
        # routing here the moment it directed the drain
        self._deregister(reason)
        # trace flush ARMS here, before the crash hooks run (ISSUE 9):
        # the queue just finished flushing, so every request span is
        # final — a SIGKILL-grace-window race after this line loses
        # nothing. The chained install_crash_hooks handler flushes
        # again; TraceWriter.flush is idempotent on an empty buffer.
        trace.flush()
        if trace.enabled():
            events.emit("trace_flushed", reason=reason)
        try:
            if self.server is not None:
                self.server.stop(grace=2.0)
        except Exception:
            logger.exception("server stop at drain failed")
        events.emit(
            "serve_drained", reason=reason, flushed=flushed,
            served=self.engine.batcher.served_total,
            shed=self.engine.batcher.shed_total,
        )
        events.emit("role_stop", reason=reason)
        events.flush()

    def run(self, poll_secs=5.0):
        """Serve until stopped. Unlike the PS, a master going away does
        NOT stop serving — the inference tier outlives training jobs;
        the poll exists only to feed fleet telemetry while a master is
        around."""
        if self.args.router_addr:
            # fleet mode drains on a router directive too; poll tight
            # enough that a shrink victim leaves within ~a second
            poll_secs = min(poll_secs, 1.0)
        if self._master_client is None:
            # bounded wait so a SIGTERM flag is noticed within one poll
            # even though the handler no longer stops the server itself
            while self.server.wait_for_termination(timeout=poll_secs):
                if self._term_flag:
                    return self._finish_term()
            return 0
        while not self._drained.is_set():
            time.sleep(poll_secs)
            if self._term_flag:
                return self._finish_term()
            try:
                self._master_client.get_comm_info()
            except Exception:
                logger.debug("telemetry poll failed (master gone?)")
        return 0


def main(argv=None):
    from elasticdl_tpu.common.platform import apply_platform_overrides

    apply_platform_overrides()
    args = parse_serve_args(argv)
    from elasticdl_tpu.testing import faults

    faults.set_role("serve-%d" % args.serve_id)
    if args.metrics_port:
        from elasticdl_tpu.observability import http_server

        # publish before any instrument is constructed: the registry
        # decides enabled/no-op at first touch
        os.environ[http_server.PORT_ENV] = str(args.metrics_port)
    from elasticdl_tpu.observability import events

    # SIGTERM chain order (the PS pattern): crash hooks install first;
    # prepare()'s handler registers last and only flags — run() then
    # drains (stop admitting + flush) off the signal path and chains
    # into the ring dump + exit 0
    events.install_crash_hooks()
    return ServeRole(args).prepare().run()


if __name__ == "__main__":
    sys.exit(main())
