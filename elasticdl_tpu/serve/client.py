"""Client for the Serve service: the ``edl predict --serving_addr``
path, the bench load generator, and tests all speak through this."""

import numpy as np

from elasticdl_tpu.common.constants import GRPC
from elasticdl_tpu.common.grpc_utils import build_channel
from elasticdl_tpu.common.tensor_utils import blob_to_ndarray, ndarray_to_blob
from elasticdl_tpu.observability.grpc_metrics import instrument_channel
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.proto.services import ServeStub
from elasticdl_tpu.serve.model import SINGLE_INPUT_KEY


class ServeClient:
    def __init__(self, addr):
        self._channel = instrument_channel(build_channel(addr))
        self._stub = ServeStub(self._channel)

    def predict(self, features, deadline_secs=None, deadline_ms=0,
                affinity_key=0):
        """``features``: dict of batch-leading arrays, or one bare
        array (single-input models). Returns (outputs dict, model
        step, model stamp). ``deadline_secs`` sets the gRPC deadline;
        ``deadline_ms`` rides in-message. The server sheds (never
        serves late) a request that outlives the TIGHTER of the two —
        so deadline_ms is honored even under this client's default
        transport timeout. ``affinity_key`` (a user/entity id) only
        matters against a fleet router: same key -> same replica, so
        its hot embedding cache keeps serving that id range; a single
        serve pod ignores it."""
        request = pb.PredictRequest(
            deadline_ms=int(deadline_ms),
            affinity_key=int(affinity_key),
        )
        if not isinstance(features, dict):
            features = {SINGLE_INPUT_KEY: features}
        for name, value in features.items():
            ndarray_to_blob(np.asarray(value), request.features[name])
        response = self._stub.predict(
            request,
            timeout=(
                deadline_secs if deadline_secs is not None
                else GRPC.DEFAULT_RPC_TIMEOUT_SECS
            ),
        )
        outputs = {
            name: blob_to_ndarray(blob)
            for name, blob in response.outputs.items()
        }
        return outputs, response.model_step, response.model_stamp

    def model_info(self):
        response = self._stub.model_info(
            pb.Empty(), timeout=GRPC.DEFAULT_RPC_TIMEOUT_SECS
        )
        return {
            "loaded": response.loaded,
            "step": response.step,
            "stamp": response.stamp,
            "model_zoo": response.model_zoo,
            # 0 from a pre-ISSUE-8-review server: treat as unknown
            "max_batch": response.max_batch,
        }

    def close(self):
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
