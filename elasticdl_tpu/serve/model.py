"""One loaded, servable model version.

A ``ServingModel`` is immutable once built: the export's dense params
re-applied through the model-zoo module, a jitted eval forward (the
SAME ``make_eval_step`` the trainer scores with — served predictions
are bit-exact with the trainer's eval forward on the same batch,
test-enforced), and for sparse models a read-only
``SparseBatchPreparer`` resolving ids through the extracted embedding
client against the live PS. Version hot-swap builds a NEW ServingModel
and swaps the engine's reference; in-flight batches keep serving from
the instance that admitted them.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.data.pipeline import MASK_KEY, normalize_outputs
from elasticdl_tpu.observability import device as device_obs
from elasticdl_tpu.train.export import load_exported
from elasticdl_tpu.train.step_fns import make_eval_step
from elasticdl_tpu.train.train_state import TrainState, resolve_dtype

logger = _logger_factory("elasticdl_tpu.serve.model")

# single-input models (features is one bare array, not a dict) wire
# their tensor under this reserved feature key
SINGLE_INPUT_KEY = "__input__"


def export_signature(path):
    """Identity stamp of an export artifact: ``"<step>:<npz mtime_ns>"``
    or None while the artifact is absent/incomplete. The version
    watcher polls this; a changed stamp is a new servable version.
    Stat-only (no parse): manifest.json is written AFTER model.npz
    (train/export.py), so its presence implies a complete bundle."""
    manifest = os.path.join(path, "manifest.json")
    npz = os.path.join(path, "model.npz")
    try:
        manifest_stat = os.stat(manifest)
        npz_stat = os.stat(npz)
    except OSError:
        return None
    import json

    try:
        with open(manifest) as f:
            step = int(json.load(f).get("step", -1))
    except (OSError, ValueError):
        return None
    return "%d:%d:%d" % (step, npz_stat.st_mtime_ns, manifest_stat.st_mtime_ns)


class ServingModel:
    """One export, loaded and jit-compiled, behind a padded-batch
    ``predict``.

    ``max_batch`` fixes the compiled batch shape: every formed batch is
    zero-padded to it (padding rows ride the trainer's own ``MASK_KEY``
    machinery, so padded ids never pull or materialize PS rows), and
    XLA compiles the forward exactly once per version.
    """

    def __init__(self, spec, export_path, max_batch,
                 ps_client=None, cache=None, compute_dtype=None):
        self.spec = spec
        self.export_path = export_path
        self.max_batch = int(max_batch)
        self.stamp = export_signature(export_path)
        if self.stamp is None:
            raise FileNotFoundError(
                "no complete export at %r (model.npz + manifest.json)"
                % export_path
            )
        params, model_state, step = load_exported(export_path)
        self.step = int(step)
        model = spec.custom_model()
        # recompile sentinel (ISSUE 18): padded batches mean exactly
        # one compile per loaded version; anything more is shape churn
        self._eval_fn = device_obs.instrumented_jit(
            make_eval_step(model, resolve_dtype(compute_dtype)),
            name="serve_eval",
        )
        # opt_state is the trainer's business; the eval forward reads
        # only params + model_state
        self.state = TrainState(
            step=jnp.asarray(self.step, jnp.int32),
            params=params,
            model_state=model_state,
            opt_state=(),
        )
        self._preparer = None
        if spec.sparse_embedding_specs:
            if ps_client is None:
                raise ValueError(
                    "model %r declares sparse embedding tables; serving "
                    "it needs a PS client (--ps_addrs)" % (
                        getattr(spec.module, "__name__", spec.module),
                    )
                )
            from elasticdl_tpu.train.sparse import SparseBatchPreparer

            # read_only: tables were created by the training job; a PS
            # relaunch invalidates the cache but registers nothing. The
            # preparer IS the trainer's — same unique/indices planning,
            # same EmbeddingClient pull/cache stack (ISSUE 8's no-fork
            # contract).
            self._preparer = SparseBatchPreparer(
                spec.sparse_embedding_specs(batch_size=self.max_batch),
                ps_client,
                cache=cache,
                read_only=True,
            )

    @property
    def sparse(self):
        return self._preparer is not None

    @property
    def embedding_hit_rate(self):
        if self._preparer is None:
            return 0.0
        return self._preparer._embedding.hit_rate()

    # ------------------------------------------------------------------
    def _pad(self, features, rows):
        """Zero-pad every feature's leading dim to max_batch and build
        the row mask padding rides under."""
        if rows > self.max_batch:
            raise ValueError(
                "batch of %d rows exceeds max_batch %d"
                % (rows, self.max_batch)
            )

        def pad(leaf):
            leaf = np.asarray(leaf)
            if leaf.shape[0] == self.max_batch:
                return leaf
            fill = np.zeros(
                (self.max_batch - leaf.shape[0],) + leaf.shape[1:],
                leaf.dtype,
            )
            return np.concatenate([leaf, fill], axis=0)

        mask = np.zeros((self.max_batch,), np.float32)
        mask[:rows] = 1.0
        if isinstance(features, dict):
            return {k: pad(v) for k, v in features.items()}, mask
        return pad(features), mask

    def predict(self, features, rows):
        """``features``: dict of batch-leading arrays (or one bare
        array for single-input models) with ``rows`` real rows;
        returns ``{output name: array[rows, ...]}``."""
        padded, mask = self._pad(features, rows)
        if self._preparer is not None:
            # the trainer's own prepare path: unique ids -> cached/
            # fused-pulled rows + indices features; MASK_KEY keeps the
            # padding rows' zero-ids out of the unique set entirely
            batch = {"features": dict(padded), MASK_KEY: mask}
            prepared, _ = self._preparer.prepare(batch)
            padded = prepared["features"]
        outputs = self._eval_fn(self.state, padded)
        outputs = jax.tree_util.tree_map(np.asarray, outputs)
        return normalize_outputs(outputs, rows)

    def warm(self, template_features=None, template_rows=1):
        """Compile (and prime the embedding cache for) this version
        before it takes traffic: one predict on the template — the hot
        swap's no-cold-start half. Without a template (nothing served
        yet) the first real request compiles instead."""
        if template_features is None:
            return False
        self.predict(template_features, template_rows)
        return True
