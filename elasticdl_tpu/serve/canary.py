"""Canary rollout judge for the serving fleet (ISSUE 17).

New export versions do not hit the whole tier at once: the router's
``CanaryController`` notices a fresh export bundle (replicas report the
newest complete bundle in their heartbeats), directs a canary subset of
replicas to load it, and slices ``EDL_CANARY_FRACTION`` of traffic onto
that subset by affinity-key hash. While the canary runs, the router
attributes every response to the model stamp that actually served it
(``PredictResponse.model_stamp`` — correct even mid-swap, when a canary
member still answers from the incumbent) and accumulates two
``PredictionStats`` books: prediction-score histograms plus error/shed
tallies for canary and incumbent over the SAME window.

The judge generalizes the training fleet's drift detectors (ISSUE 15's
label-shift EWMA on ``FleetMonitor``): instead of a mean-shift test on
a streaming window it compares the full prediction distributions by
total-variation distance. Once both arms saw
``EDL_CANARY_MIN_REQUESTS`` requests:

- **promote** — TV distance within ``EDL_CANARY_DRIFT_MAX`` AND the
  canary's error+shed rate no worse than the incumbent's (plus a small
  absolute slack): every replica is directed to the new export and it
  becomes the incumbent (new joiners load it at register time).
- **rollback** — otherwise: canary members are directed back to the
  incumbent export and the rejected stamp is remembered so the same
  bad bundle is never retried (a NEWER export clears the way again).
- a canary that cannot reach the verdict inside
  ``EDL_CANARY_TIMEOUT_SECS`` rolls back too ("timeout" reason) — a
  slice that never fills is itself evidence the version isn't taking
  traffic.

Every transition is journaled (``canary_started`` / ``canary_promoted``
/ ``canary_rolled_back``) with the measured numbers as reasons, so a
postmortem explains every rollout the same way ``scale_decision``
explains every resize.
"""

import threading
import time

from elasticdl_tpu.common.env_utils import env_float, env_int
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import events
from elasticdl_tpu.observability import metrics as obs_metrics

logger = _logger_factory("elasticdl_tpu.serve.canary")

CANARY_FRACTION_ENV = "EDL_CANARY_FRACTION"
CANARY_MIN_REQUESTS_ENV = "EDL_CANARY_MIN_REQUESTS"
CANARY_DRIFT_MAX_ENV = "EDL_CANARY_DRIFT_MAX"
CANARY_TIMEOUT_ENV = "EDL_CANARY_TIMEOUT_SECS"

# absolute slack on the error-rate comparison: a canary may be this
# much worse than the incumbent before the judge calls it a regression
# (two error-free arms should not flip on one unlucky shed)
_ERROR_SLACK = 0.02

_BINS = 10
# the traffic slice is cut on this many hash buckets, so the fraction
# resolves to 1/10000 granularity
_SLICE_BUCKETS = 10000


class PredictionStats:
    """One arm's book: prediction-score histogram + outcome tallies.

    Scores are the per-request mean predicted value clipped to [0, 1]
    (CTR-style models emit probabilities; anything else still lands in
    a comparable bucket). Thread-safe: the router's worker threads feed
    it concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._bins = [0] * _BINS
        self._sum = 0.0
        self._predictions = 0
        self._outcomes = {}  # outcome -> count

    def observe_prediction(self, value):
        v = min(1.0, max(0.0, float(value)))
        idx = min(_BINS - 1, int(v * _BINS))
        with self._lock:
            self._bins[idx] += 1
            self._sum += v
            self._predictions += 1

    def observe_outcome(self, outcome):
        with self._lock:
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1

    @property
    def predictions(self):
        with self._lock:
            return self._predictions

    def distribution(self):
        with self._lock:
            total = self._predictions
            if total == 0:
                return [0.0] * _BINS
            return [b / total for b in self._bins]

    def mean(self):
        with self._lock:
            if self._predictions == 0:
                return 0.0
            return self._sum / self._predictions

    def failure_rate(self):
        """(errors + sheds) / all outcomes — the canary must not buy a
        drifted model OR a slower one that sheds."""
        with self._lock:
            total = sum(self._outcomes.values())
            if total == 0:
                return 0.0
            bad = sum(
                n for o, n in self._outcomes.items() if o != "ok"
            )
            return bad / total

    def snapshot(self):
        with self._lock:
            return {
                "predictions": self._predictions,
                "mean": round(self._sum / self._predictions, 4)
                if self._predictions else 0.0,
                "outcomes": dict(self._outcomes),
            }


def total_variation(p, q):
    """TV distance between two discrete distributions: 0 identical,
    1 disjoint. The promote gate is ``tv <= EDL_CANARY_DRIFT_MAX``."""
    return 0.5 * sum(abs(a - b) for a, b in zip(p, q))


class CanaryController:
    """The rollout state machine: idle -> canary -> promote/rollback."""

    def __init__(self, registry, fraction=None, min_requests=None,
                 drift_max=None, timeout_secs=None):
        self._registry = registry
        self._fraction = min(1.0, max(0.0, (
            fraction
            if fraction is not None
            else env_float(CANARY_FRACTION_ENV, 0.25)
        )))
        self._min_requests = max(1, (
            min_requests
            if min_requests is not None
            else env_int(CANARY_MIN_REQUESTS_ENV, 200)
        ))
        self._drift_max = (
            drift_max
            if drift_max is not None
            else env_float(CANARY_DRIFT_MAX_ENV, 0.25)
        )
        self._timeout = (
            timeout_secs
            if timeout_secs is not None
            else env_float(CANARY_TIMEOUT_ENV, 120.0)
        )
        self._lock = threading.Lock()
        self._state = "idle"
        self._incumbent_export = ""
        self._incumbent_stamp = ""
        self._canary_export = ""
        self._canary_stamp = ""
        self._members = []
        self._started_at = 0.0
        self._rejected = set()  # stamps that rolled back; never retried
        self._incumbent_stats = PredictionStats()
        self._canary_stats = PredictionStats()
        self._m_cycles = obs_metrics.counter(
            "edl_serve_canary_total",
            "Canary rollout transitions", ("outcome",),
        )
        for outcome in ("started", "promoted", "rolled_back"):
            self._m_cycles.labels(outcome=outcome)

    # -- data-plane feed -----------------------------------------------
    def assign_arm(self, key_hash):
        """Which arm serves this request: the canary subset takes the
        ``EDL_CANARY_FRACTION`` slice of the key space (stable per key:
        a user either IS in the canary or is not — flapping between
        arms would blur both books). Answers "incumbent" whenever no
        canary runs."""
        with self._lock:
            if self._state != "canary":
                return "incumbent"
            slice_width = int(self._fraction * _SLICE_BUCKETS)
            if key_hash % _SLICE_BUCKETS < slice_width:
                return "canary"
            return "incumbent"

    def canary_members(self):
        with self._lock:
            return list(self._members)

    def active(self):
        with self._lock:
            return self._state == "canary"

    def note_result(self, stamp, mean_prediction, outcome):
        """Attribute one response to the arm whose model served it —
        by the RESPONSE's stamp, not by which replica answered, so a
        canary member still mid-swap books under the incumbent."""
        with self._lock:
            if self._state != "canary":
                return
            if stamp == self._canary_stamp:
                book = self._canary_stats
            elif stamp == self._incumbent_stamp:
                book = self._incumbent_stats
            else:
                return
        book.observe_outcome(outcome)
        if mean_prediction is not None and outcome == "ok":
            book.observe_prediction(mean_prediction)

    # -- control loop ---------------------------------------------------
    def tick(self, now=None):
        """One pass on the router's 1 Hz tick. Never raises."""
        try:
            self._tick(time.time() if now is None else now)
        except Exception:
            logger.exception("canary tick failed")

    def _tick(self, now):
        with self._lock:
            state = self._state
        if state == "idle":
            self._maybe_adopt_incumbent()
            self._maybe_start(now)
        else:
            self._maybe_judge(now)

    def state(self):
        """JSON-ready /statusz section."""
        with self._lock:
            return {
                "state": self._state,
                "incumbent": {
                    "export": self._incumbent_export,
                    "stamp": self._incumbent_stamp,
                },
                "canary": {
                    "export": self._canary_export,
                    "stamp": self._canary_stamp,
                    "members": list(self._members),
                    "fraction": self._fraction,
                },
                "books": {
                    "incumbent": self._incumbent_stats.snapshot(),
                    "canary": self._canary_stats.snapshot(),
                },
                "rejected": sorted(self._rejected),
            }

    # -- internals ------------------------------------------------------
    def _maybe_adopt_incumbent(self):
        """Bootstrap: before any rollout the incumbent is whatever the
        fleet already runs — the export most replicas report loaded."""
        with self._lock:
            if self._incumbent_stamp:
                return
        votes = {}  # (export, stamp) -> count
        for rid in self._registry.routable_ids():
            entry = self._registry.get(rid)
            # loaded_export only arrives with the first heartbeat
            # (register carries the stamp alone) — a nameless vote
            # would adopt an incumbent no replica can be directed to
            if (entry is None or not entry.loaded_stamp
                    or not entry.loaded_export):
                continue
            key = (entry.loaded_export, entry.loaded_stamp)
            votes[key] = votes.get(key, 0) + 1
        if not votes:
            return
        (export, stamp), _ = max(votes.items(), key=lambda kv: kv[1])
        with self._lock:
            if self._incumbent_stamp:
                return
            self._incumbent_export = export
            self._incumbent_stamp = stamp
        self._registry.set_default_target(export)
        # pin the whole fleet: before the adopt, directed replicas
        # bootstrap onto "newest available" — from here on every
        # version move goes through the canary state machine
        self._registry.set_target(self._registry.live_ids(), export)
        logger.info(
            "canary: adopted incumbent %r (stamp %s)", export, stamp
        )

    def _maybe_start(self, now):
        with self._lock:
            if not self._incumbent_stamp:
                return
            incumbent_stamp = self._incumbent_stamp
            rejected = set(self._rejected)
        # the newest complete bundle any routable replica can see that
        # is neither the incumbent nor a rejected stamp
        candidate = None  # (step, export, stamp)
        for rid in self._registry.routable_ids():
            entry = self._registry.get(rid)
            if entry is None or not entry.available_stamp:
                continue
            stamp = entry.available_stamp
            if stamp == incumbent_stamp or stamp in rejected:
                continue
            step = int(stamp.split(":", 1)[0])
            if candidate is None or step > candidate[0]:
                candidate = (step, entry.available_export, stamp)
        if candidate is None:
            return
        _, export, stamp = candidate
        routable = self._registry.routable_ids()
        if not routable:
            return
        members = sorted(routable)[
            : max(1, round(self._fraction * len(routable)))
        ]
        with self._lock:
            self._state = "canary"
            self._canary_export = export
            self._canary_stamp = stamp
            self._members = members
            self._started_at = now
            self._incumbent_stats = PredictionStats()
            self._canary_stats = PredictionStats()
        self._registry.set_target(members, export, canary=True)
        self._m_cycles.labels(outcome="started").inc()
        logger.info(
            "canary started: export %r (stamp %s) on %s, %.0f%% of "
            "traffic", export, stamp, members, self._fraction * 100,
        )
        events.emit(
            "canary_started", export=export, stamp=stamp,
            members=members, fraction=self._fraction,
        )

    def _maybe_judge(self, now):
        with self._lock:
            canary_n = self._canary_stats.predictions
            incumbent_n = self._incumbent_stats.predictions
            waited = now - self._started_at
        if waited > self._timeout and (
            canary_n < self._min_requests
            or incumbent_n < self._min_requests
        ):
            self._rollback([
                "timeout: %d canary / %d incumbent requests after "
                "%.0fs < %d minimum"
                % (canary_n, incumbent_n, waited, self._min_requests),
            ])
            return
        if canary_n < self._min_requests or (
            incumbent_n < self._min_requests
        ):
            return
        tv = total_variation(
            self._canary_stats.distribution(),
            self._incumbent_stats.distribution(),
        )
        fail_c = self._canary_stats.failure_rate()
        fail_i = self._incumbent_stats.failure_rate()
        measured = (
            "tv=%.3f (max %.3f), failure %.3f vs incumbent %.3f, "
            "mean %.4f vs %.4f over %d/%d requests"
            % (tv, self._drift_max, fail_c, fail_i,
               self._canary_stats.mean(), self._incumbent_stats.mean(),
               canary_n, incumbent_n)
        )
        reasons = []
        if tv > self._drift_max:
            reasons.append("prediction drift: " + measured)
        if fail_c > fail_i + _ERROR_SLACK:
            reasons.append("failure regression: " + measured)
        if reasons:
            self._rollback(reasons)
        else:
            self._promote(["healthy: " + measured])

    def _promote(self, reasons):
        with self._lock:
            export = self._canary_export
            stamp = self._canary_stamp
            self._incumbent_export = export
            self._incumbent_stamp = stamp
            self._state = "idle"
            members = self._members
            self._members = []
            self._canary_export = ""
            self._canary_stamp = ""
        self._registry.set_target(
            self._registry.live_ids(), export, canary=False
        )
        self._registry.set_default_target(export)
        self._m_cycles.labels(outcome="promoted").inc()
        logger.info("canary promoted: %r — %s", export,
                    "; ".join(reasons))
        events.emit(
            "canary_promoted", export=export, stamp=stamp,
            members=members, reasons=reasons,
        )

    def _rollback(self, reasons):
        with self._lock:
            export = self._canary_export
            stamp = self._canary_stamp
            incumbent = self._incumbent_export
            members = self._members
            self._rejected.add(stamp)
            self._state = "idle"
            self._members = []
            self._canary_export = ""
            self._canary_stamp = ""
        self._registry.set_target(members, incumbent, canary=False)
        self._m_cycles.labels(outcome="rolled_back").inc()
        logger.warning("canary rolled back: %r — %s", export,
                       "; ".join(reasons))
        events.emit(
            "canary_rolled_back", export=export, stamp=stamp,
            members=members, reasons=reasons,
        )
