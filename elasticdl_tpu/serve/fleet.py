"""Serving-fleet control plane: replica registry, autoscaler, scaler.

ISSUE 17 turns "one serve pod" (ISSUE 8) into a replicated tier behind
a router. This module is the router's control-plane half — the data
plane (consistent-hash routing, failover, canary slicing) lives in
``serve/router.py``:

- ``ReplicaRegistry`` — the authoritative replica table, fed by the
  Router gRPC surface (register/heartbeat/deregister). A replica joins
  with its addr + capacity, heartbeats its TelemetryBlob and loaded /
  available export versions every ``EDL_ROUTER_HEARTBEAT_SECS``, and
  leaves either gracefully (``deregister_replica`` — the exactly-once
  drain ack reused from the ISSUE 7/8 scale-down path) or by silence
  (``EDL_ROUTER_REPLICA_TIMEOUT_SECS`` without a heartbeat journals
  ``replica_lost`` and pulls it from the ring). Heartbeats also carry
  directives DOWN to the replica: ``drain`` (shrink victim / shutdown)
  and ``target_export`` (canary / promote version steering).
- ``ReplicaAutoscaler`` — generalizes the training fleet's
  ``ElasticController`` (ISSUE 7) to the serving tier: replica-reported
  QPS / queue-depth / shed-rate drive grow/shrink through the same
  ``DecisionGate`` hold+cooldown hysteresis, every decision journaled
  as a ``scale_decision`` event (``tag="serve"``) with the signals
  that fired. Shrink victims drain through the registry: the router
  stops routing to them at ``begin_drain`` and the replica exits after
  its ``deregister_replica`` ack.
- ``SubprocessReplicaScaler`` — the CPU-CI/bench scaler: replicas are
  local ``serve.main`` subprocesses (in production the k8s pod manager
  plays this role via the serving manifest).
- ``scan_export_versions`` — versioned-export discovery: the fleet
  export root holds one subdirectory per export bundle; replicas
  report the newest complete bundle in heartbeats and the router's
  canary controller (``serve/canary.py``) decides who loads it.
"""

import os
import signal
import subprocess
import sys
import threading
import time

from elasticdl_tpu.common.env_utils import env_float, env_int
from elasticdl_tpu.common.grpc_utils import build_channel, find_free_port
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.master.autoscaler import DecisionGate
from elasticdl_tpu.observability import events
from elasticdl_tpu.observability import metrics as obs_metrics
from elasticdl_tpu.proto import services
from elasticdl_tpu.serve.model import export_signature

logger = _logger_factory("elasticdl_tpu.serve.fleet")

HEARTBEAT_ENV = "EDL_ROUTER_HEARTBEAT_SECS"
REPLICA_TIMEOUT_ENV = "EDL_ROUTER_REPLICA_TIMEOUT_SECS"
MIN_REPLICAS_ENV = "EDL_SERVE_MIN_REPLICAS"
MAX_REPLICAS_ENV = "EDL_SERVE_MAX_REPLICAS"
SCALE_STEP_ENV = "EDL_SERVE_SCALE_STEP"
SCALE_HOLD_ENV = "EDL_SERVE_SCALE_HOLD_SECS"
SCALE_COOLDOWN_ENV = "EDL_SERVE_SCALE_COOLDOWN_SECS"
QUEUE_PER_REPLICA_ENV = "EDL_SERVE_QUEUE_PER_REPLICA"
QPS_PER_REPLICA_ENV = "EDL_SERVE_QPS_PER_REPLICA"


def scan_export_versions(root):
    """Complete export bundles under ``root``, oldest first.

    Returns ``[(rel_name, step, stamp), ...]`` for every subdirectory
    holding a complete bundle (``export_signature`` answers None for
    half-written ones, so a publisher racing this scan is invisible
    until its manifest lands — the same torn-read guard the single-pod
    engine's watcher relies on). The root itself as a flat bundle is
    the single-pod layout and is NOT a fleet version.
    """
    out = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for name in names:
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        sig = export_signature(path)
        if sig is None:
            continue
        out.append((name, int(sig.split(":", 1)[0]), sig))
    out.sort(key=lambda t: (t[1], t[0]))
    return out


class _Replica:
    """One registered serve replica, as the router sees it."""

    __slots__ = (
        "replica_id", "addr", "channel", "stub", "max_batch",
        "registered_at", "last_heartbeat", "loaded_export",
        "loaded_stamp", "available_export", "available_stamp",
        "draining", "drain_reason", "target_export", "qps",
        "queue_depth", "shed_total", "served", "canary",
    )

    def __init__(self, replica_id, addr, channel, stub, max_batch, now):
        self.replica_id = replica_id
        self.addr = addr
        self.channel = channel
        self.stub = stub
        self.max_batch = max_batch
        self.registered_at = now
        self.last_heartbeat = now
        self.loaded_export = ""
        self.loaded_stamp = ""
        self.available_export = ""
        self.available_stamp = ""
        self.draining = False
        self.drain_reason = ""
        self.target_export = ""
        self.qps = 0.0
        self.queue_depth = 0
        self.shed_total = 0
        self.served = 0
        self.canary = False


class ReplicaRegistry:
    """Authoritative replica table behind the Router control surface.

    ``on_join(replica_id)`` / ``on_leave(replica_id)`` callbacks keep
    the data plane's hash ring in sync; draining replicas STAY on the
    ring (their keys only move when they actually leave) but stop
    being routable, so affinity is preserved for everyone else while
    the victim finishes its in-flight work.
    """

    def __init__(self, on_join=None, on_leave=None, heartbeat_secs=None,
                 timeout_secs=None):
        self._on_join = on_join or (lambda rid: None)
        self._on_leave = on_leave or (lambda rid: None)
        self._heartbeat = (
            heartbeat_secs
            if heartbeat_secs is not None
            else env_float(HEARTBEAT_ENV, 2.0)
        )
        self._timeout = (
            timeout_secs
            if timeout_secs is not None
            else env_float(REPLICA_TIMEOUT_ENV, 10.0)
        )
        self._lock = threading.Lock()
        self._replicas = {}  # replica_id -> _Replica
        self._default_target = ""  # export new joiners should load
        self._m_replicas = obs_metrics.gauge(
            "edl_router_replicas",
            "Registered serve replicas by state", ("state",),
        )
        for state in ("routable", "draining"):
            self._m_replicas.labels(state=state)  # stable series set

    @property
    def heartbeat_secs(self):
        return self._heartbeat

    # -- control surface (Router RPCs call these) ----------------------
    def register(self, request, now=None):
        """A replica announced itself; returns its register response
        fields. Re-registration under a live id replaces the old entry
        (a relaunched pod that kept its id — the stale channel is
        closed, the ring position is unchanged)."""
        now = time.time() if now is None else now
        rid = request.replica_id
        channel = build_channel(request.addr)
        stub = services.ServeStub(channel)
        entry = _Replica(
            rid, request.addr, channel, stub, int(request.max_batch), now
        )
        entry.loaded_stamp = request.model_stamp
        self._fold_telemetry(entry, request.telemetry)
        with self._lock:
            old = self._replicas.pop(rid, None)
            entry.target_export = self._default_target
            self._replicas[rid] = entry
            rejoin = old is not None
        if old is not None:
            _close_quietly(old.channel)
        if not rejoin:
            self._on_join(rid)
        self._publish_gauges()
        logger.info(
            "replica %s registered at %s (max_batch=%d%s)",
            rid, request.addr, entry.max_batch,
            ", rejoin" if rejoin else "",
        )
        events.emit(
            "replica_registered", replica=rid, addr=request.addr,
            stamp=request.model_stamp, rejoin=rejoin,
        )
        return entry.target_export

    def heartbeat(self, request, now=None):
        """Fold a heartbeat in; returns ``(known, drain, target)``.
        Unknown ids get ``known=False`` and re-register (the router
        restarted, or the replica was expired while partitioned)."""
        now = time.time() if now is None else now
        rid = request.replica_id
        with self._lock:
            entry = self._replicas.get(rid)
            if entry is None:
                return False, False, ""
            entry.last_heartbeat = now
            entry.loaded_export = request.loaded_export
            entry.loaded_stamp = request.loaded_stamp
            entry.available_export = request.available_export
            entry.available_stamp = request.available_stamp
            self._fold_telemetry(entry, request.telemetry)
            return True, entry.draining, entry.target_export

    def deregister(self, request):
        """The exactly-once drain ack (same contract as the training
        master's ``deregister_worker``): remove the replica everywhere
        with no ``replica_lost`` alert. Idempotent — a second ack (or
        an ack after heartbeat expiry) is a no-op."""
        rid = request.replica_id
        with self._lock:
            entry = self._replicas.pop(rid, None)
        if entry is None:
            return False
        _close_quietly(entry.channel)
        self._on_leave(rid)
        self._publish_gauges()
        initiator = "router" if entry.draining else "replica"
        logger.info(
            "replica %s drained cleanly (%s; served=%d shed=%d)",
            rid, request.reason or "unspecified",
            request.served, request.shed,
        )
        events.emit(
            "drain_ack", replica=rid, reason=request.reason,
            initiator=initiator, served=request.served,
            shed=request.shed,
        )
        return True

    # -- lifecycle ------------------------------------------------------
    def begin_drain(self, replica_id, reason="scale_down"):
        """Stop routing to ``replica_id``; the next heartbeat carries
        the drain directive down. Idempotent."""
        with self._lock:
            entry = self._replicas.get(replica_id)
            if entry is None or entry.draining:
                return False
            entry.draining = True
            entry.drain_reason = reason
        self._publish_gauges()
        logger.info("draining replica %s (%s)", replica_id, reason)
        events.emit("replica_draining", replica=replica_id, reason=reason)
        return True

    def expire(self, now=None):
        """Drop replicas silent past the heartbeat timeout; returns the
        expired ids. The ring loses them (their keys fail over to ring
        successors) and a relaunch re-registers from scratch."""
        now = time.time() if now is None else now
        with self._lock:
            dead = [
                rid for rid, e in self._replicas.items()
                if now - e.last_heartbeat > self._timeout
            ]
            entries = {rid: self._replicas.pop(rid) for rid in dead}
        for rid, entry in entries.items():
            _close_quietly(entry.channel)
            self._on_leave(rid)
            silent = round(now - entry.last_heartbeat, 2)
            logger.warning(
                "replica %s lost: no heartbeat for %.1fs", rid, silent
            )
            events.emit("replica_lost", replica=rid, silent_secs=silent)
        if dead:
            self._publish_gauges()
        return dead

    def forget_replica(self, replica_id):
        """Administrative removal (tests / operator): close and drop
        without journaling a loss."""
        with self._lock:
            entry = self._replicas.pop(replica_id, None)
        if entry is None:
            return False
        _close_quietly(entry.channel)
        self._on_leave(replica_id)
        self._publish_gauges()
        return True

    # -- canary / version steering -------------------------------------
    def set_target(self, replica_ids, export, canary=None):
        """Direct ``replica_ids`` to load ``export`` (delivered on
        their next heartbeat). ``canary`` marks/unmarks membership in
        the canary subset for the data plane's traffic slicing."""
        with self._lock:
            for rid in replica_ids:
                entry = self._replicas.get(rid)
                if entry is None:
                    continue
                entry.target_export = export
                if canary is not None:
                    entry.canary = canary

    def set_default_target(self, export):
        """Export new joiners are told to load at register time."""
        with self._lock:
            self._default_target = export

    # -- views ----------------------------------------------------------
    def get(self, replica_id):
        with self._lock:
            return self._replicas.get(replica_id)

    def stub(self, replica_id):
        with self._lock:
            entry = self._replicas.get(replica_id)
            return entry.stub if entry is not None else None

    def is_routable(self, replica_id):
        with self._lock:
            entry = self._replicas.get(replica_id)
            return entry is not None and not entry.draining

    def live_ids(self):
        with self._lock:
            return list(self._replicas)

    def routable_ids(self):
        with self._lock:
            return [
                rid for rid, e in self._replicas.items() if not e.draining
            ]

    def canary_ids(self):
        with self._lock:
            return [rid for rid, e in self._replicas.items() if e.canary]

    def telemetry_totals(self):
        """Fleet-wide signals for the autoscaler, routable only (a
        draining victim's backlog must not buy capacity twice — its
        replacement already did)."""
        with self._lock:
            routable = [
                e for e in self._replicas.values() if not e.draining
            ]
            return {
                "replicas": len(routable),
                "qps": sum(e.qps for e in routable),
                "queue_depth": sum(e.queue_depth for e in routable),
                "shed_total": sum(e.shed_total for e in routable),
            }

    def min_max_batch(self):
        """The fleet's answer to model_info.max_batch: the TIGHTEST
        replica cap, so a client sizing batches against the router
        never overruns any replica."""
        with self._lock:
            caps = [
                e.max_batch for e in self._replicas.values()
                if e.max_batch > 0 and not e.draining
            ]
        return min(caps) if caps else 0

    def state(self):
        """JSON-ready /statusz section."""
        now = time.time()
        with self._lock:
            return {
                rid: {
                    "addr": e.addr,
                    "heartbeat_age": round(now - e.last_heartbeat, 2),
                    "loaded_export": e.loaded_export,
                    "loaded_stamp": e.loaded_stamp,
                    "available_export": e.available_export,
                    "target_export": e.target_export,
                    "draining": e.draining,
                    "canary": e.canary,
                    "qps": round(e.qps, 2),
                    "queue_depth": e.queue_depth,
                    "shed_total": e.shed_total,
                }
                for rid, e in self._replicas.items()
            }

    # -- internals ------------------------------------------------------
    @staticmethod
    def _fold_telemetry(entry, blob):
        entry.qps = float(blob.serve_qps)
        entry.queue_depth = int(blob.serve_queue_depth)
        entry.shed_total = int(blob.serve_shed_total)

    def _publish_gauges(self):
        with self._lock:
            routable = sum(
                1 for e in self._replicas.values() if not e.draining
            )
            draining = len(self._replicas) - routable
        self._m_replicas.labels(state="routable").set(routable)
        self._m_replicas.labels(state="draining").set(draining)


def _close_quietly(channel):
    try:
        channel.close()
    except Exception:
        # a torn channel to a dead replica: the close is best-effort
        logger.debug("replica channel close failed", exc_info=True)


class ReplicaAutoscaler:
    """Telemetry-driven replica count, ``ElasticController`` discipline.

    Grow when the routable tier is saturated — queue depth per replica
    over ``EDL_SERVE_QUEUE_PER_REPLICA``, shed rate above zero, or QPS
    per replica over the ``EDL_SERVE_QPS_PER_REPLICA`` nominal capacity
    — sustained through the ``DecisionGate`` hold and cooldown. Shrink
    when the fleet would still run under half capacity with one fewer
    replica and nothing queued/shedding; victims are the coldest
    (lowest-QPS) replicas, drained through the registry so the router
    stops routing before the pod dies. A tier below
    ``EDL_SERVE_MIN_REPLICAS`` (a SIGKILLed replica) is replaced
    immediately — no hold, the floor is a contract — subject only to
    the cooldown so a flapping scaler can't spawn-storm.
    """

    def __init__(self, registry, scaler, min_replicas=None,
                 max_replicas=None, step=None, hold_secs=None,
                 cooldown_secs=None, queue_per_replica=None,
                 qps_per_replica=None):
        self._registry = registry
        self._scaler = scaler
        self._min = int(
            min_replicas
            if min_replicas is not None
            else env_int(MIN_REPLICAS_ENV, 1)
        )
        self._max = int(
            max_replicas
            if max_replicas is not None
            else env_int(MAX_REPLICAS_ENV, 8)
        )
        self._step = max(1, int(
            step if step is not None else env_int(SCALE_STEP_ENV, 1)
        ))
        hold = (
            hold_secs
            if hold_secs is not None
            else env_float(SCALE_HOLD_ENV, 3.0)
        )
        cooldown = (
            cooldown_secs
            if cooldown_secs is not None
            else env_float(SCALE_COOLDOWN_ENV, 10.0)
        )
        self._queue_mark = max(0.1, (
            queue_per_replica
            if queue_per_replica is not None
            else env_float(QUEUE_PER_REPLICA_ENV, 16.0)
        ))
        self._qps_mark = max(0.1, (
            qps_per_replica
            if qps_per_replica is not None
            else env_float(QPS_PER_REPLICA_ENV, 100.0)
        ))
        self._gate = DecisionGate(hold, cooldown)
        self._last_shed = None  # (ts, shed_total) for the rate
        self._last_decision = {}
        self._m_decisions = obs_metrics.counter(
            "edl_serve_scale_decisions_total",
            "Serving-fleet resize decisions", ("direction",),
        )
        for direction in ("grow", "shrink"):
            self._m_decisions.labels(direction=direction)

    def state(self):
        return {
            "min_replicas": self._min,
            "max_replicas": self._max,
            "step": self._step,
            "last_decision": dict(self._last_decision),
        }

    def tick(self, now=None):
        """One decision pass on the router's 1 Hz tick. Never raises."""
        try:
            self._tick(time.time() if now is None else now)
        except Exception:
            logger.exception("replica autoscaler tick failed")

    def _tick(self, now):
        tel = self._registry.telemetry_totals()
        routable = tel["replicas"]
        total = len(self._registry.live_ids())  # incl. draining victims
        queue = tel["queue_depth"]
        qps = tel["qps"]
        shed_rate = self._shed_rate(now, tel["shed_total"])

        # -- floor enforcement: a lost replica is replaced NOW (modulo
        # cooldown); the hold exists to damp signals, and "the tier is
        # under its floor" is a fact, not a signal
        if routable < self._min and total < self._max:
            if not self._gate.in_cooldown(now):
                self._grow(
                    now, min(self._min - routable, self._max - total),
                    routable, queue, qps,
                    reasons=["below_floor: %d routable < min_replicas %d"
                             % (routable, self._min)],
                )
            return

        # -- grow: sustained saturation. The ceiling binds on TOTAL
        # replicas (draining victims still hold pods/ports)
        per = max(1, routable)
        reasons = []
        if queue / per > self._queue_mark:
            reasons.append(
                "queue: %d queued / %d replicas > %.1f watermark"
                % (queue, routable, self._queue_mark)
            )
        if shed_rate > 0.5:
            reasons.append("shedding: %.1f req/s shed" % shed_rate)
        if qps / per > self._qps_mark:
            reasons.append(
                "qps: %.1f/replica > %.1f nominal capacity"
                % (qps / per, self._qps_mark)
            )
        want_grow = bool(reasons) and total < self._max
        if self._gate.observe("grow", want_grow, now):
            self._grow(
                now, min(self._step, self._max - total),
                routable, queue, qps, reasons=reasons,
            )
            return

        # -- shrink: the remaining tier would still run under half its
        # nominal capacity, nothing queued, nothing shedding
        want_shrink = (
            routable > self._min
            and queue == 0
            and shed_rate <= 0.0
            and qps / max(1, routable - self._step) < 0.5 * self._qps_mark
        )
        if self._gate.observe("shrink", want_shrink, now):
            self._shrink(now, routable, queue, qps)

    # ------------------------------------------------------------------
    def _shed_rate(self, now, shed_total):
        last = self._last_shed
        self._last_shed = (now, shed_total)
        if last is None or now <= last[0]:
            return 0.0
        return max(0.0, shed_total - last[1]) / (now - last[0])

    def _grow(self, now, delta, replicas, queue, qps, reasons):
        if delta <= 0:
            return
        started = self._scaler.scale_up(delta)
        added = len(started) if started is not None else delta
        if added <= 0:
            return  # scaler couldn't place any
        self._gate.fired("grow", now)
        self._last_decision = {
            "direction": "grow", "delta": added, "replicas": replicas,
            "queue_depth": queue, "at": now, "reasons": reasons,
        }
        self._m_decisions.labels(direction="grow").inc()
        logger.info(
            "serve autoscaler grow +%d (replicas %d, queue %d): %s",
            added, replicas, queue, "; ".join(reasons),
        )
        events.emit(
            "scale_decision", direction="grow", delta=added,
            workers=replicas, queue_depth=queue, qps=round(qps, 1),
            reasons=reasons, tag="serve",
        )

    def _shrink(self, now, replicas, queue, qps):
        victims = self._pick_victims(min(self._step, replicas - self._min))
        if not victims:
            return
        self._gate.fired("shrink", now)
        reasons = [
            "idle: %.1f qps over %d replicas fits %.0f%% of %d"
            % (qps, replicas, 50, replicas - len(victims)),
        ]
        self._last_decision = {
            "direction": "shrink", "delta": len(victims),
            "replicas": replicas, "victims": victims, "at": now,
            "reasons": reasons,
        }
        self._m_decisions.labels(direction="shrink").inc()
        logger.info(
            "serve autoscaler shrink -%d (victims %s): %s",
            len(victims), victims, "; ".join(reasons),
        )
        events.emit(
            "scale_decision", direction="shrink", delta=len(victims),
            workers=replicas, queue_depth=queue, qps=round(qps, 1),
            victims=victims, reasons=reasons, tag="serve",
        )
        for rid in victims:
            self._registry.begin_drain(rid, reason="scale_down")

    def _pick_victims(self, count):
        """Coldest first: the replica whose loss moves the fewest warm
        affinity keys is the one serving the least traffic. Canary
        members are spared — shrinking the canary mid-judgment would
        starve the verdict."""
        if count <= 0:
            return []
        candidates = []
        for rid in self._registry.routable_ids():
            entry = self._registry.get(rid)
            if entry is None or entry.canary:
                continue
            candidates.append((entry.qps, rid))
        candidates.sort()
        return [rid for _, rid in candidates[:count]]


class SubprocessReplicaScaler:
    """Replicas as local ``serve.main`` subprocesses (bench / CPU CI).

    Production uses the k8s serving manifest + pod manager; this scaler
    gives the bench and the tier-1e+ smoke the same grow surface with
    nothing but fork/exec. Each replica gets a free port and registers
    itself with the router; ``reap()`` forgets exited pids so the
    autoscaler's floor check sees real capacity.
    """

    def __init__(self, router_addr, export_root, extra_args=(), env=None,
                 log_dir=None):
        self._router_addr = router_addr
        self._export_root = export_root
        self._extra_args = list(extra_args)
        self._env = dict(env) if env is not None else dict(os.environ)
        self._log_dir = log_dir
        self._lock = threading.Lock()
        self._procs = {}  # pid -> (Popen, log file or None)
        self._seq = 0

    def scale_up(self, n):
        started = []
        for _ in range(max(0, int(n))):
            with self._lock:
                self._seq += 1
                seq = self._seq
            port = find_free_port()
            cmd = [
                sys.executable, "-m", "elasticdl_tpu.serve.main",
                "--export_dir", self._export_root,
                "--port", str(port),
                "--router_addr", self._router_addr,
            ] + self._extra_args
            logf = None
            if self._log_dir is not None:
                logf = open(
                    os.path.join(self._log_dir, "replica-%d.log" % seq),
                    "ab",
                )
            proc = subprocess.Popen(
                cmd, env=self._env,
                stdout=logf if logf is not None else None,
                stderr=subprocess.STDOUT if logf is not None else None,
            )
            with self._lock:
                self._procs[proc.pid] = (proc, logf)
            started.append(proc.pid)
            logger.info(
                "spawned serve replica pid=%d port=%d", proc.pid, port
            )
        return started

    def reap(self):
        """Forget exited replicas; returns their pids."""
        gone = []
        with self._lock:
            for pid in list(self._procs):
                proc, logf = self._procs[pid]
                if proc.poll() is not None:
                    gone.append(pid)
                    del self._procs[pid]
                    if logf is not None:
                        logf.close()
        return gone

    def replica_pids(self):
        self.reap()
        with self._lock:
            return list(self._procs)

    def kill(self, pid, sig=signal.SIGKILL):
        """Fault injection for the bench: hard-kill one replica."""
        with self._lock:
            proc, _ = self._procs.get(pid, (None, None))
        if proc is not None:
            proc.send_signal(sig)

    def stop_all(self, grace_secs=10.0):
        with self._lock:
            items = list(self._procs.items())
        for _, (proc, _) in items:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + grace_secs
        for _, (proc, _) in items:
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
        self.reap()
