"""Serving-router role entry point: the fleet's fifth role (ISSUE 17).

Usage: python -m elasticdl_tpu.serve.router_main --port=50060 \
    [--min_replicas=2 --max_replicas=8 \
     --export_root=/artifacts/exports --replica_args="--model_zoo=..."]

One process, two gRPC surfaces (``serve/router.py``): clients point
``--serving_addr`` here exactly as they would at a single serve pod;
replicas register/heartbeat/deregister on the Router surface. The 1 Hz
control loop expires silent replicas, advances the canary state
machine, and — when a scaler is available — runs the
``ReplicaAutoscaler``. With ``--replica_args`` the router manages its
own local replica subprocesses (bench / CPU CI topology); without it
the replica set is whatever registers (k8s pods from the serving
manifest).

Full platform treatment like every other role: /metrics /healthz
/readyz (ready = at least one routable replica), /routerz (registry +
ring + canary view), flight-recorder journal, SIGTERM flag-only drain.
"""

import argparse
import os
import shlex
import signal
import sys
import threading
import time

from elasticdl_tpu.common.env_utils import env_int
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.serve.router_main")

ROUTER_PORT_ENV = "EDL_ROUTER_PORT"


def parse_router_args(argv=None):
    parser = argparse.ArgumentParser("elasticdl_tpu serve router")
    parser.add_argument("--router_id", type=int, default=0)
    parser.add_argument(
        "--port", type=int, default=0,
        help="client+replica gRPC port (0 = EDL_ROUTER_PORT or 50060)",
    )
    parser.add_argument(
        "--min_replicas", type=int, default=-1,
        help="autoscaler floor (<0 = EDL_SERVE_MIN_REPLICAS or 1)",
    )
    parser.add_argument(
        "--max_replicas", type=int, default=-1,
        help="autoscaler ceiling (<0 = EDL_SERVE_MAX_REPLICAS or 8)",
    )
    parser.add_argument(
        "--export_root", default="",
        help="versioned export root replicas load from; required for "
        "--replica_args self-managed replicas",
    )
    parser.add_argument(
        "--replica_args", default="",
        help="extra serve.main args for self-managed replica "
        "subprocesses (e.g. \"--model_zoo=... --ps_addrs=...\"); "
        "empty = replicas are managed externally and only register",
    )
    parser.add_argument(
        "--replica_log_dir", default="",
        help="per-replica log files for self-managed replicas "
        "(default: inherit this process's stdio)",
    )
    parser.add_argument("--metrics_port", type=int, default=0)
    return parser.parse_args(argv)


class RouterRole:
    def __init__(self, args):
        self.args = args
        self.port = args.port or env_int(ROUTER_PORT_ENV, 50060)
        self.servicer = None
        self.autoscaler = None
        self.scaler = None
        self.server = None
        self.observability = None
        self._drained = threading.Event()
        # SIGTERM arrival marker — flag-only, like every role: the
        # handler must not drain while the interrupted thread may hold
        # registry/journal locks; run() polls and drains off-signal
        self._term_flag = False
        self._term_previous = None

    # ------------------------------------------------------------------
    def prepare(self):
        from elasticdl_tpu.common.grpc_utils import build_server
        from elasticdl_tpu.observability import (
            events,
            http_server,
            profiler,
            trace,
        )
        from elasticdl_tpu.proto.services import (
            add_router_servicer_to_server,
            add_serve_servicer_to_server,
        )
        from elasticdl_tpu.serve.fleet import (
            ReplicaAutoscaler,
            SubprocessReplicaScaler,
        )
        from elasticdl_tpu.serve.router import RouterServicer

        role = "router-%d" % self.args.router_id
        trace.configure(role)
        events.configure(role)
        events.emit("role_start", port=self.port)
        profiler.maybe_start(role)
        self.servicer = RouterServicer()
        if self.args.replica_args:
            if not self.args.export_root:
                raise SystemExit(
                    "--replica_args needs --export_root (the versioned "
                    "export directory replicas load from)"
                )
            self.scaler = SubprocessReplicaScaler(
                "127.0.0.1:%d" % self.port,
                self.args.export_root,
                extra_args=shlex.split(self.args.replica_args),
                log_dir=self.args.replica_log_dir or None,
            )
        if self.scaler is not None:
            self.autoscaler = ReplicaAutoscaler(
                self.servicer.registry,
                self.scaler,
                min_replicas=(
                    self.args.min_replicas
                    if self.args.min_replicas >= 0 else None
                ),
                max_replicas=(
                    self.args.max_replicas
                    if self.args.max_replicas >= 0 else None
                ),
            )
        self.server = build_server()
        add_serve_servicer_to_server(self.servicer, self.server)
        add_router_servicer_to_server(self.servicer, self.server)
        self.server.add_insecure_port("[::]:%d" % self.port)
        self.server.start()
        self.observability = http_server.maybe_start(
            role, cli_port=self.args.metrics_port
        )
        if self.observability is not None:
            # ready = the tier can answer a predict at all
            self.observability.add_readiness_check(
                "routable_replica",
                lambda: bool(self.servicer.registry.routable_ids()),
            )
            self.observability.add_json_handler(
                "/routerz", self._routerz
            )
        self._install_sigterm_drain()
        logger.info("router %d on :%d", self.args.router_id, self.port)
        return self

    def _routerz(self):
        state = self.servicer.state()
        if self.autoscaler is not None:
            state["autoscaler"] = self.autoscaler.state()
        return state

    def _install_sigterm_drain(self):
        self._term_previous = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            self._term_flag = True  # flag-only; run() drains

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            logger.warning(
                "not on main thread; router SIGTERM drain not installed"
            )

    def _finish_term(self):
        self.drain(reason="sigterm")
        previous = self._term_previous
        if callable(previous):
            previous(signal.SIGTERM, None)
        return 0

    def drain(self, reason="shutdown"):
        """Stop the server; self-managed replicas are SIGTERMed too
        (they drain through their own path and ack). Externally
        managed replicas are left running — a router restart must not
        take the tier down with it."""
        from elasticdl_tpu.observability import events, trace

        if self._drained.is_set():
            return
        self._drained.set()
        try:
            if self.server is not None:
                self.server.stop(grace=2.0)
        except Exception:
            logger.exception("server stop at drain failed")
        if self.scaler is not None:
            try:
                self.scaler.stop_all()
            except Exception:
                logger.exception("replica stop at drain failed")
        trace.flush()
        if trace.enabled():
            events.emit("trace_flushed", reason=reason)
        events.emit("role_stop", reason=reason)
        events.flush()

    def run(self, tick_secs=1.0):
        """The control loop: replica expiry, canary state machine,
        autoscaler — one pass a second until stopped."""
        while not self._drained.is_set():
            time.sleep(tick_secs)
            if self._term_flag:
                return self._finish_term()
            try:
                self.servicer.tick()
                if self.scaler is not None:
                    self.scaler.reap()
                if self.autoscaler is not None:
                    self.autoscaler.tick()
            except Exception:
                logger.exception("router tick failed")
        return 0


def main(argv=None):
    from elasticdl_tpu.common.platform import apply_platform_overrides

    apply_platform_overrides()
    args = parse_router_args(argv)
    from elasticdl_tpu.testing import faults

    faults.set_role("router-%d" % args.router_id)
    if args.metrics_port:
        from elasticdl_tpu.observability import http_server

        os.environ[http_server.PORT_ENV] = str(args.metrics_port)
    from elasticdl_tpu.observability import events

    events.install_crash_hooks()
    return RouterRole(args).prepare().run()


if __name__ == "__main__":
    sys.exit(main())
