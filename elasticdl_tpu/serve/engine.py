"""Model-version lifecycle: load, watch, warm, atomically swap.

The engine owns the active ``ServingModel`` reference and the shared
embedding cache. A watcher thread polls the export artifact's signature
(``EDL_SERVE_WATCH_SECS``); when it changes, the replacement version is
built and WARMED in the background — export load, jit compile, one
template predict — while the active version keeps serving, then swapped
in with one reference assignment. A batch that already entered
``_run_batch`` holds its own model reference, so in-flight requests
finish on the version that admitted them and none fail across a swap
(the bench hard-gates this).

A PS relaunch (restored-stamp change on the pull path, the PR 4/6
identity machinery) invalidates the shared cache from whatever thread
detected it — the cache is built ``thread_safe=True`` for exactly this.
"""

import os
import threading
import time

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.embedding import HotRowCache
from elasticdl_tpu.models.registry import get_model_spec
from elasticdl_tpu.observability import events, metrics
from elasticdl_tpu.serve.batcher import MicroBatcher, _env_num
from elasticdl_tpu.serve.model import ServingModel, export_signature

logger = _logger_factory("elasticdl_tpu.serve.engine")

WATCH_SECS_ENV = "EDL_SERVE_WATCH_SECS"
CACHE_TTL_ENV = "EDL_SERVE_CACHE_TTL_SECS"

# requests_shed journal lines are rate-limited to one per window: under
# real overload sheds arrive at request rate, and a write-through
# journal line per shed would amplify exactly the pressure shedding
# exists to relieve
_SHED_EVENT_WINDOW_SECS = 1.0


class ServingEngine:
    def __init__(self, model_zoo, export_dir, ps_client=None,
                 model_def="", model_params="", symbol_overrides=None,
                 compute_dtype=None, max_batch=None, max_delay_ms=None,
                 queue_depth=None, deadline_ms=None, cache_ttl_secs=None,
                 cache_capacity=1_000_000, watch_secs=None,
                 registry=None, directed=False):
        self.model_zoo = model_zoo
        self.export_dir = export_dir
        # directed mode (ISSUE 17 fleet replicas): export_dir is a
        # VERSIONED ROOT (one subdirectory per export bundle) and the
        # router steers which version this replica loads via
        # set_target(); undirected (single-pod) keeps the flat layout
        # and autonomously follows whatever lands in export_dir
        self._directed = bool(directed)
        self._target_rel = None  # None = no directive yet: newest wins
        self._loaded_rel = ""
        self._ps = ps_client
        self._compute_dtype = compute_dtype
        self.spec = get_model_spec(
            model_zoo, model_def=model_def, model_params=model_params,
            symbol_overrides=symbol_overrides,
        )
        if cache_ttl_secs is None:
            cache_ttl_secs = _env_num(CACHE_TTL_ENV, 2.0, float)
        self.cache = None
        if (
            self.spec.sparse_embedding_specs
            and ps_client is not None
            and cache_ttl_secs > 0
        ):
            # serving has no push thread bounding row age, so freshness
            # is wall-clock TTL; thread_safe because batcher, warmer
            # and the PS-restart hook all touch it
            self.cache = HotRowCache(
                capacity=cache_capacity,
                ttl_secs=cache_ttl_secs,
                thread_safe=True,
            )
        if watch_secs is None:
            watch_secs = _env_num(WATCH_SECS_ENV, 2.0, float)
        self._watch_secs = float(watch_secs)
        self._model = None          # the active ServingModel
        self._swap_lock = threading.Lock()  # guards the stamp CAS only
        self._template = None       # (features, rows) of a recent batch
        self._stopped = threading.Event()
        self.swaps = 0
        self._last_shed_event = 0.0
        self._shed_at_last_event = 0
        reg = registry or metrics.default_registry()
        self._m_model_info = reg.gauge(
            "edl_serve_model_info",
            "1 for the loaded model version (export step), 0 for "
            "versions served earlier in this process's life",
            ("version",),
        )
        self._m_swaps = reg.counter(
            "edl_serve_version_swaps_total",
            "Completed model-version hot swaps",
        )
        self._m_cache_hit_rate = reg.gauge(
            "edl_serve_cache_hit_rate",
            "Lifetime hit fraction of the serving embedding row cache",
        )
        self.batcher = MicroBatcher(
            self._run_batch,
            max_batch=max_batch,
            max_delay_ms=max_delay_ms,
            queue_depth=queue_depth,
            default_deadline_ms=deadline_ms,
            on_shed=self._on_shed,
            registry=reg,
        )
        # PS-restart identity hook (PR 4/6): chain the engine's shared-
        # cache invalidation onto whatever hook the client already
        # carries (a co-resident trainer's, or None). Read-only
        # preparers never take the hook slot (train/sparse), so one
        # chain here covers every ServingModel build.
        self._chain_resync_hook()
        self._watcher = threading.Thread(
            target=self._watch_loop, name="edl-serve-watcher", daemon=True
        )

    # ------------------------------------------------------------------
    def start(self, block=False):
        """Try the initial load, then start the export watcher. With
        ``block`` the call waits for a loadable artifact (tests);
        otherwise readiness (/readyz) simply stays false until the
        watcher sees one."""
        while True:
            try:
                self._load_and_swap()
            except FileNotFoundError:
                if not block:
                    logger.info(
                        "no export at %s yet; serving unready until one "
                        "appears", self.export_dir,
                    )
                    break
                time.sleep(0.2)
                continue
            break
        self._watcher.start()
        return self

    @property
    def loaded(self):
        return self._model is not None

    @property
    def loaded_export(self):
        """Rel name of the loaded version under the export root
        (directed mode); "" for the flat single-pod layout."""
        return self._loaded_rel

    def set_target(self, rel):
        """Directed mode: the router told this replica which version to
        run (canary membership, promote, or rollback). The watcher
        picks the change up on its next tick — the swap machinery is
        exactly the single-pod hot swap, including the in-flight
        requests finishing on the version that admitted them."""
        if not self._directed or not rel or rel == self._target_rel:
            return
        self._target_rel = rel
        logger.info("export target directed to %r", rel)

    def _resolve_export(self):
        """(directory, rel) the engine should be serving right now."""
        if not self._directed:
            return self.export_dir, ""
        rel = self._target_rel
        if not rel:
            # no directive yet (bootstrap): newest complete bundle —
            # the router adopts whatever the fleet converged on as the
            # incumbent and pins everyone from then on
            from elasticdl_tpu.serve.fleet import scan_export_versions

            versions = scan_export_versions(self.export_dir)
            if not versions:
                return self.export_dir, ""
            rel = versions[-1][0]
        return os.path.join(self.export_dir, rel), rel

    @property
    def model(self):
        return self._model

    def model_info(self):
        model = self._model
        return {
            "loaded": model is not None,
            "step": model.step if model is not None else -1,
            "stamp": model.stamp if model is not None else "",
            "model_zoo": str(self.model_zoo),
            "max_batch": self.batcher.max_batch,
        }

    # ------------------------------------------------------------------
    def _chain_resync_hook(self):
        """Wrap whatever resync hook the shared PS client currently
        carries so a PS relaunch ALSO clears the shared serving cache
        immediately, from whatever thread detected it. Serving-side
        (read-only) preparers never install their own hook, so this
        chain survives every ServingModel build."""
        if self._ps is None or not hasattr(self._ps, "resync_hook"):
            return
        inner = self._ps.resync_hook

        def _chained(shard, _inner=inner):
            if _inner is not None:
                _inner(shard)
            self._on_ps_restart(shard)

        self._ps.resync_hook = _chained

    def _build(self, export_dir):
        return ServingModel(
            self.spec,
            export_dir,
            max_batch=self.batcher.max_batch,
            ps_client=self._ps,
            cache=self.cache,
            compute_dtype=self._compute_dtype,
        )

    def _load_and_swap(self):
        # build + warm OUTSIDE the swap lock: _build reads the export
        # from disk (np.load) and warm compiles — anyone contending on
        # the lock must not stall behind seconds of IO + XLA. The lock
        # guards only the stamp compare-and-swap; a builder that loses
        # the race to the same stamp drops its replacement.
        export_dir, rel = self._resolve_export()
        previous = self._model
        replacement = self._build(export_dir)
        if previous is not None and replacement.stamp == previous.stamp:
            return False
        # warm BEFORE the swap: the compile (and the cache priming
        # pull) happens while the old version still takes traffic,
        # so the swap itself is one reference assignment
        template = self._template
        if template is not None:
            try:
                replacement.warm(template[0], template[1])
            except Exception:
                logger.exception(
                    "warm-up of export %s failed; swapping cold",
                    replacement.stamp,
                )
        with self._swap_lock:
            previous = self._model
            if previous is not None and replacement.stamp == previous.stamp:
                return False
            self._model = replacement
            self._loaded_rel = rel
        self._m_model_info.labels(
            version=str(replacement.step)
        ).set(1)
        if previous is not None:
            self._m_model_info.labels(
                version=str(previous.step)
            ).set(0)
            self.swaps += 1
            self._m_swaps.inc()
            events.emit(
                "version_swapped",
                from_step=previous.step,
                to_step=replacement.step,
                stamp=replacement.stamp,
            )
            logger.info(
                "model version swapped: step %d -> %d (%s)",
                previous.step, replacement.step, replacement.stamp,
            )
        else:
            events.emit(
                "model_loaded",
                step=replacement.step,
                stamp=replacement.stamp,
                path=str(self.export_dir),
            )
            logger.info(
                "model loaded: step %d (%s)",
                replacement.step, replacement.stamp,
            )
        return True

    def _watch_loop(self):
        while not self._stopped.wait(self._watch_secs):
            try:
                signature = export_signature(self._resolve_export()[0])
                model = self._model
                if signature is None:
                    continue
                if model is not None and signature == model.stamp:
                    continue
                self._load_and_swap()
            except Exception:
                # a torn mid-write artifact read fails here and
                # succeeds on a later tick; the active version keeps
                # serving either way
                logger.exception("export watch tick failed")

    # ------------------------------------------------------------------
    def _run_batch(self, features, rows):
        model = self._model  # one read: in-flight batches keep theirs
        if model is None:
            raise RuntimeError("no model loaded")
        # remember a schema template for warming future versions (tiny:
        # one max_batch-row feature set)
        if self._template is None:
            self._template = (features, rows)
        outputs = model.predict(features, rows)
        if self.cache is not None:
            self._m_cache_hit_rate.set(model.embedding_hit_rate)
        return outputs, model.step, model.stamp

    def predict(self, features, rows, deadline_secs=None):
        """The servicer's entry: admission -> batch -> forward."""
        return self.batcher.submit(features, rows, deadline_secs)

    def _on_shed(self, reason, total):
        now = time.monotonic()
        if now - self._last_shed_event < _SHED_EVENT_WINDOW_SECS:
            return
        shed_since = total - self._shed_at_last_event
        self._last_shed_event = now
        self._shed_at_last_event = total
        events.emit(
            "requests_shed", reason=reason, count=shed_since, total=total
        )

    def _on_ps_restart(self, shard):
        if self.cache is not None:
            # safe from any thread (thread_safe cache): rows cached
            # from the dead process must not serve another request
            self.cache.clear()
            logger.warning(
                "PS shard %s relaunched; serving embedding cache dropped",
                shard,
            )

    # ------------------------------------------------------------------
    def drain(self, timeout=30.0):
        """SIGTERM: stop admitting, flush the queue, stop the watcher.
        Returns the flushed-request count."""
        self._stopped.set()
        flushed = self.batcher.drain(timeout=timeout)
        if self._watcher.is_alive():
            self._watcher.join(timeout=self._watch_secs + 1.0)
        return flushed
