"""gRPC surface of the serving role: the ``elasticdl_tpu.Serve``
service (proto/services.py), one thin decode/encode layer over the
engine. Admission outcomes map 1:1 onto status codes:

- bounded queue at depth       -> RESOURCE_EXHAUSTED (shed)
- deadline expired while queued -> DEADLINE_EXCEEDED (never served late)
- SIGTERM drain in progress     -> UNAVAILABLE
- no model loaded yet           -> FAILED_PRECONDITION (mirrors /readyz)
"""

import time

import grpc
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.common.tensor_utils import blob_to_ndarray, ndarray_to_blob
from elasticdl_tpu.observability import metrics, trace
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.serve import batcher as batcher_mod
from elasticdl_tpu.serve.model import SINGLE_INPUT_KEY

logger = _logger_factory("elasticdl_tpu.serve.servicer")


class ServeServicer:
    def __init__(self, engine, registry=None):
        self._engine = engine
        reg = registry or metrics.default_registry()
        self._m_latency = reg.histogram(
            "edl_serve_request_seconds",
            "End-to-end predict latency (admission queue + batch "
            "formation + forward), successful requests",
        )
        self._m_requests = reg.counter(
            "edl_serve_requests_total",
            "Predict RPCs by outcome",
            ("code",),
        )
        for code in ("OK", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED",
                     "UNAVAILABLE", "INVALID_ARGUMENT"):
            self._m_requests.labels(code=code)

    # ------------------------------------------------------------------
    def _abort(self, context, code, detail):
        self._m_requests.labels(code=code.name).inc()
        # grpc's abort raises a bare Exception carrying no status, so
        # stamp the code onto the open serve_predict root span here —
        # critical_path.py classifies sheds by this arg
        trace.annotate(code=code.name)
        context.abort(code, detail)

    def predict(self, request, context):
        # the serve-side trace root, opened at ADMISSION time
        # (ISSUE 9): queue wait, batch formation, forward, and the
        # EmbeddingClient's PS pulls all become children; a shed
        # surfaces as this span failing with the abort's status code.
        # If the CALLER propagated a context, root_span degrades to a
        # child span so the client's trace stays whole.
        with trace.root_span("serve_predict", role="serve"):
            return self._predict(request, context)

    def _predict(self, request, context):
        start = time.perf_counter()
        if not self._engine.loaded:
            self._abort(
                context, grpc.StatusCode.FAILED_PRECONDITION,
                "no model loaded yet (see /readyz)",
            )
        features = {
            name: blob_to_ndarray(blob)
            for name, blob in request.features.items()
        }
        if not features:
            self._abort(
                context, grpc.StatusCode.INVALID_ARGUMENT,
                "request has no features",
            )
        if any(np.asarray(v).ndim == 0 for v in features.values()):
            self._abort(
                context, grpc.StatusCode.INVALID_ARGUMENT,
                "features must have a leading batch dimension "
                "(got a 0-d tensor)",
            )
        if set(features) == {SINGLE_INPUT_KEY}:
            features = features[SINGLE_INPUT_KEY]
            rows_set = {int(np.asarray(features).shape[0])}
        else:
            rows_set = {
                int(np.asarray(v).shape[0]) for v in features.values()
            }
        if len(rows_set) != 1:
            self._abort(
                context, grpc.StatusCode.INVALID_ARGUMENT,
                "features disagree on the batch dimension: %s"
                % sorted(rows_set),
            )
        rows = rows_set.pop()
        if rows < 1 or rows > self._engine.batcher.max_batch:
            self._abort(
                context, grpc.StatusCode.INVALID_ARGUMENT,
                "request rows %d outside [1, max_batch=%d]"
                % (rows, self._engine.batcher.max_batch),
            )
        # latency budget: the TIGHTER of the RPC deadline and the
        # request's in-message budget — or, when no in-message budget
        # was set, the server default (EDL_SERVE_DEADLINE_MS). The
        # server default must still CAP the queueing budget under a
        # client transport's loose default timeout: admission control
        # is the server's protection, and a 60 s transport timeout is
        # not a request to queue for 60 s.
        deadline_secs = context.time_remaining()
        budget = (
            request.deadline_ms / 1e3 if request.deadline_ms > 0
            else self._engine.batcher.default_deadline_secs
        )
        if budget > 0:
            deadline_secs = (
                budget if deadline_secs is None
                else min(deadline_secs, budget)
            )
        try:
            outputs, step, stamp = self._engine.predict(
                features, rows, deadline_secs
            )
        except batcher_mod.QueueFull as e:
            self._abort(context, grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except batcher_mod.DeadlineExpired as e:
            self._abort(context, grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        except batcher_mod.Draining as e:
            self._abort(context, grpc.StatusCode.UNAVAILABLE, str(e))
        response = pb.PredictResponse(model_step=step, model_stamp=stamp)
        for name, value in outputs.items():
            ndarray_to_blob(np.asarray(value), response.outputs[name])
        self._m_latency.observe(time.perf_counter() - start)
        self._m_requests.labels(code="OK").inc()
        return response

    def model_info(self, request, context):
        info = self._engine.model_info()
        return pb.ModelInfoResponse(
            loaded=info["loaded"],
            step=max(info["step"], 0),
            stamp=info["stamp"],
            model_zoo=info["model_zoo"],
            max_batch=info["max_batch"],
        )
