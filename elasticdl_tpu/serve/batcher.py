"""Admission-controlled micro-batcher.

Requests enter a BOUNDED queue (``EDL_SERVE_QUEUE_DEPTH``); beyond the
bound they are SHED immediately (``QueueFull`` -> RESOURCE_EXHAUSTED on
the wire) — queueing past the depth/deadline budget only converts
overload into latency nobody asked for. A single formation thread
drains the queue into batches by max-size-or-max-delay
(``EDL_SERVE_MAX_BATCH`` rows / ``EDL_SERVE_MAX_DELAY_MS``), drops any
request whose deadline expired while it queued (``DeadlineExpired`` ->
DEADLINE_EXCEEDED: a late answer is a wrong answer to a caller that
already gave up), concatenates the survivors along the batch dim, runs
them through the engine's active model in ONE forward, and splits the
outputs back per request.

The deque is bounded by construction (``maxlen``) on top of the
explicit under-lock depth check — the admission check is what sheds
with a clean error; the maxlen is the belt-and-braces the
``serve-unbounded-queue`` edlint rule pins for every queue in this
package.
"""

import collections
import threading
import time

import numpy as np

from elasticdl_tpu.common.env_utils import env_float, env_int
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import metrics, trace

logger = _logger_factory("elasticdl_tpu.serve.batcher")

MAX_BATCH_ENV = "EDL_SERVE_MAX_BATCH"
MAX_DELAY_MS_ENV = "EDL_SERVE_MAX_DELAY_MS"
QUEUE_DEPTH_ENV = "EDL_SERVE_QUEUE_DEPTH"
DEADLINE_MS_ENV = "EDL_SERVE_DEADLINE_MS"

_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _env_num(name, default, cast):
    if cast is int:
        return env_int(name, default)
    return env_float(name, default)


class QueueFull(Exception):
    """Admission queue at depth: the request was shed, not queued."""


class DeadlineExpired(Exception):
    """The request's latency budget passed while it queued: shed, not
    served late."""


class Draining(Exception):
    """The role is in its SIGTERM drain: no new admissions."""


def _leaf_schema(value):
    value = np.asarray(value)
    return (value.shape[1:], value.dtype.str)


def _schema(features):
    """Co-batch key: feature names AND per-feature trailing shape +
    dtype. Concatenation along the batch dim is only defined within
    such a group — without the shape/dtype part, one malformed request
    makes the whole batch's concatenate raise and poisons every
    co-batched request with its error."""
    if isinstance(features, dict):
        return tuple(
            (name,) + _leaf_schema(features[name])
            for name in sorted(features)
        )
    return _leaf_schema(features)


class _Request:
    __slots__ = (
        "features", "rows", "deadline", "enqueued", "done",
        "outputs", "error", "keys", "adopt_trace",
    )

    def __init__(self, features, rows, deadline):
        self.features = features
        self.rows = int(rows)
        self.deadline = deadline  # monotonic seconds, or None
        self.enqueued = time.monotonic()
        self.done = threading.Event()
        self.outputs = None
        self.error = None
        self.keys = _schema(features)
        # span-context snapshot from the admitting RPC thread: the
        # formation thread adopts the batch HEAD's so the forward (and
        # its PS pulls) lands in the head request's trace (ISSUE 9 —
        # batch-level work is attributed to the request that opened
        # the formation window)
        self.adopt_trace = trace.capture_context()

    def resolve(self, outputs):
        self.outputs = outputs
        self.done.set()

    def fail(self, error):
        self.error = error
        self.done.set()


class MicroBatcher:
    """``runner(features, rows) -> (outputs, step, stamp)`` executes one
    padded batch; everything else — admission, shedding, deadlines,
    formation, response splitting — lives here."""

    def __init__(self, runner, max_batch=None, max_delay_ms=None,
                 queue_depth=None, default_deadline_ms=None,
                 on_shed=None, registry=None):
        self._runner = runner
        self.max_batch = int(
            max_batch if max_batch is not None
            else _env_num(MAX_BATCH_ENV, 32, int)
        )
        self.max_delay_secs = (
            max_delay_ms if max_delay_ms is not None
            else _env_num(MAX_DELAY_MS_ENV, 5.0, float)
        ) / 1e3
        self.queue_depth = int(
            queue_depth if queue_depth is not None
            else _env_num(QUEUE_DEPTH_ENV, 256, int)
        )
        self.default_deadline_secs = (
            default_deadline_ms if default_deadline_ms is not None
            else _env_num(DEADLINE_MS_ENV, 1000.0, float)
        ) / 1e3
        if self.max_batch < 1 or self.queue_depth < 1:
            raise ValueError("max_batch and queue_depth must be >= 1")
        self._on_shed = on_shed
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = collections.deque(maxlen=self.queue_depth)
        self._draining = False
        self._stopped = False
        # counters move from RPC threads AND the formation thread, and
        # they feed hard assertions (bench gate, drain journal) — a
        # dedicated lock, NOT self._lock: _shed runs both under the
        # admission condition and lock-free from the formation thread
        self._count_lock = threading.Lock()
        self.shed_total = 0
        self.served_total = 0
        reg = registry or metrics.default_registry()
        self._m_queue_depth = reg.gauge(
            "edl_serve_queue_depth",
            "Instantaneous admission-queue depth of the micro-batcher",
        )
        self._m_shed = reg.counter(
            "edl_serve_requests_shed_total",
            "Requests shed (queue at depth, or deadline expired while "
            "queued), by reason",
            ("reason",),
        )
        self._m_batch_size = reg.histogram(
            "edl_serve_batch_size",
            "Rows per formed inference batch",
            buckets=_BATCH_BUCKETS,
        )
        # pre-register so /metrics shows the series at zero
        self._m_shed.labels(reason="queue_full")
        self._m_shed.labels(reason="deadline")
        self._thread = threading.Thread(
            target=self._loop, name="edl-serve-batcher", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------
    def _shed(self, reason):
        with self._count_lock:
            self.shed_total += 1
            total = self.shed_total
        self._m_shed.labels(reason=reason).inc()
        if self._on_shed is not None:
            try:
                self._on_shed(reason, total)
            except Exception:
                logger.exception("on_shed callback failed")

    def submit(self, features, rows, deadline_secs=None):
        """Blocks until served; returns ``(outputs, step, stamp)``.
        Raises QueueFull / DeadlineExpired / Draining (each maps to one
        gRPC status in the servicer)."""
        if deadline_secs is None:
            deadline_secs = self.default_deadline_secs
        deadline = (
            time.monotonic() + deadline_secs if deadline_secs > 0 else None
        )
        request = _Request(features, rows, deadline)
        with self._cond:
            if self._draining:
                raise Draining("serve role is draining; not admitting")
            if len(self._pending) >= self.queue_depth:
                self._shed("queue_full")
                raise QueueFull(
                    "admission queue at depth %d" % self.queue_depth
                )
            self._pending.append(request)
            self._m_queue_depth.set(len(self._pending))
            self._cond.notify()
        # the formation thread resolves every admitted request (serve,
        # shed, or error); the pad is pure defense against a wedged
        # runner — surface it as an error rather than hanging the RPC.
        # A request with no budget at all still gets a bounded wait
        # for the same reason (an RPC thread must not leak forever).
        wait = (
            deadline - time.monotonic() + 30.0
            if deadline is not None
            else 600.0
        )
        if not request.done.wait(timeout=wait):
            # wedged runner: pull the request back out of the queue if
            # it hasn't been popped into a forming batch, so an
            # unwedged runner doesn't later burn a forward on a caller
            # that's gone; either way the client sees a shed
            with self._cond:
                try:
                    self._pending.remove(request)
                except ValueError:
                    pass  # already popped into a forming batch
                else:
                    self._m_queue_depth.set(len(self._pending))
            self._shed("deadline")
            raise DeadlineExpired("request timed out awaiting the batcher")
        if request.error is not None:
            raise request.error
        with self._count_lock:
            self.served_total += 1
        return request.outputs

    # ------------------------------------------------------------------
    def _take_batch(self):
        """Under the condition: wait for work, then pop one batch —
        same-schema requests up to max_batch rows, closing when the
        head has waited max_delay. Returns [] only at stop."""
        with self._cond:
            while not self._pending and not self._stopped:
                self._cond.wait(timeout=0.1)
            if self._stopped and not self._pending:
                return []
            head = self._pending[0]
            close_at = head.enqueued + self.max_delay_secs
            # wait out the formation window while under-filled; only
            # the head's contiguous same-schema run counts — rows past
            # a schema boundary can't join this batch, so counting
            # them would close the window early and under-filled. The
            # scan stops at max_batch rows: under a deep backlog an
            # unbounded per-wake scan starves the runner thread.
            while not self._stopped:
                rows = 0
                for request in self._pending:
                    if request.keys != head.keys:
                        break
                    rows += request.rows
                    if rows >= self.max_batch:
                        break
                remaining = close_at - time.monotonic()
                if rows >= self.max_batch or remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            batch = []
            rows = 0
            while self._pending:
                nxt = self._pending[0]
                if nxt.keys != head.keys:
                    break  # schema boundary: next batch takes it
                if rows + nxt.rows > self.max_batch and batch:
                    break
                batch.append(self._pending.popleft())
                rows += nxt.rows
            self._m_queue_depth.set(len(self._pending))
            return batch

    def _run(self, batch):
        """Shed the expired, concatenate the live, run, split."""
        now = time.monotonic()
        live = []
        for request in batch:
            if request.deadline is not None and now > request.deadline:
                self._shed("deadline")
                request.fail(DeadlineExpired(
                    "deadline expired after %.1f ms in queue"
                    % ((now - request.enqueued) * 1e3)
                ))
            else:
                live.append(request)
        if not live:
            return
        try:
            if len(live) == 1:
                features = live[0].features
            elif not isinstance(live[0].features, dict):
                features = np.concatenate(
                    [np.asarray(r.features) for r in live], axis=0
                )
            else:
                features = {
                    key: np.concatenate(
                        [np.asarray(r.features[key]) for r in live], axis=0
                    )
                    for key in live[0].features
                }
            total = sum(r.rows for r in live)
            with live[0].adopt_trace():
                self._m_batch_size.observe(total)
                with trace.span(
                    "serve_batch_run", requests=len(live), rows=total
                ):
                    outputs, step, stamp = self._runner(features, total)
            offset = 0
            for request in live:
                request.resolve((
                    {
                        k: v[offset:offset + request.rows]
                        for k, v in outputs.items()
                    },
                    step,
                    stamp,
                ))
                offset += request.rows
        except BaseException as e:  # noqa: BLE001 - every request must resolve
            logger.exception("inference batch failed")
            for request in live:
                if not request.done.is_set():
                    request.fail(e)

    def _loop(self):
        while True:
            batch = self._take_batch()
            if not batch:
                if self._stopped:
                    return
                continue
            self._run(batch)

    # ------------------------------------------------------------------
    def pending_count(self):
        """Instantaneous admission-queue depth (``queue_depth`` is the
        configured BOUND — an attribute, so don't name a method after
        it)."""
        return len(self._pending)

    def drain(self, timeout=30.0):
        """SIGTERM path: stop admitting (submit raises Draining), serve
        everything already queued, stop the formation thread. Returns
        the number of requests flushed."""
        with self._cond:
            self._draining = True
            flushed = len(self._pending)
            self._cond.notify_all()
        deadline = time.monotonic() + timeout
        while self._pending and time.monotonic() < deadline:
            time.sleep(0.01)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=max(0.0, deadline - time.monotonic()))
        return flushed

    def stop(self):
        """Test/teardown convenience: drain with a short flush window."""
        return self.drain(timeout=5.0)
