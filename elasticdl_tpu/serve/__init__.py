"""Online serving tier (ISSUE 8): the fourth role.

``elasticdl train`` produces an export (``train/export.py``); this
package serves it — the ``elasticdl predict`` job type of the reference
(PAPER.md L8) grown into a low-latency online tier:

- ``model.py``    — load an export, re-apply the model-zoo module,
  resolve sparse features through the extracted embedding client
  (``elasticdl_tpu/embedding``) against the live PS, one jitted
  forward.
- ``batcher.py``  — admission-controlled micro-batching: bounded queue
  with load shedding, max-size-or-max-delay batch formation,
  per-request deadlines honored (a late request is shed, never served
  late).
- ``engine.py``   — model-version lifecycle: export watcher, background
  warm-up, atomic hot swap (in-flight requests finish on the version
  that admitted them).
- ``servicer.py`` / ``client.py`` — the gRPC Predict surface.
- ``main.py``     — the role entry point (probes, flight recorder,
  SIGTERM graceful drain, optional fleet-telemetry piggyback).

The fleet layer (ISSUE 17) fronts N such replicas with a fifth role:

- ``router.py``      — same ``Serve`` gRPC surface, consistent-hash
  affinity routing with bounded-retry failover, per-replica in-flight
  caps (shed, don't spill).
- ``fleet.py``       — replica registry (register/heartbeat/expire),
  replica autoscaler reusing the master's ``DecisionGate``, subprocess
  replica placement for bench/CI.
- ``canary.py``      — telemetry-judged canary rollout: fraction slice
  on new exports, TV-distance + failure-rate judge, auto
  promote/rollback, every decision journaled.
- ``router_main.py`` — the router role entry point.

See docs/SERVING.md for topology and knobs ("Fleet topology" for the
router tier).
"""
