"""Streaming evaluation metrics.

The reference aggregates evaluation with `tf.keras.metrics` objects
master-side (common/evaluation_utils.py:21-110). This framework has no TF
dependency on the control plane, so metrics are small numpy accumulators
with the same update_state/result/reset_states contract. Model-zoo modules
return these from ``eval_metrics_fn`` (reference model contract:
common/model_utils.py:139-198).
"""

import numpy as np


class Metric:
    name = "metric"

    def update_state(self, labels, outputs):
        raise NotImplementedError

    def result(self):
        raise NotImplementedError

    def reset_states(self):
        raise NotImplementedError


class Mean(Metric):
    """Mean of a scalar stream (e.g. loss)."""

    def __init__(self, name="mean"):
        self.name = name
        self.reset_states()

    def reset_states(self):
        self._total = 0.0
        self._count = 0

    def update_state(self, labels, outputs):
        values = np.asarray(outputs, dtype=np.float64)
        self._total += float(values.sum())
        self._count += values.size

    def result(self):
        return self._total / max(self._count, 1)


class Accuracy(Metric):
    """Sparse categorical accuracy: argmax(outputs) == labels."""

    def __init__(self, name="accuracy"):
        self.name = name
        self.reset_states()

    def reset_states(self):
        self._correct = 0
        self._count = 0

    def update_state(self, labels, outputs):
        labels = np.asarray(labels).reshape(-1)
        outputs = np.asarray(outputs)
        if outputs.ndim > 1 and outputs.shape[-1] > 1:
            preds = np.argmax(outputs, axis=-1).reshape(-1)
        else:
            preds = np.round(outputs).astype(labels.dtype).reshape(-1)
        self._correct += int((preds == labels).sum())
        self._count += labels.size

    def result(self):
        return self._correct / max(self._count, 1)


class BinaryAccuracy(Metric):
    def __init__(self, threshold=0.5, from_logits=False, name="binary_accuracy"):
        self.name = name
        self._threshold = threshold
        self._from_logits = from_logits
        self.reset_states()

    def reset_states(self):
        self._correct = 0
        self._count = 0

    def update_state(self, labels, outputs):
        labels = np.asarray(labels).reshape(-1)
        outputs = np.asarray(outputs, dtype=np.float64).reshape(-1)
        if self._from_logits:
            outputs = 1.0 / (1.0 + np.exp(-outputs))
        preds = (outputs >= self._threshold).astype(labels.dtype)
        self._correct += int((preds == labels).sum())
        self._count += labels.size

    def result(self):
        return self._correct / max(self._count, 1)


class AUC(Metric):
    """Exact ROC AUC via the rank statistic over buffered scores.

    Buffers scores/labels (evaluation sets in this framework's scope are
    master-side and modest); computes the Mann-Whitney U form, which is
    exact rather than the binned approximation Keras uses.
    """

    def __init__(self, from_logits=False, name="auc"):
        self.name = name
        self._from_logits = from_logits
        self.reset_states()

    def reset_states(self):
        self._scores = []
        self._labels = []

    def update_state(self, labels, outputs):
        outputs = np.asarray(outputs, dtype=np.float64).reshape(-1)
        if self._from_logits:
            outputs = 1.0 / (1.0 + np.exp(-outputs))
        self._scores.append(outputs)
        self._labels.append(np.asarray(labels).reshape(-1).astype(np.int64))

    def result(self):
        if not self._scores:
            return 0.0
        scores = np.concatenate(self._scores)
        labels = np.concatenate(self._labels)
        pos = int(labels.sum())
        neg = labels.size - pos
        if pos == 0 or neg == 0:
            return 0.0
        order = np.argsort(scores, kind="mergesort")
        ranks = np.empty(scores.size, dtype=np.float64)
        sorted_scores = scores[order]
        # average ranks over ties
        ranks_sorted = np.arange(1, scores.size + 1, dtype=np.float64)
        lo = 0
        while lo < scores.size:
            hi = lo
            while hi + 1 < scores.size and sorted_scores[hi + 1] == sorted_scores[lo]:
                hi += 1
            ranks_sorted[lo : hi + 1] = 0.5 * (lo + 1 + hi + 1)
            lo = hi + 1
        ranks[order] = ranks_sorted
        rank_sum_pos = float(ranks[labels == 1].sum())
        u = rank_sum_pos - pos * (pos + 1) / 2.0
        return u / (pos * neg)


class MeanSquaredError(Metric):
    def __init__(self, name="mse"):
        self.name = name
        self.reset_states()

    def reset_states(self):
        self._total = 0.0
        self._count = 0

    def update_state(self, labels, outputs):
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        outputs = np.asarray(outputs, dtype=np.float64).reshape(-1)
        self._total += float(((labels - outputs) ** 2).sum())
        self._count += labels.size

    def result(self):
        return self._total / max(self._count, 1)


class MeanAbsoluteError(Metric):
    def __init__(self, name="mae"):
        self.name = name
        self.reset_states()

    def reset_states(self):
        self._total = 0.0
        self._count = 0

    def update_state(self, labels, outputs):
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        outputs = np.asarray(outputs, dtype=np.float64).reshape(-1)
        self._total += float(np.abs(labels - outputs).sum())
        self._count += labels.size

    def result(self):
        return self._total / max(self._count, 1)


class EvaluationMetrics:
    """Books metrics for single- or multi-output models.

    Reference parity: common/evaluation_utils.py:21-110. ``metrics_dict``
    is either {metric_name: Metric} (single output) or
    {output_name: {metric_name: Metric}}.
    """

    def __init__(self, metrics_dict):
        self._nested = any(
            isinstance(v, dict) for v in metrics_dict.values()
        )
        self._metrics = metrics_dict

    def update_evaluation_metrics(self, model_outputs, labels):
        """model_outputs: {output_name: ndarray}; labels: ndarray."""
        if self._nested:
            for output_name, metrics in self._metrics.items():
                if output_name not in model_outputs:
                    continue
                outputs = model_outputs[output_name]
                for metric in metrics.values():
                    metric.update_state(labels, outputs)
        else:
            # single output: use the first (and only) reported tensor
            outputs = next(iter(model_outputs.values()))
            for metric in self._metrics.values():
                metric.update_state(labels, outputs)

    def get_evaluation_summary(self):
        if self._nested:
            return {
                output_name: {
                    name: metric.result() for name, metric in metrics.items()
                }
                for output_name, metrics in self._metrics.items()
            }
        return {name: metric.result() for name, metric in self._metrics.items()}

    def reset(self):
        stack = [self._metrics]
        while stack:
            current = stack.pop()
            for value in current.values():
                if isinstance(value, dict):
                    stack.append(value)
                else:
                    value.reset_states()
