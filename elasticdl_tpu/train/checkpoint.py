"""Dense train-state checkpoints: full-state, re-shardable, versioned.

The reference checkpoints only PS-held parameters and silently drops
optimizer slot state (ps/parameters.py:194-199, save_utils.py:124-141);
resume re-shards dense params by name-hash across the new PS count
(save_utils.py:229-282). The TPU-native design checkpoints the ENTIRE
TrainState pytree (params + model_state + optimizer state + step) via
orbax, and re-sharding on resume is free: orbax restores into whatever
NamedShardings the new mesh prescribes, so a job can come back on a
different topology (the elastic-slice equivalent of the reference's
"any old N -> new N" PS re-sharding).

Layout mirrors the reference's versioned dirs: ``<dir>/<version>/`` with
keep-max GC, plus ``latest_version()`` that only reports *complete*
checkpoints (orbax commit semantics give us that for free).
"""

import os

import jax
import numpy as np
import orbax.checkpoint as ocp

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.train.train_state import TrainState

logger = _logger_factory("elasticdl_tpu.train.checkpoint")


class DenseCheckpointManager:
    """Versioned full-TrainState snapshots with keep-max GC.

    ``async_save=True`` (opt-in) runs the serialization/write on
    orbax's background machinery so the training loop resumes after
    the device arrays are snapshotted instead of after the files are
    durable — the next save (or ``close``) joins the previous write
    first, and ``latest_version`` only ever reports COMMITTED steps,
    so a crash mid-write still resumes from the last complete
    checkpoint. Default stays synchronous: simpler failure semantics,
    and the lockstep multi-host path has only measured that mode."""

    def __init__(self, checkpoint_dir, keep_max=3, create=True,
                 async_save=False):
        # create=False for read-only resume: materializing an empty dir
        # at a typo'd path would mask the operator's mistake.
        self._dir = os.path.abspath(checkpoint_dir)
        self._async = bool(async_save)
        if not create and not os.path.isdir(self._dir):
            raise FileNotFoundError(
                "checkpoint dir %s does not exist" % self._dir
            )
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_max if keep_max > 0 else None,
                create=create,
                enable_async_checkpointing=self._async,
            ),
        )

    # ------------------------------------------------------------------
    def save(self, version, state: TrainState):
        self._mgr.save(
            int(version), args=ocp.args.StandardSave(state)
        )
        if not self._async:
            self._mgr.wait_until_finished()
        logger.info(
            "Saved dense checkpoint version %d under %s%s",
            int(version),
            self._dir,
            " (async)" if self._async else "",
        )

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def latest_version(self):
        return self._mgr.latest_step()

    def restore(self, version=None, template: TrainState = None,
                shardings=None):
        """Restore a TrainState.

        - ``template``: a TrainState with the target structure (shapes/
          dtypes); typically the freshly-initialized state. When
          ``shardings`` (a matching pytree of NamedSharding, e.g. from
          infer_state_shardings over the *current* mesh) is given, every
          leaf is restored directly into that layout — resume onto a
          different mesh re-shards implicitly.
        """
        version = version if version is not None else self.latest_version()
        if version is None:
            return None
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype
            ),
            template,
        )
        if shardings is None:
            # Pin every leaf to this process's default device rather than
            # letting orbax read the sharding file written at save time:
            # a checkpoint saved on an N-device mesh must restore on a
            # single-chip worker (cross-topology resume).
            shardings = jax.tree_util.tree_map(
                lambda _: jax.sharding.SingleDeviceSharding(
                    jax.devices()[0]
                ),
                abstract,
            )
        abstract = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract,
            shardings,
        )
        state = self._mgr.restore(
            int(version), args=ocp.args.StandardRestore(abstract)
        )
        logger.info(
            "Restored dense checkpoint version %d from %s",
            int(version),
            self._dir,
        )
        return state

    def close(self):
        self._mgr.close()
