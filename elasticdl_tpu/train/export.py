"""Model export: persist trained state for serving/resume.

Reference parity: the SavedModel export driven by the TRAIN_END_CALLBACK
task (elasticdl/python/elasticdl/callbacks.py:25-67,
common/model_handler.py:242-284). The TPU-native export format is an
orbax/npz parameter bundle rather than a TF SavedModel graph: serving a
JAX model means re-applying the module to restored params.
"""

import json
import os

import jax
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.train.export")


def _flatten(tree, prefix=""):
    flat = {}
    if isinstance(tree, dict):
        for key, value in tree.items():
            flat.update(_flatten(value, prefix + key + "/"))
    else:
        flat[prefix.rstrip("/")] = np.asarray(tree)
    return flat


def _unflatten(flat):
    tree = {}
    for path, value in flat.items():
        parts = path.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return tree


def export_train_state(state, path):
    """Write params (+ mutable model state) as an .npz bundle + manifest."""
    os.makedirs(path, exist_ok=True)
    params = jax.device_get(state.params)
    model_state = jax.device_get(state.model_state)
    flat = _flatten({"params": params, "model_state": model_state})
    np.savez(os.path.join(path, "model.npz"), **flat)
    manifest = {
        "format": "elasticdl_tpu.export.v1",
        "step": int(np.asarray(jax.device_get(state.step))),
        "num_arrays": len(flat),
    }
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    logger.info("Exported model (%d arrays) to %s", len(flat), path)
    return path


def load_exported(path):
    """Returns (params, model_state, step)."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    data = np.load(os.path.join(path, "model.npz"))
    tree = _unflatten({name: data[name] for name in data.files})
    return (
        tree.get("params", {}),
        tree.get("model_state", {}),
        manifest["step"],
    )
