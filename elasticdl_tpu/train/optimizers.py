"""Optimizer factory over optax.

The reference carries optimizer identity as (opt_type, opt_args) strings
so the Go PS can reconstruct kernels (common/model_utils.py:234-261,
go/pkg/ps/optimizer.go:297-390). Here the dense path is on-device optax,
but the same string spec survives as the cross-process interchange format
(CLI flags, sparse-PS optimizer config, checkpoints).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

SUPPORTED = (
    "SGD", "Momentum", "Adam", "Adagrad", "AdamW", "RMSprop",
    "Adamax", "Nadam", "Adadelta", "Ftrl",
)


class FtrlState(NamedTuple):
    accum: optax.Updates  # n: sum of squared gradients
    linear: optax.Updates  # z: the proximal linear term
    count: jnp.ndarray  # step counter for schedule resolution


def ftrl(learning_rate, learning_rate_power=-0.5,
         initial_accumulator_value=0.1, l1_regularization_strength=0.0,
         l2_regularization_strength=0.0):
    """FTRL-proximal (McMahan et al. 2013), the CTR workhorse the
    reference supports via Keras (optimizer_wrapper.py:116-149 lists its
    slots 'accumulator'/'linear'). optax ships no FTRL, so this is a
    from-scratch GradientTransformation with the same update rule as
    tf.keras.optimizers.Ftrl. Note the sign convention: this transform
    returns delta = w_new - w_old directly (it reconstructs the weight
    from the proximal closed form), so it composes with apply_updates
    like any other optax optimizer."""
    lr_power = learning_rate_power
    l1 = l1_regularization_strength
    l2 = l2_regularization_strength

    def init_fn(params):
        return FtrlState(
            accum=jax.tree_util.tree_map(
                lambda p: jnp.full_like(p, initial_accumulator_value),
                params,
            ),
            linear=jax.tree_util.tree_map(jnp.zeros_like, params),
            count=jnp.zeros((), jnp.int32),
        )

    def update_fn(grads, state, params):
        if params is None:
            raise ValueError("ftrl requires params")
        # learning_rate may be an optax schedule (step -> lr), like the
        # optax-built optimizer branches
        lr = (
            learning_rate(state.count)
            if callable(learning_rate)
            else learning_rate
        )

        def per_leaf(g, n, z, w):
            g = g.astype(w.dtype)
            new_n = n + g * g
            sigma = (new_n ** -lr_power - n ** -lr_power) / lr
            new_z = z + g - sigma * w
            quadratic = new_n ** -lr_power / lr + 2.0 * l2
            trigger = jnp.abs(new_z) > l1
            new_w = jnp.where(
                trigger,
                (jnp.sign(new_z) * l1 - new_z) / quadratic,
                jnp.zeros_like(w),
            )
            return new_w - w, new_n, new_z

        flat = jax.tree_util.tree_map(
            per_leaf, grads, state.accum, state.linear, params
        )
        # tree_transpose splits the per-leaf (delta, n, z) triples into
        # three trees shaped like grads — structure-driven, so a params
        # tree that itself contains 3-tuples cannot be misparsed
        updates, new_accum, new_linear = jax.tree_util.tree_transpose(
            jax.tree_util.tree_structure(grads),
            jax.tree_util.tree_structure((0, 0, 0)),
            flat,
        )
        return updates, FtrlState(
            accum=new_accum,
            linear=new_linear,
            count=state.count + 1,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def create_optimizer(opt_type: str, **opt_args) -> optax.GradientTransformation:
    opt_type_lower = opt_type.lower()
    # learning_rate may be a float, an optax schedule (callable of step —
    # compiles into the step, the idiomatic TPU form of the reference's
    # LearningRateScheduler), or a traced scalar (inject_hyperparams).
    lr = opt_args.pop("learning_rate", 0.01)
    if isinstance(lr, (str, int)):
        lr = float(lr)
    if opt_type_lower == "sgd":
        momentum = float(opt_args.pop("momentum", 0.0))
        nesterov = _parse_bool(opt_args.pop("nesterov", False))
        _reject_extra(opt_type, opt_args)
        return optax.sgd(lr, momentum=momentum or None, nesterov=nesterov)
    if opt_type_lower == "momentum":
        momentum = float(opt_args.pop("momentum", 0.9))
        nesterov = _parse_bool(opt_args.pop("nesterov", False))
        _reject_extra(opt_type, opt_args)
        return optax.sgd(lr, momentum=momentum, nesterov=nesterov)
    if opt_type_lower == "adam":
        b1 = float(opt_args.pop("beta_1", 0.9))
        b2 = float(opt_args.pop("beta_2", 0.999))
        eps = float(opt_args.pop("epsilon", 1e-8))
        _reject_extra(opt_type, opt_args)
        return optax.adam(lr, b1=b1, b2=b2, eps=eps)
    if opt_type_lower == "adamw":
        b1 = float(opt_args.pop("beta_1", 0.9))
        b2 = float(opt_args.pop("beta_2", 0.999))
        eps = float(opt_args.pop("epsilon", 1e-8))
        wd = float(opt_args.pop("weight_decay", 1e-4))
        _reject_extra(opt_type, opt_args)
        return optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if opt_type_lower == "adagrad":
        eps = float(opt_args.pop("epsilon", 1e-7))
        init_acc = float(opt_args.pop("initial_accumulator_value", 0.1))
        _reject_extra(opt_type, opt_args)
        return optax.adagrad(
            lr, initial_accumulator_value=init_acc, eps=eps
        )
    if opt_type_lower == "rmsprop":
        decay = float(opt_args.pop("rho", 0.9))
        eps = float(opt_args.pop("epsilon", 1e-7))
        momentum = float(opt_args.pop("momentum", 0.0))
        _reject_extra(opt_type, opt_args)
        return optax.rmsprop(lr, decay=decay, eps=eps, momentum=momentum)
    if opt_type_lower == "adamax":
        b1 = float(opt_args.pop("beta_1", 0.9))
        b2 = float(opt_args.pop("beta_2", 0.999))
        eps = float(opt_args.pop("epsilon", 1e-8))
        _reject_extra(opt_type, opt_args)
        return optax.adamax(lr, b1=b1, b2=b2, eps=eps)
    if opt_type_lower == "nadam":
        b1 = float(opt_args.pop("beta_1", 0.9))
        b2 = float(opt_args.pop("beta_2", 0.999))
        eps = float(opt_args.pop("epsilon", 1e-8))
        _reject_extra(opt_type, opt_args)
        return optax.nadam(lr, b1=b1, b2=b2, eps=eps)
    if opt_type_lower == "adadelta":
        rho = float(opt_args.pop("rho", 0.95))
        eps = float(opt_args.pop("epsilon", 1e-7))
        _reject_extra(opt_type, opt_args)
        return optax.adadelta(lr, rho=rho, eps=eps)
    if opt_type_lower == "ftrl":
        kwargs = {
            "learning_rate_power": float(
                opt_args.pop("learning_rate_power", -0.5)
            ),
            "initial_accumulator_value": float(
                opt_args.pop("initial_accumulator_value", 0.1)
            ),
            "l1_regularization_strength": float(
                opt_args.pop("l1_regularization_strength", 0.0)
            ),
            "l2_regularization_strength": float(
                opt_args.pop("l2_regularization_strength", 0.0)
            ),
        }
        _reject_extra(opt_type, opt_args)
        return ftrl(lr, **kwargs)
    raise ValueError(
        "Unsupported optimizer %r (supported: %s)" % (opt_type, SUPPORTED)
    )


def create_host_schedulable_optimizer(
    opt_type: str, **opt_args
) -> optax.GradientTransformation:
    """Like create_optimizer, but the learning rate lives in
    ``opt_state.hyperparams`` (optax.inject_hyperparams) so the
    LearningRateScheduler callback can rewrite it between steps with NO
    recompile — the TPU equivalent of the reference mutating
    ``optimizer.learning_rate`` per batch (elasticdl/callbacks.py:114-155,
    ps/learning_rate_modulator.py)."""
    lr = opt_args.pop("learning_rate", 0.01)

    def factory(learning_rate):
        return create_optimizer(
            opt_type, learning_rate=learning_rate, **opt_args
        )

    return optax.inject_hyperparams(factory)(learning_rate=lr)


def set_learning_rate(opt_state, learning_rate):
    """Rewrite the learning_rate hyperparameter inside an opt_state built
    by create_host_schedulable_optimizer. Returns the new opt_state, or
    None if this opt_state has no injected hyperparams."""
    inject_types = (
        optax.InjectHyperparamsState,
        optax.InjectStatefulHyperparamsState,
    )

    def rewrite(s):
        # the inject states are themselves NamedTuples, so test for them
        # BEFORE treating tuples as containers
        if isinstance(s, inject_types) and "learning_rate" in s.hyperparams:
            import jax.numpy as jnp

            hp = dict(s.hyperparams)
            hp["learning_rate"] = jnp.asarray(
                learning_rate, jnp.asarray(hp["learning_rate"]).dtype
            )
            return s._replace(hyperparams=hp), True
        if type(s) is tuple:
            parts = [rewrite(p) for p in s]
            return tuple(p for p, _ in parts), any(f for _, f in parts)
        return s, False

    new_state, found = rewrite(opt_state)
    return new_state if found else None


def parse_opt_args(opt_args_str: str) -> dict:
    """Parse 'k=v;k=v' optimizer arg strings (the reference's Go-PS flag
    format, go/pkg/ps/optimizer.go parseOptArgs)."""
    args = {}
    for part in (opt_args_str or "").split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError("Bad opt_args segment %r" % part)
        key, value = part.split("=", 1)
        args[key.strip()] = value.strip()
    return args


def _parse_bool(value):
    if isinstance(value, bool):
        return value
    return str(value).lower() in ("1", "true", "yes")


def _reject_extra(opt_type, extra):
    if extra:
        raise ValueError(
            "Unknown args for optimizer %s: %s" % (opt_type, sorted(extra))
        )
